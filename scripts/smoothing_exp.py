#!/usr/bin/env python
"""Partition-stability experiment (VERDICT r2 weak #4 / next-round #7).

Round 2's c5 artifact showed the transformer's node_time vector swinging
7.4<->30.8s epoch-to-epoch on the CPU mesh and the partition oscillating with
it. Two candidate stabilizers exist; this experiment measures both on the
c5-style config so the default is evidence-based, not vibes:

  A. probe_mode=always, time_smoothing=0    (round-2 behavior, the baseline)
  B. probe_mode=adaptive, time_smoothing=0  (round-3 default: epochs 2+ feed
     the solver noise-free MODELED times)
  C. probe_mode=always, time_smoothing=0.5  (EMA damping on measured times)

Metric per arm: partition churn = mean over epochs>=3 of max_r |share_r(e) -
share_r(e-1)| (0 = frozen), plus the share trajectory of the straggled
worker. Writes artifacts/SMOOTHING.json; runs on the CPU mesh by default
(the noise source under study IS host contention).

Usage: python scripts/smoothing_exp.py [--epochs 8] [--ntrain 60000]
"""

import argparse
import json
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def churn(partitions: list) -> dict:
    p = np.asarray(partitions, dtype=np.float64)
    if len(p) < 4:
        return {"mean_step": None, "max_step": None}
    steps = np.abs(np.diff(p, axis=0)).max(axis=1)[2:]  # epochs >= 3
    return {
        "mean_step": float(steps.mean()),
        "max_step": float(steps.max()),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--ntrain", type=int, default=60_000)
    ap.add_argument("--straggler", default="3,1,1,1")
    ap.add_argument("--arms", default="", help="comma list of arm names to (re)run")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")  # beats the axon TPU plugin

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.train.lm_engine import LMTrainer

    arms = {
        "A_always_raw": dict(probe_mode="always", time_smoothing=0.0),
        "B_adaptive_raw": dict(probe_mode="adaptive", time_smoothing=0.0),
        "C_always_ema05": dict(probe_mode="always", time_smoothing=0.5),
    }
    out = {"config": vars(args), "arms": {}}
    if os.path.exists("artifacts/SMOOTHING.json"):
        try:
            with open("artifacts/SMOOTHING.json") as f:
                out["arms"] = json.load(f).get("arms", {})
        except Exception:
            pass
    if args.arms:
        selected = {a.strip() for a in args.arms.split(",") if a.strip()}
        unknown = selected - set(arms)
        if unknown:
            raise SystemExit(f"unknown arms {sorted(unknown)}; choose from {sorted(arms)}")
    else:
        selected = None
    for name, kw in arms.items():
        if selected is not None and name not in selected:
            continue
        cfg = Config(
            debug=False,
            world_size=4,
            batch_size=80,
            learning_rate=0.01,
            epoch_size=args.epochs,
            dataset="wikitext2",
            model="transformer",
            dynamic_batch_size=True,
            bucket=4,
            bptt=35,
            grad_clip=0.25,
            n_train=args.ntrain,
            straggler=args.straggler,
            fault_mode="compute",
            **kw,
        )
        tr = LMTrainer(cfg, log_to_file=False)
        parts, times = [], []
        for e in range(args.epochs):
            tr.run_epoch(e)
            parts.append(tr.shares.tolist())
            times.append([round(t, 4) for t in tr.node_times.tolist()])
        out["arms"][name] = {
            # per-arm config snapshot: merged re-runs of single arms must not
            # let stale arms masquerade as results for the current argv
            "config": vars(args),
            "partitions": [[round(x, 4) for x in p] for p in parts],
            "node_times": times,
            "churn": churn(parts),
            "straggler_share_final": round(parts[-1][0], 4),
        }
        os.makedirs("artifacts", exist_ok=True)
        with open("artifacts/SMOOTHING.json", "w") as f:
            json.dump(out, f, indent=1)
        print(name, out["arms"][name]["churn"], "w0 share", parts[-1][0], flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
