#!/usr/bin/env python
"""Micro step-time leg: commit an on-chip number from ANY tunnel window.

VERDICT r4 next-round #1/#4: the round-3 tunnel window was ~2 minutes and
produced nothing committed because every queued leg assumed minutes of
runtime. This leg is sized so even a sub-2-minute window lands evidence:

  - time ~MICRO_REPS (20) DenseNet-121 B=512 bf16 fwd+bwd+SGD steps,
    blocking-min and pipelined, for BOTH dense-block variants:
      * use_buffer=True  (round-4 right-to-left static-slice restructure)
      * use_buffer=False (literal per-layer concat, the reference shape)
    — this is the on-hardware verdict on the −36% byte claim
      (artifacts/ROOFLINE.md) that round 4 left as a cost-model number.
  - per-variant XLA cost-model FLOPs + bytes accessed → MFU vs chip peak.
  - writes artifacts/STEPTIME_tpu.json INCREMENTALLY (variant 1 is on disk
    and committable before variant 2 starts compiling).

Time budget on chip: 2 compiles (cold ~30-60s each, cached thereafter in
./.jax_cache) + 2×~25 steps at ~40-80 ms ≈ a few seconds of stepping.
A warm-cache rerun is well under 90 s end to end.

Plumbing (CPU) mode: MICRO_CPU=1 shrinks to a tiny DenseNet so the leg's
own machinery (timing, cost model, JSON schema, incremental saves) is
provable without the chip; writes artifacts/STEPTIME_cpu_plumbing.json.

Reference parity note: the reference's half of this measurement is cuDNN
step time on its CUDA devices (dbs.py:363, README.md:23-28); this leg is
the TPU twin on the canonical model/batch.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")

FORCE_CPU = os.environ.get("MICRO_CPU", "") == "1"
MICRO_MODEL = os.environ.get("MICRO_MODEL", "densenet")
if MICRO_MODEL not in ("densenet", "regnet"):
    # a typo'd leg must not silently bench the wrong model and commit its
    # numbers under an existing artifact name
    sys.stderr.write(f"[micro_leg] unknown MICRO_MODEL={MICRO_MODEL!r}\n")
    sys.exit(2)
_STEM = "REGNET_COMPILE" if MICRO_MODEL == "regnet" else "STEPTIME"
OUT = os.environ.get(
    "MICRO_OUT",
    os.path.join(
        "artifacts", f"{_STEM}_cpu_plumbing.json" if FORCE_CPU else f"{_STEM}_tpu.json"
    ),
)
RESULT: dict = {"variants": {}}


def _save() -> None:
    os.makedirs(os.path.dirname(OUT) or ".", exist_ok=True)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(RESULT, f, indent=1)
    os.replace(tmp, OUT)


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _install_watchdog(cap_s: float, label: str):
    import threading

    def _fire():
        sys.stderr.write(f"[micro_leg] {label} watchdog fired after {cap_s}s\n")
        os._exit(17)

    t = threading.Timer(cap_s, _fire)
    t.daemon = True
    t.start()
    return t


def main() -> int:
    wd = _install_watchdog(float(os.environ.get("MICRO_INIT_CAP_S", 300)), "init")
    import jax

    if FORCE_CPU:
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    wd.cancel()
    if not FORCE_CPU and devs[0].platform != "tpu":
        # silent CPU fallback must NOT stamp the leg done / commit CPU
        # numbers under the _tpu artifact name — exit nonzero so the queue
        # retries on the next up-window
        sys.stderr.write(f"[micro_leg] expected tpu, got {devs[0].platform}; refusing\n")
        return 3
    # everything past backend init is bounded compute; one overall cap so a
    # tunnel drop mid-compile can't hang the queue slot
    _install_watchdog(float(os.environ.get("MICRO_TOTAL_CAP_S", 600)), "total")
    import jax.numpy as jnp
    import numpy as np
    import optax

    dev = devs[0]
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True, text=True, timeout=10
        ).stdout.strip()
    except Exception:
        rev = "?"
    RESULT.update(
        {
            "platform": dev.platform,
            "device_kind": getattr(dev, "device_kind", "?"),
            "git_rev": rev,
            "measured_at_unix": time.time(),
            "measured_at_iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
    )
    _save()

    from dynamic_load_balance_distributeddnn_tpu.models.densenet import DenseNet, DenseNet121
    from dynamic_load_balance_distributeddnn_tpu.obs.flops import chip_peak_flops

    if MICRO_MODEL == "regnet":
        # VERDICT r4 next #3(c): prove the FUSED grouped conv (the thing
        # XLA:CPU cannot compile) compiles in seconds on the chip. One
        # variant, decompose forced off on chip; CPU plumbing keeps the
        # decomposition (the fused grouped conv is exactly the XLA:CPU
        # pathology) and the variant name says which one actually ran.
        from dynamic_load_balance_distributeddnn_tpu.models import build_model

        B = int(os.environ.get("MICRO_B", 16 if FORCE_CPU else 512))
        reps = int(os.environ.get("MICRO_REPS", 3 if FORCE_CPU else 20))
        if FORCE_CPU:
            variants = [("decomposed_grouped", None)]
            RESULT["decompose_grouped"] = True
        else:
            variants = [("fused_grouped", None)]
            os.environ["DBS_DECOMPOSE_GROUPED_CONV"] = "0"
            RESULT["decompose_grouped"] = False
        mk = lambda _: build_model("regnet", num_classes=10).module  # noqa: E731
        RESULT["model"] = "regnety_400mf"
    elif FORCE_CPU:
        B = int(os.environ.get("MICRO_B", 16))
        reps = int(os.environ.get("MICRO_REPS", 3))
        variants = [("buffer", True), ("concat", False)]
        mk = lambda ub: DenseNet((2, 2), growth_rate=12, use_buffer=ub)  # noqa: E731
        RESULT["model"] = "densenet_tiny_2x2_g12"
    else:
        B = int(os.environ.get("MICRO_B", 512))
        reps = int(os.environ.get("MICRO_REPS", 20))
        variants = [("buffer", True), ("concat", False)]
        mk = lambda ub: DenseNet121(use_buffer=ub)  # noqa: E731
        RESULT["model"] = "densenet121"
    RESULT["global_batch"] = B
    RESULT["reps"] = reps
    peak = chip_peak_flops(dev)
    RESULT["bf16_peak_flops_per_dev"] = peak

    # synthetic CIFAR-shaped batch; bf16 compute, f32 master weights —
    # mirrors StepLibrary's mixed-precision policy (train/steps.py)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, 32, 32, 3).astype(np.float32) * 2 - 1, jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 10, (B,)), jnp.int32)
    tx = optax.sgd(0.01, momentum=0.9)

    def build_step(model):
        def loss_fn(p, xx, yy):
            cast = jax.tree_util.tree_map(
                lambda t: t.astype(jnp.bfloat16) if t.dtype == jnp.float32 else t, p
            )
            logits = model.apply(cast, xx, train=True)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.mean(jnp.take_along_axis(logp, yy[:, None], axis=1))

        @jax.jit
        def step(p, opt, xx, yy):
            loss, g = jax.value_and_grad(loss_fn)(p, xx, yy)
            g = jax.tree_util.tree_map(lambda t: t.astype(jnp.float32), g)
            up, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, up), opt, loss

        return step

    for name, ub in variants:
        t_sec = RESULT["variants"][name] = {}
        try:
            model = mk(ub)
            params = model.init(jax.random.PRNGKey(0), x[:2].astype(jnp.float32), train=False)
            opt = tx.init(params)
            step = build_step(model)
            t0 = time.perf_counter()
            lowered = step.lower(params, opt, x, y)
            compiled = lowered.compile()
            t_sec["compile_s"] = time.perf_counter() - t0
            try:  # cost model optional (obs/flops.py documents backends without it)
                cost = compiled.cost_analysis()
                if isinstance(cost, (list, tuple)):
                    cost = cost[0] if cost else {}
                t_sec["flops_per_step"] = float(cost.get("flops", 0.0)) or None
                t_sec["bytes_accessed_per_step"] = (
                    float(cost.get("bytes accessed", 0.0)) or None
                )
            except Exception:
                t_sec["flops_per_step"] = t_sec["bytes_accessed_per_step"] = None
            # warmup + blocking-min
            p2, o2, _ = step(params, opt, x, y)
            jax.block_until_ready(p2)
            times = []
            for _ in range(reps):
                t0 = time.perf_counter()
                p2, o2, loss = step(p2, o2, x, y)
                jax.block_until_ready(p2)
                times.append(time.perf_counter() - t0)
            times.sort()
            t_min = times[0]
            t_sec["blocking_step_ms_min"] = t_min * 1e3
            t_sec["blocking_step_ms_median"] = times[len(times) // 2] * 1e3
            # pipelined: reps dispatches, block once
            t0 = time.perf_counter()
            for _ in range(reps):
                p2, o2, loss = step(p2, o2, x, y)
            jax.block_until_ready(p2)
            t_pipe = (time.perf_counter() - t0) / reps
            t_sec["pipelined_step_ms"] = t_pipe * 1e3
            t_sec["examples_per_s"] = B / t_pipe
            t_sec["final_loss"] = float(loss)
            # synced: per-step HOST READBACK of the loss scalar plus a final
            # block, timed as one total wall. float(loss) cannot return until
            # the device has produced the value, so this is immune to any
            # block_until_ready quirk on experimental/tunneled platforms —
            # the round-5 first on-chip run produced a buffer-variant
            # blocking_step_ms that implied >500% of bf16 peak, which only a
            # broken block (not physics) can explain. total_wall/reps is the
            # trustworthy step time; per-step medians are kept for shape.
            times2 = []
            t_all0 = time.perf_counter()
            for _ in range(reps):
                t0 = time.perf_counter()
                p2, o2, loss = step(p2, o2, x, y)
                _ = float(loss)
                times2.append(time.perf_counter() - t0)
            jax.block_until_ready(p2)
            t_wall = (time.perf_counter() - t_all0) / reps
            times2.sort()
            t_sec["synced_step_ms_median"] = times2[len(times2) // 2] * 1e3
            t_sec["synced_total_wall_ms_per_step"] = t_wall * 1e3
            t_sec["synced_examples_per_s"] = B / t_wall
            if t_sec.get("flops_per_step") and peak:
                t_sec["step_mfu_synced"] = t_sec["flops_per_step"] / t_wall / peak
            # chained: reps data-dependent steps, ONE float(loss) readback.
            # The per-step synced wall above pays a full host<->device round
            # trip per step — over the axon tunnel that RTT is O(100 ms) and
            # dominates, so it only upper-bounds the step time. Here one RTT
            # amortizes over reps steps; subtracting a directly-measured RTT
            # (tiny jitted op, synced readback) gives the device-pure step
            # time that neither the broken block_until_ready nor the
            # per-step-synced wall can: true ~= (wall - rtt) / reps.
            tiny = jax.jit(lambda a: a + 1.0)
            _ = float(tiny(jnp.float32(0)))  # compile
            rtts = sorted(
                _timed(lambda: float(tiny(jnp.float32(i)))) for i in range(5)
            )
            rtt = rtts[len(rtts) // 2]
            t_sec["tunnel_rtt_ms_median"] = rtt * 1e3
            t0 = time.perf_counter()
            for _ in range(reps):
                p2, o2, loss = step(p2, o2, x, y)
            _ = float(loss)
            wall_chain = time.perf_counter() - t0
            t_chain = max(wall_chain - rtt, 1e-9) / reps
            t_sec["chained_step_ms"] = t_chain * 1e3
            t_sec["chained_examples_per_s"] = B / t_chain
            if t_sec.get("flops_per_step") and peak:
                t_sec["step_mfu_chained"] = t_sec["flops_per_step"] / t_chain / peak
            f = t_sec["flops_per_step"]
            if f and peak:
                t_sec["step_mfu_blocking"] = f / t_min / peak
                t_sec["step_mfu_pipelined"] = f / t_pipe / peak
            del params, opt, p2, o2
        except Exception as e:  # OOM / lowering failure on one variant is a finding
            t_sec["error"] = f"{type(e).__name__}: {e}"[:500]
        _save()

    v = RESULT["variants"]
    if "pipelined_step_ms" in v.get("buffer", {}) and "pipelined_step_ms" in v.get("concat", {}):
        RESULT["buffer_speedup_vs_concat"] = (
            v["concat"]["pipelined_step_ms"] / v["buffer"]["pipelined_step_ms"]
        )
        if v["buffer"].get("bytes_accessed_per_step") and v["concat"].get(
            "bytes_accessed_per_step"
        ):
            RESULT["buffer_bytes_ratio"] = (
                v["buffer"]["bytes_accessed_per_step"] / v["concat"]["bytes_accessed_per_step"]
            )
    _save()
    print(json.dumps({k: RESULT[k] for k in RESULT if k != "variants"}))
    for name, sec in RESULT["variants"].items():
        print(name, json.dumps(sec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
