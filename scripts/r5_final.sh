#!/bin/bash
# Round-5 final measurement sequence (after two c3 SIGABRTs in XLA:CPU's
# 40 s collective-rendezvous timeout — DenseNet's ~130 s per-shard segments
# mean any thread staggering at the 4-device all-reduce, e.g. from an
# epoch-1 new-shape compile running concurrently, can blow the window):
#   1. c3 with STATIS_GPU_MAP=0,0,0,0 — all 4 workers on ONE device, so
#      the combine has no cross-device rendezvous at all. Same serialized
#      1-core compute as every other CPU-tier row; topology recorded in
#      the out_dir nesting + manifest args.
#   2. seed-4321 c1 pair (the uint32 seed-overflow bug in the per-epoch
#      shuffle is fixed).
#   3. ONE merged AB_TABLE.md across both statis dirs.
cd "$(dirname "$0")/.."
set -u
OUT=artifacts/acceptance_cpu_small_r5

echo "[r5_final] === c3 densenet 4ep gpumap0000 ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
STATIS_CPU=1 STATIS_ONLY=c3_densenet STATIS_NTRAIN=2048 STATIS_EPOCHS=4 \
  STATIS_GPU_MAP=0,0,0,0 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
echo "[r5_final] c3 rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

echo "[r5_final] === seed-4321 c1 ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 \
  STATIS_SEED=4321 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
echo "[r5_final] seed c1 rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

python scripts/summarize_statis.py "$OUT/statis" "$OUT/gpumap0000/statis" \
  --markdown "$OUT/AB_TABLE.md" >> /tmp/r5_chain.log 2>&1
{
  echo ""
  echo "Provenance: round-5 code, CPU tier (1-core box; 8-virtual-device"
  echo "mesh except the c3 row, which runs all 4 workers on one device —"
  echo "XLA:CPU's 40 s collective-rendezvous termination timeout aborts"
  echo "cross-device combines whose per-shard segments run ~130 s, see"
  echo "gpumap0000/ nesting; same serialized 1-core compute either way),"
  echo "synthetic stand-in data (zero-egress env), seeds paired across arms"
  echo "(1234; cross-seed noise band: seed4321/ c1 pair), walls exclude"
  echo "probe cost (wall_excludes_probes). Scales: vision n_train=2048"
  echo "(c4 B=256), LM 120k tokens. Epochs: c1=12, c2/c3/c4/c5=4."
} >> "$OUT/AB_TABLE.md"
echo "[r5_final] done at $(date -u +%H:%M:%S)" >> /tmp/r5_chain.log
