#!/bin/bash
# Round-5 final measurement sequence (after two c3 SIGABRTs in XLA:CPU's
# 40 s collective-rendezvous timeout — DenseNet's ~130 s per-shard segments
# mean any thread staggering at the 4-device all-reduce, e.g. from an
# epoch-1 new-shape compile running concurrently, can blow the window):
#   1. c3 with STATIS_GPU_MAP=0,0,0,0 — all 4 workers on ONE device, so
#      the combine has no cross-device rendezvous at all. Same serialized
#      1-core compute as every other CPU-tier row; topology recorded in
#      the out_dir nesting + manifest args.
#   2. seed-4321 c1 pair (the uint32 seed-overflow bug in the per-epoch
#      shuffle is fixed).
#   3. ONE merged AB_TABLE.md across both statis dirs.
cd "$(dirname "$0")/.."
set -u
OUT=artifacts/acceptance_cpu_small_r5

# HISTORICAL NOTE (end of round): the c3 leg below was ultimately dropped —
# every new DenseNet-121 executable shape costs ~40 min to compile on
# XLA:CPU even single-device, putting an honest A/B at ~2.5 h/arm; see
# AB_TABLE.md's provenance footer for the full diagnosis. The seed pair
# and the committed table were produced by the trimmed /tmp runner; this
# file is kept as the record of the intended sequence, with the review
# fixes (rc gating; no table on a failed leg) applied.
echo "[r5_final] === c3 densenet 4ep gpumap0000 ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
STATIS_CPU=1 STATIS_ONLY=c3_densenet STATIS_NTRAIN=2048 STATIS_EPOCHS=4 \
  STATIS_GPU_MAP=0,0,0,0 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
C3_RC=$?
echo "[r5_final] c3 rc=$C3_RC ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

echo "[r5_final] === seed-4321 c1 ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 \
  STATIS_SEED=4321 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
echo "[r5_final] seed c1 rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

if [ "$C3_RC" -ne 0 ]; then
  echo "[r5_final] c3 failed; NOT regenerating the table (it would silently drop the row)" >> /tmp/r5_chain.log
  exit "$C3_RC"
fi
if python scripts/summarize_statis.py "$OUT/statis" "$OUT/gpumap0000/statis" \
  --markdown "$OUT/AB_TABLE.md" >> /tmp/r5_chain.log 2>&1; then
  {
    echo ""
    echo "Provenance: round-5 code ($(git rev-parse --short HEAD)), CPU tier"
    echo "(1-core box; 8-virtual-device mesh except the c3 row, which runs"
    echo "all 4 workers on one device — XLA:CPU's 40 s collective-rendezvous"
    echo "termination timeout aborts cross-device combines whose per-shard"
    echo "segments run ~130 s, see gpumap0000/ nesting; same serialized"
    echo "1-core compute either way), synthetic stand-in data (zero-egress"
    echo "env), seeds paired across arms (1234; cross-seed noise band:"
    echo "seed4321/ c1 pair), walls exclude probe cost"
    echo "(wall_excludes_probes). Scales: vision n_train=2048 (c4 B=256),"
    echo "LM 120k tokens. Epochs: c1=12, c2/c3/c4/c5=4."
  } >> "$OUT/AB_TABLE.md"
fi
echo "[r5_final] done at $(date -u +%H:%M:%S)" >> /tmp/r5_chain.log
