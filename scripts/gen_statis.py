#!/usr/bin/env python
"""Generate the BASELINE.md acceptance-config statis artifacts.

Runs the 5 acceptance configs (BASELINE.md §"Acceptance configs"), each with
dbs on AND off (the A/B of the reference's run.sh:25-41), through the REAL
entry point (``cli.main`` — the analogue of ``python dbs.py <flags>``,
dbs.py:527-544), producing the 9-series ``.npy``/``.json`` recorder artifacts
per run (mirroring dbs.py:440-442) under ``--out_dir``.

Straggler profiles are induced deterministically with ``--straggler`` (the
analogue of the reference README's contended GPU map ``-gpu 0,0,0,1``,
README.md:23-28) in ``compute`` mode: real extra device FLOPs, so the
balancer reacts to genuinely measured time.

Scale knobs (env): STATIS_NTRAIN (vision examples, default 4096),
STATIS_LM_NTRAIN (LM tokens, default 120000), STATIS_EPOCHS (default 6),
STATIS_CPU=1 (force the 8-virtual-device CPU mesh — the reference's
gloo-on-localhost debug analogue), STATIS_ONLY (comma list of config names
to run, e.g. "c3_densenet"). Real data is used when present under ./data //
./rnn_data (run data/prepare.py first); otherwise the synthetic stand-ins.

Usage: python scripts/gen_statis.py [--out_dir artifacts/acceptance]
"""

import argparse
import json
import os
import sys
import time

if os.environ.get("STATIS_CPU") == "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compile cache: a tunnel-drop retry must not re-pay compiles
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")

NTRAIN = int(os.environ.get("STATIS_NTRAIN", 4096))
LM_NTRAIN = int(os.environ.get("STATIS_LM_NTRAIN", 120_000))
EPOCHS = int(os.environ.get("STATIS_EPOCHS", 6))

# name -> cli args (without -dbs; both arms added by the driver loop below).
# ocp on for the CNN sweep legs, as run.sh:25-41 does.
CONFIGS = {
    # 1. MnistNet / FashionMNIST, 2-worker, debug-mode scale (BASELINE #1)
    "c1_mnistnet": [
        "-d", "true", "-ws", "2", "-b", "128", "-m", "mnistnet", "-ds", "mnist",
        "--straggler", "3,1",
    ],
    # 2. ResNet-18 / CIFAR-10, 4-worker, balanced workers (BASELINE #2)
    "c2_resnet18": [
        "-d", "false", "-ws", "4", "-b", "512", "-m", "resnet18", "-ds", "cifar10",
        "-ocp", "true",
    ],
    # 3. DenseNet-121 / CIFAR-10, 4-worker, 3:1 straggler — the README recipe
    #    (BASELINE #3, north star)
    "c3_densenet": [
        "-d", "false", "-ws", "4", "-b", "512", "-m", "densenet", "-ds", "cifar10",
        "-ocp", "true", "--straggler", "3,1,1,1",
    ],
    # 4. RegNet / CIFAR-10, 8-worker heterogeneous mix (BASELINE #4)
    "c4_regnet_ws8": [
        "-d", "false", "-ws", "8", "-b", "512", "-m", "regnet", "-ds", "cifar10",
        "-ocp", "true", "--straggler", "3,2,1,1,1,1,1,1",
    ],
    # 4b. GoogLeNet twin of BASELINE #4 ("RegNet / GoogLeNet on CIFAR-10,
    #     8-worker"); not in the default queue — run via STATIS_ONLY
    "c4b_googlenet_ws8": [
        "-d", "false", "-ws", "8", "-b", "512", "-m", "googlenet", "-ds", "cifar10",
        "-ocp", "true", "--straggler", "3,2,1,1,1,1,1,1",
    ],
    # 5. Transformer LM / wikitext-2, 4-worker (BASELINE #5)
    "c5_transformer": [
        "-d", "false", "-ws", "4", "-b", "80", "-m", "transformer", "-ds", "wikitext2",
        "--bptt", "35", "--grad_clip", "0.25", "--bucket", "4",
        "--straggler", "3,1,1,1",
    ],
}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", default="artifacts/acceptance")
    ns = ap.parse_args()

    seed = os.environ.get("STATIS_SEED")
    if seed:
        # seed is NOT part of Config.base_filename(), so sentinels and
        # recorder artifacts of different seeds would collide in one
        # out_dir (first-seed sentinels silently skip the second seed's
        # runs; cleared sentinels overwrite its artifacts). Nest per seed
        # so collisions are structurally impossible.
        ns.out_dir = os.path.join(ns.out_dir, f"seed{seed}")
    if os.environ.get("STATIS_GPU_MAP"):
        # same collision hazard: the device map is not config-encoded
        ns.out_dir = os.path.join(
            ns.out_dir, "gpumap" + os.environ["STATIS_GPU_MAP"].replace(",", "")
        )

    import jax

    if os.environ.get("STATIS_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")  # beats the axon TPU plugin

    from dynamic_load_balance_distributeddnn_tpu import cli
    from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
        arm_stall_watchdog,
    )

    # A dropped TPU tunnel leaves PJRT hung in C++ (0% CPU, uninterruptible);
    # the engine heartbeats per compile/probe/epoch, so a stale heartbeat
    # means a dead backend — exit and let the queue retry on the next window.
    if os.environ.get("STATIS_CPU") != "1":
        arm_stall_watchdog(
            os.path.join(ns.out_dir, ".hb"),
            float(os.environ.get("STATIS_STALL_S", 1200)),
        )

    stat_dir = os.path.join(ns.out_dir, "statis")
    log_dir = os.path.join(ns.out_dir, "logs")
    os.makedirs(stat_dir, exist_ok=True)

    only = os.environ.get("STATIS_ONLY")
    # opt-in extras (run via STATIS_ONLY) — a bare invocation runs exactly
    # the 5 BASELINE acceptance configs the docstring promises
    optional = {"c4b_googlenet_ws8"}
    if only:
        wanted = set(only.split(","))
        names = [n for n in CONFIGS if n in wanted]
    else:
        names = [n for n in CONFIGS if n not in optional]
    vision_b = os.environ.get("STATIS_VISION_B")  # reduced-scale CPU insurance
    # STATIS_GPU_MAP: explicit worker->device map (the reference's -gpu
    # 0,0,0,1 contention syntax). CPU-tier escape hatch: mapping all workers
    # to one device keeps per-worker executables single-device — the
    # 8-device SPMD compile of a decomposed-grouped-conv RegNet is an
    # XLA:CPU compile blowup even though the same graph compiles in ~42 s
    # per worker single-device. Applied ONLY to vision configs whose
    # world_size equals the map length (it is a per-config escape hatch,
    # not a global topology override), and the run nests into its own
    # out_dir because the device map is not part of the config-encoded
    # filenames (same collision hazard as STATIS_SEED above).
    gpu_map = os.environ.get("STATIS_GPU_MAP")  # out_dir nesting done above
    # STATIS_FORCE_ELASTIC=1: for configs that would otherwise take a
    # whole-epoch fused/packed CNN scan (no straggler -> uniform fused plan,
    # i.e. c2), map two workers per device so both arms use the elastic
    # per-worker executables — the XLA *CPU* backend compiles the fused CNN
    # scan pathologically slowly (30+ min for ResNet-18) while the elastic
    # path's small per-step graphs compile in seconds. Straggler configs
    # already run elastic (compute-mode probes force it) and keep their
    # default topology. CPU-insurance only; TPU runs skip this env var.
    force_elastic = os.environ.get("STATIS_FORCE_ELASTIC") == "1"
    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", "?")
    # merge with any existing manifest: the queue fills this dir across
    # several invocations (c1/c5 on the CPU tier, c2-c4 on chip, retries
    # after tunnel drops) and each run's provenance must survive them all
    mpath = os.path.join(ns.out_dir, "manifest.json")
    manifest = {}
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        pass
    manifest.update(
        {
            "platform": platform,
            "device_kind": device_kind,
            "ntrain": NTRAIN,
            "lm_ntrain": LM_NTRAIN,
            "epochs": EPOCHS,
        }
    )
    manifest.setdefault("runs", {})
    for name in names:
        base = list(CONFIGS[name])
        if vision_b and name != "c5_transformer":
            bi = base.index("-b")
            base[bi + 1] = vision_b
        if (
            gpu_map
            and "-gpu" not in base
            and name != "c5_transformer"
            and len(gpu_map.split(",")) == int(base[base.index("-ws") + 1])
        ):
            print(f"[gen_statis] {name}: applying STATIS_GPU_MAP={gpu_map}", flush=True)
            base += ["-gpu", gpu_map]
        if force_elastic and "-gpu" not in base and "--straggler" not in base:
            ws = int(base[base.index("-ws") + 1])
            if ws >= 4:  # >=2 devices, >=2 workers/device: elastic, not packed
                base += ["-gpu", ",".join(str(i // 2) for i in range(ws))]
        n_train = LM_NTRAIN if name == "c5_transformer" else NTRAIN
        # STATIS_ARM_ORDER=false_first flips the arms: running the A/B in
        # both orders exposes host-throughput drift between the two arms'
        # time windows (sequential arms on a noisy 1-core box can differ
        # several % for identical work)
        arm_order = (
            ("false", "true")
            if os.environ.get("STATIS_ARM_ORDER") == "false_first"
            else ("true", "false")
        )
        seed = os.environ.get("STATIS_SEED")  # second-seed parity pairs
        for dbs in arm_order:
            args = base + (["--seed", seed] if seed else []) + [
                "-dbs", dbs,
                "-e", str(EPOCHS),
                "--n_train", str(n_train),
                "--fault_mode", "compute",
                # warm_start pre-compiles the shape ladder — worth it on TPU
                # (cached, fast), prohibitive on the CPU mesh. The balancer's
                # signal is compile-free either way (probe warm pass).
                "--warm_start", os.environ.get("STATIS_WARM", "false"),
                "--stat_dir", stat_dir,
                "--log_dir", log_dir,
            ]
            from dynamic_load_balance_distributeddnn_tpu.config import (
                config_from_args,
            )
            from dynamic_load_balance_distributeddnn_tpu.obs.logging import (
                _done_sentinel,
                run_already_done,
            )

            cfg = config_from_args(args)
            key = f"{name}_dbs{dbs}"
            # a non-tpu (e.g. reduced-scale CPU-insurance) run must never
            # clobber a chip entry's provenance — it runs a different config
            # (different sentinel), so record it under its own key and leave
            # the tpu entry (and its sentinel) standing
            if (
                platform != "tpu"
                and (manifest["runs"].get(key) or {}).get("platform") == "tpu"
            ):
                key = f"{key}_{platform}"
            # chip runs supersede CPU-tier runs in the same out_dir (never
            # the reverse): if this arm's sentinel was written by a non-TPU
            # invocation and we are ON the chip now, clear it so the run
            # re-executes here instead of being skipped by the reference
            # idempotence probe
            if platform == "tpu":
                prev_run = manifest["runs"].get(key) or {}
                # only the PER-RUN platform is trustworthy: the top-level
                # manifest platform is whatever the last invocation ran on
                # (a CPU-tier c1 run after a TPU c3 run would misclassify the
                # TPU sentinels and re-burn tunnel window re-running them).
                # Anything not positively attributed to the chip — explicit
                # cpu tier, a legacy entry with no platform field, or an
                # unattributed sentinel skip — is superseded by running here:
                # one idempotent re-run, after which the manifest records tpu
                prev_platform = prev_run.get("platform")
                if prev_platform != "tpu":
                    sentinel = _done_sentinel(cfg)
                    if os.path.isfile(sentinel):
                        os.unlink(sentinel)
                        print(
                            f"[gen_statis] {name} dbs={dbs}: clearing "
                            f"{prev_platform or 'unattributed'} sentinel, "
                            "re-running on tpu",
                            flush=True,
                        )
            skipped = run_already_done(cfg)
            t0 = time.time()
            print(f"[gen_statis] {name} dbs={dbs}: cli.main({' '.join(args)})", flush=True)
            rc = cli.main(args)
            if skipped and key in manifest["runs"]:
                # sentinel skip: the run that produced the artifacts is the
                # recorded one — keep its provenance, don't clobber wall_s
                # and platform with the skip's
                pass
            else:
                manifest["runs"][key] = {
                    "rc": rc,
                    "wall_s": round(time.time() - t0, 1),
                    # a sentinel skip executed nothing here: the artifacts
                    # came from an invocation this manifest never saw, so
                    # their platform is unknown — recording THIS invocation's
                    # would let a later TPU pass wrongly trust (or clear) them
                    "platform": "unknown" if skipped else platform,
                    "device_kind": "?" if skipped else device_kind,
                    "args": args,
                    **({"sentinel_skip": True} if skipped else {}),
                }
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=2)
            if rc != 0:
                print(f"[gen_statis] {name} dbs={dbs} FAILED rc={rc}", file=sys.stderr)
                return rc
    print("[gen_statis] all runs complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
