#!/bin/bash
# Round-3 TPU queue: loop forever, and whenever the tunnel answers, run the
# on-chip work in priority order (VERDICT r2 "next round" items 1, 2, 4, 9):
#   1. bench.py (device-cache + packed pipeline)  -> artifacts/BENCH_local_tpu.json
#   2. scripts/mfu_probe.py                       -> artifacts/MFU_PROBE.json
#   3. TPU-marked flash-attention test            (validates the lse tiling fix)
#   4. scripts/kernel_bench.py                    -> artifacts/kernel_bench_tpu.json
#   5. scripts/gen_statis.py c2/c3/c4             -> artifacts/acceptance/
#   6. scripts/precision_bench.py                 -> artifacts/PRECISION.md
# Per-leg stamps under artifacts/.queue3/ make every leg idempotent; a leg
# that fails (tunnel drop) is retried on the next up-window. While ANY leg
# is running, .tpu_busy exists at the repo root — heavy host work (test
# suites) must not run then, or it poisons the on-chip timing (round-2
# lesson). Logs: /tmp/tpu_queue3.log. Safe to kill at any point.
set -u
cd "$(dirname "$0")/.."
STAMPS=artifacts/.queue3
mkdir -p "$STAMPS" artifacts
trap 'rm -f .tpu_busy' EXIT

# Commit any artifact evidence the moment a leg produces it — the round-3
# lesson is that a tunnel window can close before a round ends, and the
# round-4 lesson is that it may never open again. Committed == survives.
commit_evidence () {
  git add -A artifacts/ 2>/dev/null
  if ! git diff --cached --quiet -- artifacts/ 2>/dev/null; then
    if git commit -q -m "tpu queue: on-chip evidence ($1, $(date -u +%H:%M:%SZ))" -- artifacts/; then
      echo "[queue3] committed evidence after $1"
    else
      echo "[queue3] WARNING: evidence commit FAILED after $1 (rc=$?) — artifacts staged but NOT committed" >&2
    fi
  fi
}

leg () {  # leg <name> <timeout_s> <cmd...>
  local name="$1" tmo="$2"; shift 2
  [ -f "$STAMPS/$name.done" ] && return 0
  [ -f "$STAMPS/$name.gaveup" ] && return 0
  echo "[queue3] === leg $name ($(date -u +%H:%M:%S)) ==="
  touch .tpu_busy
  if timeout "$tmo" "$@"; then
    touch "$STAMPS/$name.done"
    echo "[queue3] leg $name done"
    rm -f .tpu_busy
    commit_evidence "$name"
    return 0
  else
    local rc=$?
    echo "[queue3] leg $name failed rc=$rc"
    rm -f .tpu_busy
    # even a failed leg may have produced partial incremental artifacts
    commit_evidence "$name (partial)"
    # tunnel still up right after the failure => the failure is REAL, not a
    # drop. Bound real failures (3 attempts) so one broken leg cannot
    # starve everything queued behind it; a drop keeps unlimited retries.
    if PROBE_CAP_S=60 timeout 80 python scripts/tpu_probe_once.py 2>&1 | grep -q "PROBE ok"; then
      local n=0
      [ -f "$STAMPS/$name.attempts" ] && n=$(cat "$STAMPS/$name.attempts")
      n=$((n + 1)); echo "$n" > "$STAMPS/$name.attempts"
      if [ "$n" -ge 3 ]; then
        echo "[queue3] leg $name failed $n times with the tunnel up; skipping it"
        touch "$STAMPS/$name.gaveup"
        return 0
      fi
    fi
    return "$rc"
  fi
}

all_done () {
  for n in micro micro_regnet bench mfu flash kernels statis precision statis_c5; do
    [ -f "$STAMPS/$n.done" ] || [ -f "$STAMPS/$n.gaveup" ] || return 1
  done
  return 0
}

while true; do
  if all_done; then
    echo "[queue3] all legs complete at $(date -u +%H:%M:%S)"
    exit 0
  fi
  if PROBE_CAP_S="${TPU_PROBE_CAP_S:-300}" timeout "$(( ${TPU_PROBE_CAP_S:-300} + 20 ))" python scripts/tpu_probe_once.py 2>&1 | grep -q "PROBE ok"; then
    echo "[queue3] TPU up at $(date -u +%H:%M:%S)"
    # a failed leg usually means the tunnel dropped mid-run — go straight
    # back to the probe loop instead of burning every later leg's timeout
    # against a dead backend
    #
    # micro FIRST (VERDICT r4 #1): sized so a sub-2-minute window still
    # commits a current-code on-chip number (incremental saves + the
    # commit_evidence hook fire even on a mid-leg tunnel drop). Also the
    # on-hardware verdict on the DenseNet buffer-vs-concat byte claim (#4).
    # outer timeout > MICRO_INIT_CAP_S + MICRO_TOTAL_CAP_S so the script's
    # own watchdogs, not the queue, decide a slow-but-live run. Round-5
    # observation: the first DenseNet-121 B=512 compile over the axon tunnel
    # exceeded the original 600 s total cap (watchdog fired, 0 variants
    # landed), so the caps are sized for tunnel-compile latency now; the
    # persistent ./.jax_cache makes retries and later legs cheap.
    leg micro 4000 env MICRO_INIT_CAP_S=600 MICRO_TOTAL_CAP_S=3300 python scripts/tpu_micro_leg.py || continue
    # VERDICT r4 #3(c): the fused grouped conv (XLA:CPU's pathology) must be
    # shown compiling in seconds on the chip — one variant, ~1 compile
    leg micro_regnet 2500 env MICRO_MODEL=regnet MICRO_INIT_CAP_S=600 MICRO_TOTAL_CAP_S=1800 python scripts/tpu_micro_leg.py || continue
    leg bench 6600 env BENCH_TOTAL_BUDGET="${BENCH_TOTAL_BUDGET:-5400}" BENCH_CPU_INSURANCE=0 \
      sh -c 'python bench.py > artifacts/BENCH_local_tpu.json.tmp 2>/tmp/bench_full3.log && { head -c 200 artifacts/BENCH_local_tpu.json.tmp | grep -q "\"backend\": \"tpu\"" && mv artifacts/BENCH_local_tpu.json.tmp artifacts/BENCH_local_tpu.json; }' \
      || continue
    leg mfu 4800 python scripts/mfu_probe.py || continue
    leg flash 1500 env RUN_TPU_TESTS=1 python -m pytest \
      tests/test_pallas.py::test_flash_nondefault_blocks_real_tpu -q || continue
    leg kernels 2400 python scripts/kernel_bench.py --repeats 30 || continue
    # STATIS_WARM=false: the queued configs are all 1-chip vision -> packed
    # path, where the elastic warm ladder compiles executables the run never
    # times (probes self-warm untimed); the ladder would burn 30-90 min of
    # tunnel window across the three model families
    leg statis 14400 env STATIS_ONLY=c2_resnet18,c3_densenet,c4_regnet_ws8 STATIS_WARM=false \
      sh -c 'python scripts/gen_statis.py --out_dir artifacts/acceptance >> /tmp/gen_statis_tpu.log 2>&1' \
      || continue
    leg precision 3600 python scripts/precision_bench.py || continue
    # bonus leg (after every VERDICT item): the LM acceptance config on chip,
    # completing the full BASELINE matrix c1-c5 (c1 runs fine on CPU tier)
    leg statis_c5 7200 env STATIS_ONLY=c5_transformer STATIS_WARM=false \
      sh -c 'python scripts/gen_statis.py --out_dir artifacts/acceptance >> /tmp/gen_statis_tpu.log 2>&1' \
      || continue
  else
    echo "[queue3] TPU down at $(date -u +%H:%M:%S); sleeping ${TPU_PROBE_SLEEP_S:-120}s"
    sleep "${TPU_PROBE_SLEEP_S:-120}"
  fi
  sleep 5
done
