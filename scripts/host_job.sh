#!/bin/bash
# Run a heavy host job, SIGSTOPping it whenever the TPU queue is mid-leg
# (.tpu_busy at the repo root) — heavy host work running concurrently with
# an on-chip measurement poisons the chip timing (round-2 lesson). The
# job's own walls are sacrificial: epochs that overlap a pause are ruined
# and the job should simply be re-run (its sentinels make that cheap).
#
# The job runs in its own session (setsid) and ALL signals target the
# process group: stopping only the direct child would leave its
# subprocesses (multiprocessing workers, chained scripts) burning CPU
# during a TPU leg — the exact contention this wrapper exists to prevent.
cd "$(dirname "$0")/.."
setsid "$@" &
PID=$!
# wait for the child to become its own group leader — group signals sent
# before setsid(2) completes would silently miss (ESRCH), letting the job
# run unthrottled through a TPU leg or escape the exit cleanup
MATCHED=0
for _ in $(seq 1 50); do
  if [ "$(ps -o pgid= -p "$PID" 2>/dev/null | tr -d ' ')" = "$PID" ]; then MATCHED=1; break; fi
  kill -0 "$PID" 2>/dev/null || break
  sleep 0.1
done
if [ "$MATCHED" != 1 ]; then
  if ! kill -0 "$PID" 2>/dev/null; then
    # child already finished inside the poll window — nothing left to
    # monitor; propagate its real exit status instead of misdiagnosing
    wait "$PID"; exit $?
  fi
  # If the shell child was already a group leader, setsid(1) forks and $!
  # is a short-lived intermediate — group signals would target the wrong
  # (dead) pgid while the real job runs unthrottled through TPU legs.
  # Fail loudly instead of silently monitoring nothing.
  echo "[host_job] ERROR: child $PID never became its own process-group leader;" >&2
  echo "[host_job] refusing to monitor a job I cannot pause. (If the wrapper" >&2
  echo "[host_job] itself was SIGKILLed while paused, run: kill -CONT -- -<pgid>)" >&2
  kill -- "-$PID" 2>/dev/null; kill "$PID" 2>/dev/null
  exit 70
fi
# a stopped process ignores TERM until resumed — CONT first on exit
trap 'kill -CONT -- "-$PID" 2>/dev/null; kill -- "-$PID" 2>/dev/null' EXIT
PAUSED=0
while kill -0 "$PID" 2>/dev/null; do
  if [ -f .tpu_busy ]; then
    if [ "$PAUSED" = 0 ]; then kill -STOP -- "-$PID" 2>/dev/null; PAUSED=1; echo "[host_job] paused for TPU leg"; fi
  else
    if [ "$PAUSED" = 1 ]; then kill -CONT -- "-$PID" 2>/dev/null; PAUSED=0; echo "[host_job] resumed"; fi
  fi
  sleep 10
done
trap - EXIT
wait "$PID"
