#!/bin/bash
# Run a heavy host job, SIGSTOPping it whenever the TPU queue is mid-leg
# (.tpu_busy at the repo root) — heavy host work running concurrently with
# an on-chip measurement poisons the chip timing (round-2 lesson). The
# job's own walls are sacrificial: epochs that overlap a pause are ruined
# and the job should simply be re-run (its sentinels make that cheap).
#
# The job runs in its own session (setsid) and ALL signals target the
# process group: stopping only the direct child would leave its
# subprocesses (multiprocessing workers, chained scripts) burning CPU
# during a TPU leg — the exact contention this wrapper exists to prevent.
cd "$(dirname "$0")/.."
setsid "$@" &
PID=$!
# wait for the child to become its own group leader — group signals sent
# before setsid(2) completes would silently miss (ESRCH), letting the job
# run unthrottled through a TPU leg or escape the exit cleanup
for _ in $(seq 1 50); do
  [ "$(ps -o pgid= -p "$PID" 2>/dev/null | tr -d ' ')" = "$PID" ] && break
  kill -0 "$PID" 2>/dev/null || break
  sleep 0.1
done
# a stopped process ignores TERM until resumed — CONT first on exit
trap 'kill -CONT -- "-$PID" 2>/dev/null; kill -- "-$PID" 2>/dev/null' EXIT
PAUSED=0
while kill -0 "$PID" 2>/dev/null; do
  if [ -f .tpu_busy ]; then
    if [ "$PAUSED" = 0 ]; then kill -STOP -- "-$PID" 2>/dev/null; PAUSED=1; echo "[host_job] paused for TPU leg"; fi
  else
    if [ "$PAUSED" = 1 ]; then kill -CONT -- "-$PID" 2>/dev/null; PAUSED=0; echo "[host_job] resumed"; fi
  fi
  sleep 10
done
trap - EXIT
wait "$PID"
