#!/bin/bash
# Run a heavy host job, SIGSTOPping it whenever the TPU queue is mid-leg
# (.tpu_busy at the repo root) — heavy host work running concurrently with
# an on-chip measurement poisons the chip timing (round-2 lesson). The
# job's own walls are sacrificial: epochs that overlap a pause are ruined
# and the job should simply be re-run (its sentinels make that cheap).
cd "$(dirname "$0")/.."
"$@" &
PID=$!
trap 'kill "$PID" 2>/dev/null' EXIT
PAUSED=0
while kill -0 "$PID" 2>/dev/null; do
  if [ -f .tpu_busy ]; then
    if [ "$PAUSED" = 0 ]; then kill -STOP "$PID" 2>/dev/null; PAUSED=1; echo "[host_job] paused for TPU leg"; fi
  else
    if [ "$PAUSED" = 1 ]; then kill -CONT "$PID" 2>/dev/null; PAUSED=0; echo "[host_job] resumed"; fi
  fi
  sleep 10
done
trap - EXIT
wait "$PID"
