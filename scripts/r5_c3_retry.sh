#!/bin/bash
# c3 retry (round 5): the first c3 attempt died in XLA:CPU's intra-process
# collective rendezvous (hard 40 s termination timeout, rendezvous.cc:127)
# because a concurrent DenseNet compile starved one of the 4 device
# threads on the 1-core box. The leg is fine standalone (r3b precedent);
# this retry runs it with the box otherwise idle, then regenerates the
# unified table and runs the cheap seed-4321 c1 parity pair. The heavy
# CPU-insurance bench is dropped (round-time budget).
cd "$(dirname "$0")/.."
set -u
OUT=artifacts/acceptance_cpu_small_r5

while ! grep -q "\[r5_chain\] done" /tmp/r5_chain.log 2>/dev/null; do sleep 30; done

echo "[r5_c3_retry] === c3 densenet 4ep retry ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
STATIS_CPU=1 STATIS_ONLY=c3_densenet STATIS_NTRAIN=2048 STATIS_EPOCHS=4 \
  bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
echo "[r5_c3_retry] c3 rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 \
  STATIS_SEED=4321 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
echo "[r5_c3_retry] seed-4321 c1 rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log

python scripts/summarize_statis.py "$OUT/statis" --markdown "$OUT/AB_TABLE.md" \
  >> /tmp/r5_chain.log 2>&1
{
  echo ""
  echo "Provenance: round-5 code ($(git rev-parse --short HEAD)), CPU tier"
  echo "(1-core box, 8-virtual-device mesh — the reference's gloo-on-localhost"
  echo "debug analogue), synthetic stand-in data (zero-egress env), seeds"
  echo "paired across arms (1234; cross-seed noise band from the seed4321/"
  echo "c1 pair), walls exclude probe cost (wall_excludes_probes stamp)."
  echo "Scales: vision n_train=2048 (c4 B=256), LM 120k tokens."
  echo "Epochs: c1=12, c2/c3/c4/c5=4."
} >> "$OUT/AB_TABLE.md"
echo "[r5_c3_retry] done at $(date -u +%H:%M:%S)" >> /tmp/r5_chain.log
