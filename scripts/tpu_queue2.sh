#!/bin/bash
# Second-wave TPU queue: wait for the tunnel to recover, then run the work
# that was pending when it dropped:
#   1. bench.py (device-cache path)      -> artifacts/BENCH_local_tpu.json
#   2. TPU-marked flash-attention test   (validates the lse tiling fix)
#   3. scripts/kernel_bench.py           -> kernel_bench_tpu.json + KERNELS.md
#   4. scripts/gen_statis.py c2/c3/c4    (CLI idempotence skips finished runs)
# Logs to /tmp/tpu_queue2.log. Safe to kill at any point.
set -u
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${TPU_QUEUE_WAIT_S:-21600} ))

echo "[queue2] waiting for TPU (deadline in ${TPU_QUEUE_WAIT_S:-21600}s)"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if PROBE_CAP_S=300 python scripts/tpu_probe_once.py 2>&1 | grep -q "PROBE ok"; then
    echo "[queue2] TPU up at $(date -u +%H:%M:%S)"
    echo "[queue2] === full bench (device cache) ==="
    mkdir -p artifacts
    BENCH_TOTAL_BUDGET=${BENCH_TOTAL_BUDGET:-5400} timeout 6000 python bench.py \
      > artifacts/BENCH_local_tpu.json.tmp 2>/tmp/bench_full2.log \
      || echo "[queue2] bench failed rc=$?"
    grep -q '"backend": "tpu"' artifacts/BENCH_local_tpu.json.tmp 2>/dev/null \
      && mv artifacts/BENCH_local_tpu.json.tmp artifacts/BENCH_local_tpu.json
    echo "[queue2] bench result: $(head -c 400 artifacts/BENCH_local_tpu.json 2>/dev/null)"
    echo "[queue2] === flash TPU test ==="
    RUN_TPU_TESTS=1 timeout 1500 python -m pytest \
      tests/test_pallas.py::test_flash_nondefault_blocks_real_tpu -q \
      || echo "[queue2] flash tpu test failed rc=$?"
    echo "[queue2] === kernel_bench ==="
    timeout 2400 python scripts/kernel_bench.py --repeats 30 \
      || echo "[queue2] kernel_bench failed rc=$?"
    echo "[queue2] === acceptance statis (heavy CNN configs) ==="
    STATIS_ONLY=c2_resnet18,c3_densenet,c4_regnet_ws8 STATIS_WARM=true \
      timeout 10800 python scripts/gen_statis.py --out_dir artifacts/acceptance \
      >> /tmp/gen_statis_tpu.log 2>&1 \
      || echo "[queue2] gen_statis failed rc=$?"
    echo "[queue2] done"
    exit 0
  fi
  echo "[queue2] TPU still down at $(date -u +%H:%M:%S); sleeping 120s"
  sleep 120
done
echo "[queue2] gave up waiting for TPU"
exit 1
