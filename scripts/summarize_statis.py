#!/usr/bin/env python
"""Render recorder artifacts (./statis *.npy/json) as tables.

The reference's workflow dumps per-config numpy dicts (dbs.py:440-442) and
leaves interpretation to offline plotting; this gives the same data a quick
terminal view, and computes the dbs-on/off A/B headline when both arms of a
config are present in the directory.

Usage:
  python scripts/summarize_statis.py artifacts/acceptance/statis [more dirs/files]
"""

import json
import os
import sys

import numpy as np


def load(path):
    if path.endswith(".npy"):
        d = np.load(path, allow_pickle=True).item()
        # the JSON sidecar carries run-level _meta (data provenance) that the
        # reference-parity .npy payload deliberately omits
        sidecar = path[:-4] + ".json"
        if os.path.exists(sidecar):
            try:
                with open(sidecar) as f:
                    d["_meta"] = json.load(f).get("_meta", {})
            except Exception:
                pass
        return d
    with open(path) as f:
        return json.load(f)


def fmt_run(name, d):
    rows = []
    meta = d.get("_meta") or {}
    if meta.get("synthetic"):
        name += "   [SYNTHETIC DATA — accuracies not comparable to real sets]"
    n = len(d.get("epoch", []))
    for e in range(n):
        part = np.asarray(d["partition"][e], dtype=float)
        nt = np.asarray(d["node_time"][e], dtype=float)
        rows.append(
            f"  {int(d['epoch'][e]):>3}  {d['train_loss'][e]:>8.4f}  "
            f"{d['val_loss'][e]:>8.4f}  {d['accuracy'][e]:>7.2f}  "
            f"{d['train_time'][e]:>8.3f}  {d['wallclock_time'][e]:>9.3f}  "
            f"{np.array2string(np.round(part, 3), separator=',')}"
            f"  max/min nt={nt.max() / max(nt.min(), 1e-9):.2f}"
        )
    header = (
        "  ep  train_ls   val_ls      acc   t_node0   wallclock  partition"
    )
    return f"{name}\n{header}\n" + "\n".join(rows)


def main(argv):
    md_out = None
    argv = list(argv or [])
    if "--markdown" in argv:
        i = argv.index("--markdown")
        if i + 1 >= len(argv):
            print("usage: summarize_statis.py [--markdown OUT] [PATHS...]",
                  file=sys.stderr)
            return 2
        md_out = argv[i + 1]
        del argv[i : i + 2]
    paths = []
    for a in argv or ["./statis"]:
        if os.path.isdir(a):
            paths += sorted(
                os.path.join(a, f) for f in os.listdir(a) if f.endswith(".npy")
            )
        elif os.path.exists(a):
            paths.append(a)
    runs = {}
    for p in paths:
        try:
            # keyed by basename; a same-config artifact from a second dir
            # (e.g. a gpumap/seed-nested variant of one config) must not
            # silently shadow the first — disambiguate with the parent dir
            key = os.path.basename(p)
            parent = os.path.dirname(p)
            while key in runs and parent:
                key = f"{os.path.basename(parent)}/{key}"
                parent = os.path.dirname(parent)
            runs[key] = load(p)
        except Exception as e:
            print(f"skip {p}: {e}", file=sys.stderr)
    for name, d in runs.items():
        print(fmt_run(name, d))
        print()
    # A/B headline per config: pair -dbs1- with -dbs0-
    ab_rows = []
    for name, d in runs.items():
        if "-dbs1-" not in name:
            continue
        off_name = name.replace("-dbs1-", "-dbs0-")
        off = runs.get(off_name)
        if off is None:
            continue
        on_w = np.diff([0.0] + list(d["wallclock_time"]))
        off_w = np.diff([0.0] + list(off["wallclock_time"]))
        # steady state: skip the calibration epoch (and first reaction, on-arm);
        # median headline + min alongside, like bench.py's hardened statistic
        on_win = on_w[2:] if len(on_w) > 2 else on_w[-1:]
        off_win = off_w[1:] if len(off_w) > 1 else off_w[-1:]
        on_med, off_med = float(np.median(on_win)), float(np.median(off_win))
        on_min, off_min = float(np.min(on_win)), float(np.min(off_win))
        # balancer-quality metric (BASELINE.md §protocol): distance of the
        # final partition from the ideal equilibrium share_i ∝ 1/f_i, when
        # the artifact records its induced straggler profile
        conv = None
        factors = (d.get("_meta") or {}).get("straggler_factors")
        if factors:
            inv = 1.0 / np.asarray(factors, dtype=float)
            ideal = inv / inv.sum()
            final = np.asarray(d["partition"][-1], dtype=float)
            conv = float(np.abs(final - ideal).max())
        ab_rows.append(
            {
                "config": name.split("-node")[0],
                "on_median_s": on_med,
                "off_median_s": off_med,
                "speedup_median": off_med / max(on_med, 1e-9),
                "speedup_min": off_min / max(on_min, 1e-9),
                "acc_on": float(d["accuracy"][-1]),
                "acc_off": float(off["accuracy"][-1]),
                "synthetic": bool((d.get("_meta") or {}).get("synthetic")),
                "partition_err": conv,
            }
        )
        print(
            f"A/B {name.split('-node')[0]}: steady epoch "
            f"on={on_med:.3f}s off={off_med:.3f}s "
            f"speedup(median)={off_med / max(on_med, 1e-9):.2f}x "
            f"speedup(min)={off_min / max(on_min, 1e-9):.2f}x "
            f"acc on/off={d['accuracy'][-1]:.2f}/{off['accuracy'][-1]:.2f}"
        )
    if md_out and ab_rows:
        lines = [
            "# Acceptance A/B table",
            "",
            "Steady-state epoch wall-clock, dbs on vs off (median over the "
            "steady window, min alongside; reference protocol BASELINE.md).",
            "",
            "| config | on median (s) | off median (s) | speedup (median) | "
            "speedup (min) | acc on/off | partition err |",
            "|---|---|---|---|---|---|---|",
        ]
        for r in sorted(ab_rows, key=lambda r: r["config"]):
            acc = f"{r['acc_on']:.2f}/{r['acc_off']:.2f}"
            if r["synthetic"]:
                acc += " (synthetic)"
            perr = (
                f"{r['partition_err']:.3f}"
                if r["partition_err"] is not None
                else "—"
            )
            lines.append(
                f"| {r['config']} | {r['on_median_s']:.3f} | "
                f"{r['off_median_s']:.3f} | {r['speedup_median']:.2f}x | "
                f"{r['speedup_min']:.2f}x | {acc} | {perr} |"
            )
        with open(md_out, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"[summarize_statis] wrote {md_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
