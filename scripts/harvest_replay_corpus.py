#!/usr/bin/env python
"""Regenerate the checked-in replay-corpus JSONs (tests/corpus_replay/).

The corpus is the tier-1 regression gate for the controller's decision
rule (tests/test_replaylab.py): each file is a recorded decision journal
plus the ``journal_config()`` that produced it, and the gate asserts that
a FRESH controller replayed over the recorded inputs reproduces every
recorded verdict bit-for-bit and that the whole trajectory satisfies the
controller invariants. Run this script ONLY when the decision rule
changes on purpose — a diff in the regenerated corpus is the review
artifact showing exactly which verdicts moved.

Two corpus sources, both device-free and fully deterministic:

* ``sim-*`` — closed-loop scenario simulations through the REAL
  ``OnlineRebalanceController`` (balance/replaylab.py ``simulate``), one
  per library scenario family (scalar schedule, per-worker brownout,
  kill-storm);
* ``engine-linear-ramp`` — a synthetic open-loop drive of the controller
  mimicking the engine's window cadence (rates ramping per window,
  engine-style commit/defer), exercising the defer path the scenario
  simulator never takes.

Usage::

    python scripts/harvest_replay_corpus.py [--out tests/corpus_replay]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamic_load_balance_distributeddnn_tpu.balance import replaylab  # noqa: E402
from dynamic_load_balance_distributeddnn_tpu.balance.controller import (  # noqa: E402
    OnlineRebalanceController,
)

# one scenario per schedule family — enough shapes to pin the decision
# rule without bloating the repo
CORPUS_SCENARIOS = ("sin-surge", "spike-burst", "rack-brownout", "kill-storm")


def harvest_engine_style() -> dict:
    """Open-loop drive mimicking the engine's window cadence, including a
    deferred verdict (the warm-gate veto the scenario simulator never
    issues): the corpus must pin the deferred bookkeeping path too."""
    ctl = OnlineRebalanceController(
        4, 256, [[0], [1], [2], [3]], bucket=8, hysteresis=0.05, margin=1.5
    )
    b = np.array([64, 64, 64, 64])
    base = np.array([0.002, 0.0021, 0.0019, 0.002])
    n_windows, spw = 24, 4
    defer_next = True
    for w in range(n_windows):
        # rates ramp: worker 0 degrades 1x -> 4x across the run
        eff = base * np.array([1.0 + 3.0 * w / n_windows, 1.0, 1.0, 1.0])
        ctl.observe_rates(eff)
        ctl.eval_context = {"epoch": w // 8, "window": w % 8}
        remaining = (8 - (w % 8)) * spw
        dec = ctl.propose(ctl.rates, b, remaining)
        if dec.switch:
            if defer_next:
                # first verdict-positive switch deferred (cold executables)
                ctl.note_deferred()
                defer_next = False
            else:
                ctl.commit(dec, 0.04, epoch=w // 8, window=w % 8)
                b = dec.candidate_batches.copy()
        ctl.observe_wall(0.5, 0.5)
    return replaylab.harvest(ctl, label="engine-linear-ramp")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="tests/corpus_replay")
    args = ap.parse_args(argv)
    os.makedirs(args.out, exist_ok=True)
    by_name = {s.name: s for s in replaylab.builtin_scenarios(4)}
    corpora = []
    for name in CORPUS_SCENARIOS:
        r = replaylab.simulate(by_name[name], include_journal=True)
        corpora.append(
            {
                "label": f"sim-{name}",
                "config": r["config"],
                "journal": r["journal"],
            }
        )
    corpora.append(harvest_engine_style())
    wrote = []
    for corpus in corpora:
        # a corpus that does not replay bit-for-bit TODAY must never be
        # checked in — verify strict parity and invariants before writing
        report = replaylab.replay(corpus)
        if not report["parity"] or report["invariant_violations"]:
            print(
                f"REFUSING {corpus['label']}: parity={report['parity']} "
                f"mismatches={report['mismatches'][:3]} "
                f"violations={report['invariant_violations'][:3]}",
                file=sys.stderr,
            )
            return 1
        path = os.path.join(args.out, f"{corpus['label']}.json")
        with open(path, "w") as fh:
            json.dump(corpus, fh, indent=1, sort_keys=True)
            fh.write("\n")
        wrote.append(
            f"{path}: {len(corpus['journal'])} entries, "
            f"{report['recorded']['switches']} switches, "
            f"{report['recorded']['deferred']} deferred"
        )
    print("\n".join(wrote))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
