#!/usr/bin/env python
"""bf16-vs-f32 A/B on the clean fused leg -> artifacts/PRECISION.md.

Justifies the benchmark's default compute dtype (bench.py BENCH_PRECISION)
with measured numbers: epoch walls, examples/s, MFU, and the training-loss
trajectory delta (numerics evidence — bf16 keeps f32 master weights and f32
loss/grad accumulation, so the trajectories should stay close).

Usage: python scripts/precision_bench.py [--n_train 12800] [--epochs 3]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compile cache: a tunnel-drop retry must not re-pay compiles
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")


def run_leg(precision: str, n_train: int, epochs: int, model: str):
    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    cfg = Config(
        debug=False,
        world_size=4,
        batch_size=512,
        learning_rate=0.01,
        epoch_size=epochs,
        dataset="cifar10",
        model=model,
        dynamic_batch_size=False,
        bucket=32,
        precision=precision,
    )
    bundle = load_dataset("cifar10", n_train=n_train, n_test=512)
    tr = Trainer(cfg, bundle=bundle, log_to_file=False)
    walls, losses = [], []
    for e in range(epochs):
        m = tr.run_epoch(e)
        walls.append(m["epoch_wall"])
        losses.append(m["loss"])
    out = {
        "precision": precision,
        "epoch_walls_s": [round(w, 4) for w in walls],
        "train_loss": [round(l, 5) for l in losses],
        "examples_per_s": tr.recorder.data.get("examples_per_s", [None])[-1],
        "mfu_bf16_peak": tr.recorder.data.get("mfu_bf16_peak", [None])[-1],
    }
    return out


def _r(v, nd=1):
    return round(v, nd) if isinstance(v, float) else v


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n_train", type=int, default=12800)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--model", default="densenet")
    ap.add_argument("--out_dir", default="artifacts")
    ns = ap.parse_args()

    # Backend init can wedge inside PJRT C++ when the TPU tunnel is down
    # (signals never fire there) — reuse bench.py's hard-exit watchdog so a
    # queued run fails fast instead of hanging.
    import bench

    done = bench._install_init_watchdog()
    import jax

    dev = jax.devices()[0]
    done.set()
    platform, kind = dev.platform, getattr(dev, "device_kind", "?")
    print(f"[precision_bench] {platform}/{kind}", flush=True)

    # Mid-run tunnel drops hang PJRT at 0% CPU; the engine heartbeats per
    # epoch/probe, so a stale heartbeat means a dead backend — fail fast.
    # TPU-only: the XLA CPU backend's fused whole-epoch scan can legitimately
    # compile for 30+ min with no heartbeat (see gen_statis STATIS_FORCE_
    # ELASTIC note), which would false-trigger the stall check.
    if platform != "cpu":
        from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
            arm_stall_watchdog,
        )

        arm_stall_watchdog(
            os.path.join(ns.out_dir, ".precision.hb"),
            float(os.environ.get("PRECISION_STALL_S", 1200)),
        )

    results = {}
    for prec in ("float32", "bfloat16"):
        t0 = time.time()
        results[prec] = run_leg(prec, ns.n_train, ns.epochs, ns.model)
        print(f"[precision_bench] {prec}: {results[prec]} ({time.time()-t0:.0f}s)",
              flush=True)

    os.makedirs(ns.out_dir, exist_ok=True)
    payload = {"platform": platform, "device_kind": kind,
               "model": ns.model, "n_train": ns.n_train, "results": results}
    with open(os.path.join(ns.out_dir, f"precision_bench_{platform}.json"), "w") as f:
        json.dump(payload, f, indent=2)

    f32, bf16 = results["float32"], results["bfloat16"]
    # steady wall = min past the compile epoch
    w32 = min(f32["epoch_walls_s"][1:]) if len(f32["epoch_walls_s"]) > 1 else None
    w16 = min(bf16["epoch_walls_s"][1:]) if len(bf16["epoch_walls_s"]) > 1 else None
    speedup = round(w32 / w16, 3) if w32 and w16 else None
    loss_delta = max(
        abs(a - b) for a, b in zip(f32["train_loss"], bf16["train_loss"])
    )
    md = [
        f"# Precision A/B — {platform} ({kind})",
        "",
        f"{ns.model} / cifar10(synthetic-ok), B=512, ws=4, clean fused leg,",
        f"n_train={ns.n_train}. bf16 = bfloat16 compute with f32 master",
        "weights and f32 loss/grad accumulation (the MXU's native dtype).",
        "",
        "| precision | steady epoch (s) | examples/s | MFU (bf16 peak) | final train loss |",
        "|---|---|---|---|---|",
        "| float32 | {} | {} | {} | {} |".format(
            w32, _r(f32["examples_per_s"]), _r(f32["mfu_bf16_peak"], 4),
            f32["train_loss"][-1],
        ),
        "| bfloat16 | {} | {} | {} | {} |".format(
            w16, _r(bf16["examples_per_s"]), _r(bf16["mfu_bf16_peak"], 4),
            bf16["train_loss"][-1],
        ),
        "",
        f"**bf16 speedup: {speedup}x**; max per-epoch train-loss delta "
        f"{loss_delta:.4f} (same data order, same seeds).",
        "",
        "Generated by `scripts/precision_bench.py`.",
    ]
    with open(os.path.join(ns.out_dir, "PRECISION.md"), "w") as f:
        f.write("\n".join(md) + "\n")
    print(f"[precision_bench] wrote {ns.out_dir}/PRECISION.md", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
