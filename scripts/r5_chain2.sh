#!/bin/bash
# Round-5 chain, part 2 — time-recovery handoff (written mid-round when
# c4's XLA:CPU compile hump blew the original schedule). Waits for c4's
# second arm to finish, takes over from r5_cpu_chain.sh (killed here; its
# remaining legs are re-run below with trimmed epoch counts), emits the
# unified table, and appends the done marker r5_tail.sh watches for.
# Trims vs part 1: c5/c2 at 4 epochs (was 6); c3 unchanged (north star).
# All legs sentinel-idempotent.
cd "$(dirname "$0")/.."
set -u
OUT=artifacts/acceptance_cpu_small_r5
C4OFF="$OUT/logs/regnet-cifar10-debug0-n8-bs256-lr0.0100-ep4-dbs0-ft0-ftc0.100000-node0-ocp1.done"

# Hard deadline (epoch seconds; default 11:00 UTC today): if c4 is hung in
# an XLA compile by then, proceed WITHOUT it — a missing c4 row is bounded
# damage, an unbounded wait loses c3/c5/c2 and the table too.
DEADLINE="${R5_C4_DEADLINE:-$(date -u -d 'today 11:00' +%s)}"
while [ ! -f "$C4OFF" ] && [ "$(date +%s)" -lt "$DEADLINE" ]; do sleep 60; done
[ -f "$C4OFF" ] || echo "[r5_chain2] c4 deadline passed without off-arm sentinel; proceeding without c4" >> /tmp/r5_chain.log
sleep 5
pkill -f "bash scripts/r5_cpu_chain.sh" 2>/dev/null
sleep 2
pkill -f "gen_statis.py --out_dir artifacts/acceptance_cpu_small_r5" 2>/dev/null
sleep 2

leg () {
  local desc="${@: -1}"
  echo "[r5_chain2] === $desc ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
  env "${@:1:$#-2}" bash scripts/host_job.sh \
    python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
  echo "[r5_chain2] $desc rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log
}

leg STATIS_CPU=1 STATIS_ONLY=c3_densenet STATIS_NTRAIN=2048 STATIS_EPOCHS=4 -- "c3 densenet 4ep"
leg STATIS_CPU=1 STATIS_ONLY=c5_transformer STATIS_LM_NTRAIN=120000 STATIS_EPOCHS=4 -- "c5 transformer 4ep"
leg STATIS_CPU=1 STATIS_ONLY=c2_resnet18 STATIS_NTRAIN=2048 STATIS_EPOCHS=4 STATIS_FORCE_ELASTIC=1 -- "c2 resnet18 4ep"

python scripts/summarize_statis.py "$OUT/statis" --markdown "$OUT/AB_TABLE.md" \
  >> /tmp/r5_chain.log 2>&1
{
  echo ""
  echo "Provenance: round-5 code ($(git rev-parse --short HEAD)), CPU tier"
  echo "(1-core box, 8-virtual-device mesh — the reference's gloo-on-localhost"
  echo "debug analogue), synthetic stand-in data (zero-egress env), seeds"
  echo "paired across arms (1234), walls exclude probe cost"
  echo "(wall_excludes_probes stamp). Scales: vision n_train=2048 (c4 B=256),"
  echo "LM 120k tokens. Epochs: c1=12, c3/c4=4, c2/c5=4."
} >> "$OUT/AB_TABLE.md"
echo "[r5_chain] done at $(date -u +%H:%M:%S)" >> /tmp/r5_chain.log
