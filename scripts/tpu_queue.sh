#!/bin/bash
# Wait for the TPU tunnel to recover, then run the queued TPU work:
#   1. scripts/kernel_bench.py  -> artifacts/kernel_bench_tpu.json + KERNELS.md
#   2. bench.py (full scale)    -> artifacts/BENCH_local_tpu.json
# Logs to /tmp/tpu_queue.log. Safe to kill at any point.
set -u
cd "$(dirname "$0")/.."
DEADLINE=$(( $(date +%s) + ${TPU_QUEUE_WAIT_S:-14400} ))

echo "[queue] waiting for TPU (deadline in ${TPU_QUEUE_WAIT_S:-14400}s)"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if PROBE_CAP_S=300 python scripts/tpu_probe_once.py 2>&1 | grep -q "PROBE ok"; then
    echo "[queue] TPU up at $(date -u +%H:%M:%S)"
    echo "[queue] === kernel_bench ==="
    timeout 2400 python scripts/kernel_bench.py --repeats 30 || echo "[queue] kernel_bench failed rc=$?"
    echo "[queue] === full bench ==="
    mkdir -p artifacts
    BENCH_TOTAL_BUDGET=${BENCH_TOTAL_BUDGET:-5400} timeout 6000 python bench.py \
      > artifacts/BENCH_local_tpu.json.tmp 2>/tmp/bench_full.log \
      || echo "[queue] bench failed rc=$?"
    grep -q '"backend": "tpu"' artifacts/BENCH_local_tpu.json.tmp 2>/dev/null \
      && mv artifacts/BENCH_local_tpu.json.tmp artifacts/BENCH_local_tpu.json
    echo "[queue] bench result: $(cat artifacts/BENCH_local_tpu.json 2>/dev/null | head -c 400)"
    echo "[queue] === acceptance statis (heavy CNN configs) ==="
    STATIS_ONLY=c2_resnet18,c3_densenet,c4_regnet_ws8 STATIS_WARM=true \
      timeout 7200 python scripts/gen_statis.py --out_dir artifacts/acceptance \
      >> /tmp/gen_statis_tpu.log 2>&1 \
      || echo "[queue] gen_statis failed rc=$?"
    echo "[queue] done"
    exit 0
  fi
  echo "[queue] TPU still down at $(date -u +%H:%M:%S); sleeping 120s"
  sleep 120
done
echo "[queue] gave up waiting for TPU"
exit 1
