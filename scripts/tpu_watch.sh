#!/bin/bash
# Loop backend-init probes; log to .tpu_watch.log; touch .tpu_up on success.
cd /root/repo
while true; do
  echo "[$(date +%H:%M:%S)] probing..." >> .tpu_watch.log
  if PROBE_CAP_S=2400 python scripts/tpu_probe_once.py >> .tpu_watch.log 2>&1; then
    date +%H:%M:%S > .tpu_up
    echo "[$(date +%H:%M:%S)] TPU UP" >> .tpu_watch.log
    sleep 600   # don't hammer claims while up; re-confirm every 10 min
  else
    sleep 120
  fi
done
