#!/usr/bin/env bash
# CI lint annotation: run the full graftlint pass (single-file G001-G010 +
# whole-program flow G011-G016, graftmesh G014-G016, graftrdzv G017-G019)
# and emit SARIF 2.1.0 so the CI can annotate PR diffs per-line (GitHub:
# upload with codeql-action/upload-sarif or any SARIF ingester; the region
# startLine/startColumn map straight onto diff positions).
#
# Usage:  scripts/lint_sarif.sh [output.sarif]
#
# GRAFTLINT_CACHE_DIR, when set, pins the content-hash cache directory —
# the tier-1 gate (tests/test_lint_clean.py) runs this script hermetically
# against a tmp cache; CI jobs can point it at a restored cache volume so
# the warm pass stays inside the flow-budget envelope.
#
# Exit status is graftlint's own: 0 clean, 1 findings (fail the check),
# 2 usage/parse errors — so the step can gate merges directly. There is
# deliberately NO baseline file: every finding fails the gate.
set -u
cd "$(dirname "$0")/.."
OUT="${1:-artifacts/lint.sarif}"
mkdir -p "$(dirname "$OUT")"
CACHE_ARGS=()
if [ -n "${GRAFTLINT_CACHE_DIR:-}" ]; then
    CACHE_ARGS=(--cache-dir "$GRAFTLINT_CACHE_DIR")
fi
python -m dynamic_load_balance_distributeddnn_tpu.analysis.cli \
    --flow --format sarif "${CACHE_ARGS[@]}" \
    dynamic_load_balance_distributeddnn_tpu bench.py > "$OUT"
rc=$?
count=$(python - "$OUT" <<'EOF'
import json, sys
with open(sys.argv[1]) as fh:
    sarif = json.load(fh)
print(sum(len(r.get("results", [])) for r in sarif.get("runs", [])))
EOF
)
echo "graftlint: $count finding(s) -> $OUT (exit $rc)" >&2
exit "$rc"
