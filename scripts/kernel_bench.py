#!/usr/bin/env python
"""Pallas-vs-XLA kernel microbenchmark on the real chip (VERDICT #3).

For each custom kernel (ops/pallas/: flash attention, fused GroupNorm, fused
softmax-xent) and each shape the model zoo actually uses — plus the
long-sequence shapes ring attention targets — time the jitted forward and
forward+grad against the plain-XLA equivalent the kernel would replace
(the reference delegates these to cuDNN, SURVEY §2.2; here the alternative
is stock XLA fusion).

Writes artifacts/kernel_bench_<platform>.json and a markdown table to
artifacts/KERNELS.md. The use_pallas / use_flash_attention config defaults
are chosen from (and justified by) this table.

Usage: python scripts/kernel_bench.py [--repeats 30] [--quick]
"""

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compile cache: a tunnel-drop retry must not re-pay compiles
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")

import jax
import jax.numpy as jnp

from dynamic_load_balance_distributeddnn_tpu.ops.losses import per_example_cross_entropy
from dynamic_load_balance_distributeddnn_tpu.ops.pallas.flash_attention import flash_attention
from dynamic_load_balance_distributeddnn_tpu.ops.pallas.groupnorm import fused_group_norm
from dynamic_load_balance_distributeddnn_tpu.ops.pallas.xent import fused_softmax_xent


def timeit(fn, *args, repeats=30):
    """Median wall of a jitted call, post-warmup, fully fenced."""
    out = fn(*args)
    jax.block_until_ready(out)
    walls = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        walls.append(time.perf_counter() - t0)
    return statistics.median(walls)


# ------------------------------------------------------------ XLA baselines


def xla_attention(q, k, v, causal):
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, jnp.finfo(s.dtype).min)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, axis=-1), v)


def xla_group_norm(x, scale, bias, groups, eps=1e-6):
    shape = x.shape
    c = shape[-1]
    xg = x.reshape(shape[0], -1, groups, c // groups).astype(jnp.float32)
    mean = xg.mean(axis=(1, 3), keepdims=True)
    var = ((xg - mean) ** 2).mean(axis=(1, 3), keepdims=True)
    y = (xg - mean) / jnp.sqrt(var + eps)
    y = y.reshape(shape[0], -1, c) * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.reshape(shape).astype(x.dtype)


# ------------------------------------------------------------ benchmark legs


def bench_attention(results, dtype, repeats, quick):
    """LM shapes: the reference transformer is T=35 bptt, 2 heads, d=100
    (dbs.py:337-343); ring/long-context targets go to 4k."""
    shapes = [(40, 2, 64, 128), (8, 2, 512, 128), (4, 4, 2048, 128)]
    if not quick:
        shapes.append((2, 4, 4096, 128))
    for b, h, t, d in shapes:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (b, h, t, d), dtype)
        k = jax.random.normal(kk, (b, h, t, d), dtype)
        v = jax.random.normal(kv, (b, h, t, d), dtype)

        for causal in (True,):
            pall = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=False))
            base = jax.jit(lambda q, k, v: xla_attention(q, k, v, causal))
            pall_g = jax.jit(jax.grad(lambda q, k, v: flash_attention(q, k, v, causal=causal, interpret=False).sum(), argnums=(0, 1, 2)))
            base_g = jax.jit(jax.grad(lambda q, k, v: xla_attention(q, k, v, causal).sum(), argnums=(0, 1, 2)))
            row = {
                "kernel": "flash_attention",
                "shape": f"B{b}xH{h}xT{t}xD{d}",
                "dtype": str(dtype.__name__),
                "causal": causal,
            }
            try:
                row["fwd_pallas_ms"] = timeit(pall, q, k, v, repeats=repeats) * 1e3
                row["fwd_xla_ms"] = timeit(base, q, k, v, repeats=repeats) * 1e3
                row["grad_pallas_ms"] = timeit(pall_g, q, k, v, repeats=repeats) * 1e3
                row["grad_xla_ms"] = timeit(base_g, q, k, v, repeats=repeats) * 1e3
            except Exception as e:  # a kernel that won't lower is a result, not a crash
                row["error"] = f"{type(e).__name__}: {e}"[:300]
            results.append(row)
            print(json.dumps(row), flush=True)


def bench_groupnorm(results, dtype, repeats, quick):
    """CNN shapes: 32x32 CIFAR maps through the zoo's widths, GroupNorm(32)
    (Net/Resnet.py:11-13); batch = per-worker 128 of the B=512/ws=4 recipe."""
    shapes = [(128, 32, 32, 64), (128, 16, 16, 256), (128, 8, 8, 512)]
    if not quick:
        shapes.append((256, 32, 32, 128))
    for b, hh, ww, c in shapes:
        groups = 32
        kx, ks = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(kx, (b, hh, ww, c), dtype)
        scale = jax.random.normal(ks, (c,), jnp.float32)
        bias = jnp.zeros((c,), jnp.float32)

        # plain GN, and the GN->relu pair every CNN block actually runs
        # (models/*: nn.relu(group_norm(...))) with the kernel's fused
        # relu epilogue vs XLA fusing the pair itself
        variants = [
            ("fused_group_norm",
             lambda x, s, b_: fused_group_norm(x, s, b_, groups, interpret=False),
             lambda x, s, b_: xla_group_norm(x, s, b_, groups)),
            ("fused_group_norm_relu",
             # bare kernel call, as the models run it (group_norm(relu=True)
             # with NO outer relu — an outer relu over the custom call would
             # re-add the elementwise pass the epilogue removes)
             lambda x, s, b_: fused_group_norm(
                 x, s, b_, groups, interpret=False, relu=True
             ),
             lambda x, s, b_: jax.nn.relu(xla_group_norm(x, s, b_, groups))),
        ]
        for kname, pfn, bfn in variants:
            pall = jax.jit(pfn)
            base = jax.jit(bfn)
            pall_g = jax.jit(jax.grad(lambda x, s, b_: pfn(x, s, b_).sum(), argnums=(0, 1, 2)))
            base_g = jax.jit(jax.grad(lambda x, s, b_: bfn(x, s, b_).sum(), argnums=(0, 1, 2)))
            row = {
                "kernel": kname,
                "shape": f"B{b}x{hh}x{ww}xC{c}/g{groups}",
                "dtype": str(dtype.__name__),
            }
            try:
                row["fwd_pallas_ms"] = timeit(pall, x, scale, bias, repeats=repeats) * 1e3
                row["fwd_xla_ms"] = timeit(base, x, scale, bias, repeats=repeats) * 1e3
                row["grad_pallas_ms"] = timeit(pall_g, x, scale, bias, repeats=repeats) * 1e3
                row["grad_xla_ms"] = timeit(base_g, x, scale, bias, repeats=repeats) * 1e3
            except Exception as e:
                row["error"] = f"{type(e).__name__}: {e}"[:300]
            results.append(row)
            print(json.dumps(row), flush=True)


def bench_xent(results, dtype, repeats, quick):
    """Loss shapes: CIFAR [B,10/100] and the LM's [B*bptt, V=33278]
    (dbs.py:270, 337)."""
    shapes = [(512, 10), (512, 100), (700, 33278)]
    if not quick:
        shapes.append((2800, 33278))
    for r, v in shapes:
        kx, kl = jax.random.split(jax.random.PRNGKey(2))
        logits = jax.random.normal(kx, (r, v), dtype)
        labels = jax.random.randint(kl, (r,), 0, v)

        pall = jax.jit(lambda lg, lb: fused_softmax_xent(lg, lb, interpret=False).sum())
        base = jax.jit(lambda lg, lb: per_example_cross_entropy(lg, lb).sum())
        pall_g = jax.jit(jax.grad(lambda lg, lb: fused_softmax_xent(lg, lb, interpret=False).sum(), argnums=0))
        base_g = jax.jit(jax.grad(lambda lg, lb: per_example_cross_entropy(lg, lb).sum(), argnums=0))
        row = {"kernel": "fused_softmax_xent", "shape": f"R{r}xV{v}", "dtype": str(dtype.__name__)}
        try:
            row["fwd_pallas_ms"] = timeit(pall, logits, labels, repeats=repeats) * 1e3
            row["fwd_xla_ms"] = timeit(base, logits, labels, repeats=repeats) * 1e3
            row["grad_pallas_ms"] = timeit(pall_g, logits, labels, repeats=repeats) * 1e3
            row["grad_xla_ms"] = timeit(base_g, logits, labels, repeats=repeats) * 1e3
        except Exception as e:
            row["error"] = f"{type(e).__name__}: {e}"[:300]
        results.append(row)
        print(json.dumps(row), flush=True)


def to_markdown(results, platform, kind):
    lines = [
        f"# Kernel microbenchmarks — {platform} ({kind})",
        "",
        "Median jitted wall (ms), post-warmup, `block_until_ready`-fenced.",
        "`speedup` = XLA / Pallas (>1 means the Pallas kernel wins).",
        "Generated by `scripts/kernel_bench.py`.",
        "",
        "| kernel | shape | dtype | fwd pallas | fwd xla | fwd speedup | grad pallas | grad xla | grad speedup |",
        "|---|---|---|---|---|---|---|---|---|",
    ]
    for r in results:
        if "error" in r:
            lines.append(
                f"| {r['kernel']} | {r['shape']} | {r['dtype']} | ERROR: {r['error'][:80]} | | | | | |"
            )
            continue
        fs = r["fwd_xla_ms"] / r["fwd_pallas_ms"]
        gs = r["grad_xla_ms"] / r["grad_pallas_ms"]
        lines.append(
            f"| {r['kernel']} | {r['shape']} | {r['dtype']} "
            f"| {r['fwd_pallas_ms']:.3f} | {r['fwd_xla_ms']:.3f} | {fs:.2f}x "
            f"| {r['grad_pallas_ms']:.3f} | {r['grad_xla_ms']:.3f} | {gs:.2f}x |"
        )
    return "\n".join(lines) + "\n"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--repeats", type=int, default=30)
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--dtype", default="bfloat16", choices=["bfloat16", "float32"])
    ap.add_argument("--out_dir", default="artifacts")
    ns = ap.parse_args()

    dev = jax.devices()[0]
    platform = dev.platform
    kind = getattr(dev, "device_kind", "?")
    print(f"[kernel_bench] {platform}/{kind}", flush=True)
    dtype = jnp.bfloat16 if ns.dtype == "bfloat16" else jnp.float32

    os.makedirs(ns.out_dir, exist_ok=True)
    json_path = os.path.join(ns.out_dir, f"kernel_bench_{platform}.json")

    # Tunnel-drop armor: rows persist incrementally to json_path; if no row
    # lands for KB_STALL_S the backend is hung — exit so the queue retries.
    from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
        arm_stall_watchdog,
    )

    arm_stall_watchdog(
        json_path + ".hb",
        float(os.environ.get("KB_STALL_S", 900)),
        extra_paths=(json_path,),
    )

    class _IncrementalResults(list):
        """Persist after every row — a runtime outage mid-bench (the TPU
        tunnel can drop) must not lose completed measurements."""

        def append(self, row):
            super().append(row)
            payload = {
                "platform": platform,
                "device_kind": kind,
                "dtype": ns.dtype,
                "results": list(self),
            }
            tmp = json_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=2)
            os.replace(tmp, json_path)
            with open(os.path.join(ns.out_dir, "KERNELS.md"), "w") as f:
                f.write(to_markdown(self, platform, kind))

    results = _IncrementalResults()
    bench_attention(results, dtype, ns.repeats, ns.quick)
    bench_groupnorm(results, dtype, ns.repeats, ns.quick)
    bench_xent(results, dtype, ns.repeats, ns.quick)
    print(f"[kernel_bench] wrote {json_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
