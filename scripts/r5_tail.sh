#!/bin/bash
# Round-5 tail: runs after r5_cpu_chain.sh finishes (watches its log for
# the done marker). Two legs:
#   1. second-seed c1 pair (seed 4321, 12 ep) — quantifies the cross-seed
#      noise band behind the accuracy-parity tolerance (VERDICT r4 #6).
#      Config filenames don't encode the seed, so the pair gets its own
#      subdir (seed4321/) to keep sentinels and artifacts distinct.
#   2. CPU-insurance bench with round-5 code (the r4 protocol:
#      reduced-scale DenseNet A/B on the CPU mesh, partials promoted on
#      success only).
cd "$(dirname "$0")/.."
set -u

while ! grep -q "\[r5_chain\] done" /tmp/r5_chain.log 2>/dev/null; do
  sleep 60
done

OUT=artifacts/acceptance_cpu_small_r5
# gen_statis nests per-seed (out_dir/seed4321) so the pair can't collide
# with the seed-1234 matrix
STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 \
  STATIS_SEED=4321 bash scripts/host_job.sh \
  python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_tail.log 2>&1
python scripts/summarize_statis.py "$OUT/seed4321/statis" >> /tmp/r5_tail.log 2>&1

BENCH_FORCE_CPU=1 BENCH_CPU_NTRAIN=2048 BENCH_EPOCHS=7 \
  BENCH_PARTIAL_PATH=artifacts/.bench_partial_cpu_r5.json \
  BENCH_TOTAL_BUDGET=2400 \
  bash scripts/host_job.sh sh -c \
  'python bench.py > artifacts/.BENCH_cpu_insurance_r5.tmp 2>/tmp/bench_r5_cpu.log \
     && mv artifacts/.BENCH_cpu_insurance_r5.tmp artifacts/BENCH_cpu_insurance_r5.json' \
  >> /tmp/r5_tail.log 2>&1

echo "[r5_tail] done at $(date -u +%H:%M:%S)" >> /tmp/r5_tail.log
