"""One backend-init attempt; prints one status line. Used by the watcher."""
import os, sys, time, threading
t0 = time.time()
cap = float(os.environ.get("PROBE_CAP_S", "1800"))
def watchdog():
    time.sleep(cap)
    print(f"PROBE timeout after {cap:.0f}s", flush=True)
    os._exit(17)
threading.Thread(target=watchdog, daemon=True).start()
import jax
try:
    ds = jax.devices()
    import jax.numpy as jnp
    jax.block_until_ready(jnp.ones((128,128), jnp.bfloat16) @ jnp.ones((128,128), jnp.bfloat16))
    print(f"PROBE ok in {time.time()-t0:.0f}s: {ds[0].platform}/{getattr(ds[0],'device_kind','?')} n={len(ds)}", flush=True)
except Exception as e:
    print(f"PROBE fail after {time.time()-t0:.0f}s: {type(e).__name__}: {e}", flush=True)
    sys.exit(1)
