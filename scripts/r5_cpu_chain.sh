#!/bin/bash
# Round-5 CPU-tier measurement chain (VERDICT r4 next #2/#3/#6/#7):
# regenerate the FULL 5-config acceptance matrix with round-5 code in ONE
# directory — no more cross-round archaeology. Ordered by marginal value so
# an interrupted chain still lands the important rows first:
#   1. c1  mnistnet ws2 [3,1]        12 ep  (parity anchor, ~3 min)
#   2. c4  RegNetY-400MF ws8 [3,2,1×6] 4 ep (FIRST-EVER RegNet acceptance
#          row — unblocked by the grouped-conv decomposition)
#   3. c3  DenseNet-121 ws4 [3,1,1,1]  4 ep (north-star config)
#   4. c5  Transformer LM ws4 [3,1,1,1] 6 ep (re-measured under the r4
#          probe-wall fix; LM probe accounting)
#   5. c2  ResNet-18 ws4 balanced      6 ep (elastic topology on CPU)
# then emits ONE AB_TABLE.md for the whole matrix with provenance.
#
# Every leg runs under host_job.sh so the TPU queue's on-chip legs pause it
# (.tpu_busy) instead of getting poisoned by host contention. All legs are
# sentinel-idempotent: rerunning the chain resumes where it stopped.
cd "$(dirname "$0")/.."
set -u
OUT=artifacts/acceptance_cpu_small_r5
mkdir -p "$OUT"

leg () {  # leg <env...> -- <desc>
  local desc="${@: -1}"
  echo "[r5_chain] === $desc ($(date -u +%H:%M:%S)) ===" >> /tmp/r5_chain.log
  env "${@:1:$#-2}" bash scripts/host_job.sh \
    python scripts/gen_statis.py --out_dir "$OUT" >> /tmp/r5_chain.log 2>&1
  echo "[r5_chain] $desc rc=$? ($(date -u +%H:%M:%S))" >> /tmp/r5_chain.log
}

leg STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 -- "c1 mnistnet 12ep"
leg STATIS_CPU=1 STATIS_ONLY=c4_regnet_ws8 STATIS_NTRAIN=2048 STATIS_EPOCHS=4 STATIS_VISION_B=256 -- "c4 regnet ws8 4ep"
leg STATIS_CPU=1 STATIS_ONLY=c3_densenet STATIS_NTRAIN=2048 STATIS_EPOCHS=4 -- "c3 densenet 4ep"
leg STATIS_CPU=1 STATIS_ONLY=c5_transformer STATIS_LM_NTRAIN=120000 STATIS_EPOCHS=6 -- "c5 transformer 6ep"
leg STATIS_CPU=1 STATIS_ONLY=c2_resnet18 STATIS_NTRAIN=2048 STATIS_EPOCHS=6 STATIS_FORCE_ELASTIC=1 -- "c2 resnet18 6ep"

python scripts/summarize_statis.py "$OUT/statis" --markdown "$OUT/AB_TABLE.md" \
  >> /tmp/r5_chain.log 2>&1
{
  echo ""
  echo "Provenance: round-5 code ($(git rev-parse --short HEAD)), CPU tier"
  echo "(1-core box, 8-virtual-device mesh — the reference's gloo-on-localhost"
  echo "debug analogue), synthetic stand-in data (zero-egress env), seeds"
  echo "paired across arms (1234), walls exclude probe cost"
  echo "(wall_excludes_probes stamp). Scales: vision n_train=2048 (c4 B=256),"
  echo "LM 120k tokens. Epochs: c1=12, c3/c4=4, c2/c5=6."
} >> "$OUT/AB_TABLE.md"
echo "[r5_chain] done at $(date -u +%H:%M:%S)" >> /tmp/r5_chain.log
