#!/usr/bin/env python
"""Clean-leg MFU attribution: where does the DenseNet epoch time go?

The round-2 bench measured clean_mfu_bf16_peak = 1.36% on the real chip
(artifacts/BENCH_local_tpu.json) without ever attributing the idle time.
This probe isolates each layer of the stack on the same clean leg
(DenseNet-121 / cifar10-shaped data / B=512 / bf16):

A. step-compute ceiling — the compiled fused step on device-resident
   data, per-call blocking, min over reps: pure device step time.
B. pipelined rate — N async dispatches, block once: what the scan can
   sustain; if B ~= A the device is saturated, dispatch is hidden.
C. epoch wall — Trainer.run_epoch on the same config: adds host feed,
   plan build, readback. C vs A*steps is the host-side overhead.
D. batch sweep — step time at several widths: fixed overhead vs MXU
   saturation knee (is the chip starved by small per-step work?).
E. matmul roofline — a big bf16 matmul timed the same way: what fraction
   of the chip's paper peak this tunnel-attached chip actually delivers.
F. profiler trace over a few steps, parsed via tensorboard_plugin_profile
   (present in this image) -> device busy fraction + top self-time ops.

Writes artifacts/MFU_PROBE.json incrementally (each section lands as it
completes, so a tunnel drop mid-run still leaves the earlier sections).

Usage: python scripts/mfu_probe.py [--cpu] [--quick]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "./.jax_cache")

OUT = os.path.join("artifacts", "MFU_PROBE.json")
RESULT: dict = {"sections": {}}


def _save() -> None:
    os.makedirs("artifacts", exist_ok=True)
    tmp = OUT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(RESULT, f, indent=1)
    os.replace(tmp, OUT)


def _install_watchdog(cap_s: float):
    import threading

    def _fire():
        sys.stderr.write(f"[mfu_probe] init watchdog fired after {cap_s}s\n")
        os._exit(17)

    t = threading.Timer(cap_s, _fire)
    t.daemon = True
    t.start()
    return t


def main() -> int:
    if "--parse-xplane" in sys.argv:
        path = sys.argv[sys.argv.index("--parse-xplane") + 1]
        print(json.dumps(_parse_xplane(path)))
        return 0
    force_cpu = "--cpu" in sys.argv
    quick = "--quick" in sys.argv
    if force_cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=1").strip()
    wd = _install_watchdog(float(os.environ.get("MFU_INIT_CAP_S", 1800)))
    import jax

    from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
        arm_stall_watchdog,
        heartbeat,
    )

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    wd.cancel()
    # Tunnel-drop armor, armed AFTER backend init so MFU_INIT_CAP_S keeps
    # sole authority over the init window. TPU-only: CPU fused-scan compiles
    # can out-wait any reasonable stall cap without a heartbeat.
    if devs[0].platform != "cpu":
        arm_stall_watchdog(
            OUT + ".hb",
            float(os.environ.get("MFU_STALL_S", 1200)),
            extra_paths=(OUT,),
        )
    import jax.numpy as jnp
    import numpy as np

    dev = devs[0]
    RESULT["platform"] = dev.platform
    RESULT["device_kind"] = getattr(dev, "device_kind", "?")
    RESULT["n_devices"] = len(devs)
    _save()

    from dynamic_load_balance_distributeddnn_tpu.config import Config
    from dynamic_load_balance_distributeddnn_tpu.data import load_dataset
    from dynamic_load_balance_distributeddnn_tpu.obs.flops import (
        chip_peak_flops,
        compiled_flops,
    )
    from dynamic_load_balance_distributeddnn_tpu.train import Trainer

    peak = chip_peak_flops() or float("nan")
    peak_ok = peak == peak
    RESULT["bf16_peak_flops_per_dev"] = peak if peak_ok else None

    # ---- E first: matmul roofline (cheap, and meaningful even if the rest
    # of the probe dies with the tunnel) ----
    def timed_min(fn, *args, reps=5):
        jax.block_until_ready(fn(*args))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(*args))
            best = min(best, time.perf_counter() - t0)
        heartbeat()
        return best

    n = 4096 if not quick else 1024
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.ones((n, n), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    t_mm = timed_min(mm, a, b)
    mm_flops = 2 * n**3
    RESULT["sections"]["matmul_roofline"] = {
        "n": n,
        "time_s": t_mm,
        "tflops": mm_flops / t_mm / 1e12,
        "frac_of_peak": (mm_flops / t_mm) / peak if peak_ok else None,
    }
    _save()

    # ---- Trainer on the clean leg ----
    n_train = int(os.environ.get("MFU_NTRAIN", 2048 if quick else 12800))
    model = os.environ.get("MFU_MODEL", "mnistnet" if force_cpu else "densenet")
    dataset = "mnist" if force_cpu else "cifar10"
    cfg = Config(
        debug=False,
        world_size=int(os.environ.get("MFU_WS", 4)),
        batch_size=512,
        learning_rate=0.01,
        epoch_size=2,
        dataset=dataset,
        model=model,
        dynamic_batch_size=False,
        fault_tolerance=False,
        bucket=32,
        precision="bfloat16",
    )
    bundle = load_dataset(dataset, n_train=n_train, n_test=512)
    tr = Trainer(cfg, bundle=bundle, log_to_file=False)
    RESULT["model"] = model
    RESULT["n_train"] = n_train

    # The clean leg on one chip runs the packed path: per-step global batch =
    # B + ws*bucket rows on a 1-device mesh. Build the same step shape here.
    n_dev = tr.n_dev
    h, w_, c = bundle.train_x.shape[1:]

    def step_inputs(b_total: int):
        x = jnp.asarray(np.random.RandomState(0).randint(0, 255, (b_total, h, w_, c)).astype(bundle.train_x.dtype))
        y = jnp.zeros((b_total,), jnp.int32)
        w = jnp.full((b_total,), 1.0 / b_total, jnp.float32)
        slow = jnp.zeros((n_dev,), jnp.int32)
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import batch_sharding

        x = jax.device_put(x, batch_sharding(tr.mesh, x.ndim))
        y = jax.device_put(y, batch_sharding(tr.mesh, 1))
        w = jax.device_put(w, batch_sharding(tr.mesh, 1))
        slow = jax.device_put(slow, batch_sharding(tr.mesh, 1))
        return x, y, w, slow, jnp.int32(7)

    # ---- A + B at the bench's step width ----
    b_bench = tr._cap_packed if n_dev == 1 else cfg.batch_size
    args = step_inputs(b_bench)
    state = tr.state
    probe = tr.steps.fused_step_probe
    t_block = timed_min(probe, state, *args, reps=5)
    f = compiled_flops(probe, state, *args) or float("nan")
    # pipelined: N dispatches, block once
    n_pipe = 20 if not quick else 5
    jax.block_until_ready(probe(state, *args))
    t0 = time.perf_counter()
    out = None
    for _ in range(n_pipe):
        out = probe(state, *args)
    jax.block_until_ready(out)
    t_pipe = (time.perf_counter() - t0) / n_pipe
    RESULT["sections"]["step"] = {
        "global_batch": b_bench,
        "blocking_step_s": t_block,
        "pipelined_step_s": t_pipe,
        "flops_per_step": f if f == f else None,
        "step_mfu_blocking": (f / t_block) / (peak * n_dev) if f == f and peak_ok else None,
        "step_mfu_pipelined": (f / t_pipe) / (peak * n_dev) if f == f and peak_ok else None,
        "examples_per_s_pipelined": b_bench / t_pipe,
    }
    _save()

    # ---- C: epoch wall through the Trainer (same path the bench times) ----
    walls = []
    for e in range(2):
        walls.append(tr.run_epoch(e)["epoch_wall"])
    steps_per_epoch = max(n_train // cfg.batch_size, 1)
    rec = tr.recorder.data
    RESULT["sections"]["epoch"] = {
        "walls_s": walls,
        "steps_per_epoch": steps_per_epoch,
        "device_time_est_s": t_pipe * steps_per_epoch,
        "host_overhead_s": min(walls) - t_pipe * steps_per_epoch,
        "examples_per_s": rec.get("examples_per_s", [None])[-1],
        "mfu_bf16_peak": rec.get("mfu_bf16_peak", [None])[-1],
    }
    _save()

    # ---- D: batch sweep ----
    # run_epoch donated the old state buffers (fused_epoch donate_argnums);
    # re-fetch the live state before reusing it
    state = tr.state
    args = step_inputs(b_bench)
    sweep = RESULT["sections"]["batch_sweep"] = {}
    for b_total in ([256, 512] if quick else [128, 256, 512, 1024, 2048]):
        if b_total % n_dev:
            continue
        try:
            argv = step_inputs(b_total)
            t = timed_min(probe, state, *argv, reps=3)
            fb = compiled_flops(probe, state, *argv) or float("nan")
            sweep[str(b_total)] = {
                "blocking_step_s": t,
                "examples_per_s": b_total / t,
                "step_mfu": (fb / t) / (peak * n_dev) if fb == fb and peak_ok else None,
            }
        except Exception as e:  # OOM at the top widths is a finding, not a crash
            sweep[str(b_total)] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _save()

    # ---- F: profiler trace, parsed for busy fraction + top ops ----
    try:
        import glob
        import tempfile

        trace_dir = tempfile.mkdtemp(prefix="mfu_trace_")
        jax.profiler.start_trace(trace_dir)
        out = None
        for _ in range(5):
            out = probe(state, *args)
        jax.block_until_ready(out)
        jax.profiler.stop_trace()
        section = {"trace_dir": trace_dir}
        xspaces = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True)
        if xspaces:
            # the plugin's protos clash with the already-imported protobuf
            # gencode; parse in a subprocess forced onto the python impl
            import subprocess

            env = dict(os.environ, PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION="python")
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--parse-xplane", xspaces[0]],
                capture_output=True,
                text=True,
                timeout=600,
                env=env,
            )
            try:
                section.update(json.loads(proc.stdout))
            except Exception:
                section["parse_error"] = (proc.stderr or proc.stdout)[-500:]
        RESULT["sections"]["trace"] = section
    except Exception as e:
        RESULT["sections"]["trace"] = {"error": f"{type(e).__name__}: {e}"[:500]}
    _save()
    print(json.dumps(RESULT["sections"].get("step", {})))
    return 0


def _parse_xplane(path: str) -> dict:
    """Device busy fraction + top ops from a raw xplane proto, parsed
    directly with TF's bundled xplane proto (the tensorboard profile
    plugin in this image mismatches its TF; hand-rolling the two numbers
    we need is smaller than fixing that)."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore

    space = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        space.ParseFromString(f.read())

    out: dict = {"planes": []}
    for plane in space.planes:
        is_device = any(
            k in plane.name for k in ("TPU", "/device", "GPU")
        ) and "Host" not in plane.name
        stats = {"name": plane.name, "lines": len(plane.lines)}
        if not plane.lines:
            out["planes"].append(stats)
            continue
        ev_meta = {m.id: m.name for m in plane.event_metadata.values()}
        # busy time: union of event intervals across the plane's op lines;
        # top ops: summed duration by op name (self time approximated by
        # taking only the innermost "XLA Ops"-style line per plane)
        best_line = None
        for line in plane.lines:
            if best_line is None or len(line.events) > len(best_line.events):
                best_line = line
        intervals = []
        by_op: dict = {}
        for line in plane.lines:
            for ev in line.events:
                t0 = line.timestamp_ns + ev.offset_ps // 1000
                intervals.append((t0, t0 + ev.duration_ps // 1000))
        for ev in best_line.events:
            name = ev_meta.get(ev.metadata_id, str(ev.metadata_id))
            by_op[name] = by_op.get(name, 0) + ev.duration_ps / 1e12
        intervals.sort()
        busy_ns = 0
        span_lo = intervals[0][0] if intervals else 0
        span_hi = span_lo
        cur_lo, cur_hi = None, None
        for lo, hi in intervals:
            span_hi = max(span_hi, hi)
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    busy_ns += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            busy_ns += cur_hi - cur_lo
        span_ns = max(span_hi - span_lo, 1)
        stats.update(
            {
                "span_s": span_ns / 1e9,
                "busy_s": busy_ns / 1e9,
                "busy_frac": busy_ns / span_ns,
                "is_device": is_device,
                "top_ops_s": dict(
                    sorted(by_op.items(), key=lambda kv: -kv[1])[:25]
                ),
            }
        )
        out["planes"].append(stats)
    return out


if __name__ == "__main__":
    sys.exit(main())
