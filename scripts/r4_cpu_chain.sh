#!/bin/bash
# Round-4 CPU-tier measurement chain (runs while the TPU queue waits for
# the tunnel; host_job.sh pauses it during on-chip legs):
#   1. wait for the already-running c4 RegNet ws=8 A/B to finish
#   2. c1 accuracy-parity leg: 12-epoch fixed-seed paired mnistnet A/B
#      (VERDICT r3 next #5 — enough epochs that dbs-on/off accuracy
#      converges within noise)
#   3. fresh CPU-insurance bench with round-4 code (probe cost now out of
#      the walls — VERDICT r3 weak #7's IQR check)
cd "$(dirname "$0")/.."
set -u

# 1. wait for any running c4 gen_statis
while pgrep -f "gen_statis.py --out_dir artifacts/acceptance_cpu_small_r4" > /dev/null; do
  sleep 30
done

# 2. c1 parity (12 epochs); sentinel-idempotent
STATIS_CPU=1 STATIS_ONLY=c1_mnistnet STATIS_NTRAIN=2048 STATIS_EPOCHS=12 \
  bash scripts/host_job.sh python scripts/gen_statis.py \
  --out_dir artifacts/acceptance_cpu_small_r4 >> /tmp/c1_parity.log 2>&1

# 3. round-4 CPU insurance bench (standard insurance scale); write to a
#    temp path and promote on success so an interrupted run can never
#    truncate the committed artifact
BENCH_FORCE_CPU=1 BENCH_CPU_NTRAIN=2048 BENCH_EPOCHS=7 \
  BENCH_PARTIAL_PATH=artifacts/.bench_partial_cpu_r4.json \
  BENCH_TOTAL_BUDGET=2400 \
  bash scripts/host_job.sh sh -c \
  'python bench.py > artifacts/.BENCH_cpu_insurance_r4.tmp 2>/tmp/bench_r4_cpu.log \
     && mv artifacts/.BENCH_cpu_insurance_r4.tmp artifacts/BENCH_cpu_insurance_r4.json' \
  >> /tmp/bench_r4_cpu_outer.log 2>&1

echo "[r4_chain] done at $(date -u +%H:%M:%S)"
