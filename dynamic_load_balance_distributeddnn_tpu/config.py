"""Run configuration.

Mirrors the reference CLI surface (parser.py:40-80 — 13 flags with the same
short names, defaults, and coercion rules) and adds TPU-specific knobs that
have no reference counterpart (bucketing, capacity headroom, fault-injection
mode, precision). The reference parses at module import into globals
(dbs.py:22, 32-44); here everything lives in one frozen dataclass that is
passed explicitly.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import List, Optional, Sequence

# Family-default names mirror the reference switch (dbs.py:345-362); explicit
# variants expose the full Net/ constructor surface (e.g. ResNet-18 for
# BASELINE acceptance config #2).
MODELS = [
    "mnistnet",
    "resnet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "densenet", "densenet121", "densenet169", "densenet201", "densenet161",
    "googlenet",
    "regnet", "regnetx200mf", "regnetx400mf", "regnety400mf",
    "transformer",
]
DATASETS = ["cifar10", "cifar100", "mnist", "wikitext2"]


def str2bool(v) -> bool:
    """Boolean coercion with the reference's accepted spellings (parser.py:8-16)."""
    if isinstance(v, bool):
        return v
    if v.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if v.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError("Boolean value expected.")


def _env_int(name: str, default: int) -> int:
    """Integer from the environment with a diagnosable failure: argparse's
    type= only validates CLI-passed values, so an env-driven DEFAULT that
    fails int() would otherwise kill parser construction with a contextless
    ValueError. Empty/whitespace counts as unset."""
    v = os.environ.get(name, "").strip()
    if not v:
        return default
    try:
        return int(v)
    except ValueError:
        raise SystemExit(f"env var {name} must be an integer, got {v!r}")


def device_map(v):
    """Worker→device map: a single device ordinal or a comma list, one entry
    per worker (the analogue of the reference's `-gpu 0,0,0,1`, parser.py:19-25).
    """
    if isinstance(v, int):
        return v
    if isinstance(v, (list, tuple)):
        return [int(g) for g in v]
    if "," in v:
        return [int(g) for g in v.split(",")]
    return int(v)


@dataclasses.dataclass(frozen=True)
class Config:
    # ---- reference-parity flags (parser.py:40-80) ----
    debug: bool = True                 # -d: tiny CPU-friendly smoke mode
    world_size: int = 4                # -ws: number of logical workers
    batch_size: int = 64               # -b: global batch size
    learning_rate: float = 0.01        # -lr
    epoch_size: int = 10               # -e
    dataset: str = "wikitext2"         # -ds
    dynamic_batch_size: bool = True    # -dbs: the DBS balancer on/off
    device: object = None              # -gpu analogue: worker→device map;
                                       # None = round-robin over all devices
    model: str = "transformer"         # -m
    fault_tolerance: bool = False      # -ft: straggler injection on/off
    fault_tolerance_chance: float = 0.1  # -ftc
    one_cycle_policy: bool = False     # -ocp
    disable_enhancements: bool = False  # -de: uniform grad weights + no OCP

    # ---- TPU-native knobs (new in this framework) ----
    seed: int = 1234                   # partitioner/model seed (dbs.py:313, 329)
    n_train: int = 0                   # >0: truncate the train split to this
                                       # many examples (tokens for the LM) —
                                       # controlled-scale runs through the real
                                       # entry point; 0 = full dataset
    momentum: float = 0.9              # SGD momentum (dbs.py:369)
    bucket: int = 16                   # batch shapes rounded up to a multiple of
                                       # this, bounding XLA recompiles while
                                       # keeping real per-worker compute ∝ batch
    capacity_factor: float = 2.0       # max worker share = factor/world_size;
                                       # bounds memory of the padded fast path
    snap_to_bucket: bool = True        # quantize per-worker batches to bucket
                                       # multiples: padded shape == true batch,
                                       # shape universe = a fixed ladder, so
                                       # time noise can't churn XLA compiles
    time_smoothing: float = 0.0        # EMA factor on the measured node-time
                                       # vector (0 = off, exact reference
                                       # semantics: raw last-epoch times)
    probe_overhead_correction: bool = True
                                       # subtract the per-device dispatch/sync
                                       # overhead (measured on a tiny jitted
                                       # op, the same blocking discipline as
                                       # the probes) from standalone probe
                                       # walls before they anchor the
                                       # per-example cost model or the
                                       # balancer signal. On local backends
                                       # this is O(100us) and invisible; over
                                       # a tunneled device (axon: ~66 ms RTT,
                                       # artifacts/STEPTIME_tpu.json) an
                                       # uncorrected anchor inflates the
                                       # per-example cost ~4x, which oversizes
                                       # compute-mode injection by the same
                                       # factor (a 3:1 nominal profile lands
                                       # ~9:1 in device terms). Paired
                                       # measurements (iter-cost calibration)
                                       # were already immune: the RTT cancels
                                       # in their subtraction.
    probe_mode: str = "adaptive"       # "always": per-worker probe steps every
                                       # epoch (round-2 behavior; the reference
                                       # analogue, since it re-times every
                                       # epoch, dbs.py:226-250). "adaptive":
                                       # probe epochs 0-1 to anchor a linear
                                       # per-example cost model, then SKIP
                                       # probes and feed the solver modeled
                                       # times, re-probing every probe_every
                                       # epochs, when the injection episode
                                       # changes, or when a skipped epoch's
                                       # wall deviates probe_wall_tol from the
                                       # last probed wall — so the balancer's
                                       # signal costs ~nothing once converged
                                       # (the reference's signal is free too:
                                       # it times the epoch it already ran)
    probe_every: int = 5               # adaptive mode: max epochs between real
                                       # probe anchors
    probe_wall_tol: float = 0.25       # adaptive mode: relative epoch-wall
                                       # deviation (vs the last probed epoch,
                                       # probe cost excluded) that forces a
                                       # re-probe next epoch
    fault_mode: str = "virtual"        # "virtual": add simulated seconds to the
                                       # measured time vector (exact reference
                                       # semantics, dbs.py:94-129);
                                       # "compute": inject real on-device FLOPs
    straggler: str = ""                # deterministic per-worker slowdown
                                       # factors, e.g. "3,1,1,1" — the analogue
                                       # of the reference's contended GPU map
                                       # `-gpu 0,0,0,1` (README.md:23-28); mode
                                       # taken from fault_mode; "" = off
    precision: str = "float32"         # "float32" | "bfloat16" compute dtype
    data_dir: str = "./data"
    lm_data_dir: str = "./rnn_data/wikitext-2"
    log_dir: str = "./logs"
    stat_dir: str = "./statis"
    ckpt_dir: str = ""                 # non-empty → orbax checkpointing on
    bptt: int = 35                     # LM window (dbs.py:343)
    seq_parallel: str = ""             # "ring" | "ulysses": train the LM with
                                       # the SEQUENCE axis sharded over the
                                       # mesh (long-context mode; bptt scales
                                       # with the mesh). "" = DBS data-parallel
    grad_clip: float = 0.0             # LM path uses 0.25 (dbs.py:274)
    profile_dir: str = ""              # non-empty → jax.profiler traces
    use_pallas: bool = False           # route GroupNorm/xent through the
                                       # Pallas kernels (ops/pallas/) —
                                       # numerics-preserving kernel routing
    use_flash_attention: bool = False  # LM attention via the Pallas flash
                                       # kernel; NOTE: drops attention-prob
                                       # dropout (a semantics change, hence a
                                       # separate knob from use_pallas)
    remat: bool = False                # jax.checkpoint the training forward:
                                       # activations recomputed in the
                                       # backward (exact math; HBM for ~1/3
                                       # extra FLOPs — the standard TPU
                                       # memory lever)
    fused_dbs: bool = False            # run the DBS balancer on the fused
                                       # capacity-padded SPMD path: every
                                       # worker is padded to the max bucketed
                                       # batch, so ONE compiled scan serves
                                       # every rebalanced plan (no per-step
                                       # Python dispatch); the time signal
                                       # comes from untimed per-worker probe
                                       # steps. Trades <= capacity_factor x
                                       # padding FLOPs for zero dispatch.
                                       # Needs one worker per chip.
    grad_comm: str = "flat"            # "flat": one psum over the whole data
                                       # mesh (the reference structure).
                                       # "hier": two-level ICI/DCN collective
                                       # (ISSUE 12) — full-precision in-host
                                       # reduce-scatter, ONE compressed
                                       # all-reduce hop across hosts on
                                       # grad_comm_wire (error-feedback
                                       # residuals in the TrainState), then
                                       # an in-host all-gather. Needs a
                                       # (host, device) factorization: real
                                       # multi-host processes, or a
                                       # synthetic --hier_hosts split on CPU
                                       # tiers; falls back to flat (one log
                                       # line) when none exists.
    grad_comm_wire: str = "int8"       # hier DCN hop wire format
                                       # (parallel/wire.py): "fp32" = exact
                                       # (structure-only win), "int8" = 127
                                       # levels, stochastic rounding
                                       # (unbiased), int16 wire sum — half
                                       # the f32 bytes on 1/D of the tree;
                                       # "int4" = 7 levels, round-to-nearest
                                       # (biased; the error-feedback
                                       # residual makes it convergent),
                                       # int8 wire sum — a quarter.
    dcn_bandwidth_probe: bool = False  # measure both link classes at init
                                       # (parallel/mesh.py
                                       # probe_link_bandwidth) and fall back
                                       # to the flat combine when the
                                       # three-phase hier structure does not
                                       # beat one flat psum on this fabric
                                       # (single-host meshes, symmetric
                                       # links). Off = trust --grad_comm.
    hier_hosts: int = 0                # synthetic host-axis size for
                                       # single-process meshes (CPU tiers,
                                       # tests, the grad_comm bench): split
                                       # the n devices into this many "host"
                                       # groups. 0 = derive from the real
                                       # process topology.
    hier_levels: str = ""              # N-level topology declaration
                                       # (ISSUE 17): comma list of
                                       # name:size OUTER levels,
                                       # outermost (slowest link) first —
                                       # e.g. "pod:2,host:2"; the innermost
                                       # device level is implicit and
                                       # absorbs the remainder. Prefix
                                       # "learned" (bare, or
                                       # "learned,host:2,...") merges
                                       # adjacent levels the bandwidth
                                       # probe measures as the same link
                                       # class. "" = the two-level
                                       # host/device split (hier_hosts /
                                       # process topology).
    grad_comm_wires: str = ""          # per-hop wire codecs for the tree
                                       # combine, outermost hop first,
                                       # comma list (innermost must be
                                       # fp32), e.g. "int4,int8,fp32";
                                       # "auto" = choose per hop from the
                                       # bandwidth probe's measured link
                                       # rates (parallel/wire.py
                                       # choose_wires). "" = legacy:
                                       # grad_comm_wire on the outermost
                                       # hop, fp32 below.
    dcn_probe_gate: float = 0.95       # hier-vs-flat probe verdict ratio:
                                       # hier wins when its measured wall
                                       # < gate * flat wall (the margin a
                                       # structural change must clear
                                       # before it is worth a recompile
                                       # universe).
    compress_grads: str = ""           # "int8": gradient collective quantized
                                       # to 127 levels (shared pmax scale,
                                       # stochastic rounding — unbiased, no
                                       # error feedback needed), summed in
                                       # int16: half the wire bytes. Opt-in;
                                       # fused paths, and with shard_update
                                       # the ZeRO-1 reduce-scatter rides the
                                       # same wire (PR 13).
    grad_accum: int = 1                # fused-path micro-batching: each step's
                                       # per-device batch is processed in this
                                       # many scanned slices, grads summed
                                       # before the collective (exact under
                                       # per-example weighting); activation
                                       # memory / grad_accum. Absent in the
                                       # reference (SURVEY §2.5).
    shard_update: bool = False         # cross-replica weight-update sharding
                                       # (ZeRO-1 analogue), generic over
                                       # optax transforms since PR 13:
                                       # reduce-scatter grads, tx.update on
                                       # the 1/n flat opt-state chunk,
                                       # all-gather the delta — optimizer
                                       # memory / n_dev. Composes with the
                                       # fused paths, the elastic DBS
                                       # dispatch (zero-1 combine twins),
                                       # elastic world size (chunks re-shard
                                       # onto the survivor mesh),
                                       # compress_grads (quantized
                                       # reduce-scatter) and grad_comm=hier
                                       # (the in-host RS + compressed DCN
                                       # hop), and since PR 18 scan-mode
                                       # supersteps and packed epochs (the
                                       # axis-free zero-1 twin runs inside
                                       # the compiled window). Excluded:
                                       # shard_update x compress_grads keeps
                                       # the windowed cadence in scan
                                       # topologies (stochastic rounding is
                                       # no identity even on a size-1 axis),
                                       # and non-elementwise transforms
                                       # (global-norm clipping INSIDE tx)
                                       # are out of contract — the per-worker
                                       # grad_clip runs before the combine
                                       # and is fine.
    stream_chunk_steps: int = 128      # host data path streams the epoch in
                                       # windows of this many steps (gather +
                                       # device_put of window k+1 overlaps
                                       # device compute of window k), bounding
                                       # peak host memory to O(2·chunk·batch)
                                       # instead of the whole epoch; 0 = off.
                                       # No-op when the epoch fits one window.
    warm_start: bool = False           # pre-compile the whole bucketed batch
                                       # shape ladder before epoch 0, so DBS
                                       # rebalances never pay an XLA compile
                                       # inside a timed epoch (benchmarks set
                                       # this; the persistent compile cache
                                       # makes it cheap on reruns)
    aot_warm: bool = True              # run the compile universe through the
                                       # async AOT compile service
                                       # (runtime/compiler.py): executables
                                       # are jit(...).lower(abstract).
                                       # compile()d concurrently on a thread
                                       # pool — no dummy execution, no
                                       # device_put traffic — and hot
                                       # dispatch resolves the compiled
                                       # objects from the service. off = the
                                       # legacy execute-to-compile warm loop
                                       # (kept as the A/B reference; see
                                       # bench aot_warm_ab + graftlint G007)
    aot_pool: int = 0                  # AOT compile pool width; 0 = auto
                                       # (min(8, cpus), >= 2). Lowering is
                                       # single-flight (GIL-bound) either
                                       # way; the pool parallelizes the
                                       # backend-compile phase
    aot_backend: str = "thread"        # "thread": backend compiles run on
                                       # the in-process pool (XLA releases
                                       # the GIL, but concurrent program
                                       # compiles contend on a shared
                                       # resource in the XLA:CPU emitter —
                                       # and on small hosts on the machine
                                       # itself). "process": the backend-
                                       # compile phase runs in subprocess
                                       # workers feeding the run's pinned
                                       # persistent cache; the in-process
                                       # step becomes a guaranteed cache-hit
                                       # replay (runtime/compile_worker.py).
                                       # Worth it on many-core hosts where
                                       # per-program compiles no longer
                                       # share an emitter; bench
                                       # compile_workers_ab measures it.
    aot_workers: int = 0               # process-backend subprocess count
                                       # (0 = auto: min(4, cpus)); each
                                       # worker is a full spawned JAX
                                       # runtime (~2-4 s startup, paid once,
                                       # overlapped with the run's own
                                       # warm-up)
    aot_speculate: bool = True         # when a rebalance dispatches a
                                       # ladder rung, background-compile the
                                       # ADJACENT rungs (±bucket) while the
                                       # epoch executes, so the next
                                       # rebalance's fresh layout is already
                                       # compiled and the recompile sentinel
                                       # stays silent (dbs runs only)
    speculate_scan: bool = True        # scan-mode shape-TUPLE speculation:
                                       # predict the solver's next share
                                       # vector (EMA of per-worker share
                                       # deltas, balance/solver.py
                                       # ShareTrajectoryPredictor), quantize
                                       # it exactly like the plan builder,
                                       # and background-compile the
                                       # predicted superstep (shapes,
                                       # window) keys in the epoch's untimed
                                       # tail. Mispredictions cost only
                                       # background work; hits remove the
                                       # last steady-state foreground
                                       # compile class (tuples have no
                                       # finite ±bucket adjacency).
                                       # Requires aot_speculate.
    device_cache: str = "auto"         # "auto"|"on"|"off": keep the train
                                       # arrays resident in HBM and feed each
                                       # epoch by INDEX (on-device gather in
                                       # the compiled step). The reference
                                       # rebuilds a DataLoader per epoch
                                       # (dbs.py:394-395); the TPU-native
                                       # equivalent makes the per-epoch
                                       # reshard an index permutation — per
                                       # epoch host->device traffic drops
                                       # from the whole dataset to [steps,
                                       # batch] int32. auto = on when the
                                       # arrays fit device_cache_mb (vision
                                       # path; multi-host replicates the
                                       # cache on every process's devices).
    device_cache_mb: int = 512         # HBM budget for the device cache
    coordinator: str = ""              # multi-host rendezvous: coordinator
                                       # "host:port" — the analogue of the
                                       # reference's MASTER_ADDR/MASTER_PORT +
                                       # init_process_group (dbs.py:513-515),
                                       # mapped to jax.distributed.initialize.
                                       # Non-empty -> the CLI initializes the
                                       # distributed runtime before building
                                       # the engine. Env: DBS_COORDINATOR.
    num_processes: int = 0             # multi-host: total process count
                                       # (dbs.py:538's world of processes; on
                                       # TPU pods 0 lets JAX autodetect).
                                       # Env: DBS_NUM_PROCESSES.
    process_id: int = -1               # multi-host: this process's id; -1
                                       # lets JAX autodetect (TPU pods).
                                       # Env: DBS_PROCESS_ID.
    superstep: str = "auto"            # "auto"|"on"|"off": elastic-path
                                       # supersteps (ISSUE 2). auto/on: the
                                       # elastic hot loop runs windowed — a
                                       # single-device worker group executes
                                       # a whole window as ONE compiled
                                       # lax.scan (combine cadence inside the
                                       # scan, bitwise-identical math), and
                                       # multi-device groups dispatch one
                                       # window-sliced executable per worker
                                       # per step (on-device step slicing)
                                       # behind a per-device double-buffered
                                       # transfer pipeline. off: the legacy
                                       # per-step dispatch loop (kept as the
                                       # parity/overhead reference).
    superstep_window: int = 16         # scan-mode superstep window cap: the
                                       # compiled window is a fully UNROLLED
                                       # scan (a rolled while-loop lowers
                                       # with different reduction blocking
                                       # and breaks bitwise parity with the
                                       # per-step path), so program size and
                                       # compile time scale with the window;
                                       # 16 already amortizes dispatch 16x.
                                       # Windowed (multi-device) mode streams
                                       # by stream_chunk_steps as before.
    trace: str = "off"                 # graftscope span tracing (obs/trace.py):
                                       # "on" = unbounded event buffer, "ring"
                                       # = keep the last trace_ring events
                                       # (long runs), "off" = zero-cost no-op
                                       # (every call site degrades to one
                                       # attribute check; no jax is touched,
                                       # so disabled mode is sentinel-silent
                                       # under the compile guards). Traces
                                       # save as Chrome-trace JSON under
                                       # trace_dir at end of run — open in
                                       # ui.perfetto.dev or summarize with
                                       # the `graftscope` CLI.
    trace_ring: int = 1_000_000        # ring-mode event cap (~100 bytes/event)
    trace_dir: str = "./traces"        # where run traces are written
    trace_annotations: bool = False    # ALSO wrap each span in a
                                       # jax.profiler.TraceAnnotation so host
                                       # spans line up with device timelines
                                       # inside a --profile_dir trace
    trace_spool: str = ""              # flight recorder (ISSUE 15): non-empty
                                       # = directory for a crash-durable
                                       # per-process spool file the tracer
                                       # streams into via a background
                                       # flusher (length-framed JSONL; a
                                       # SIGKILL loses at most the last
                                       # flush interval). Stitch the
                                       # survivors' + victims' spools with
                                       # `graftscope postmortem <dir>`.
                                       # Requires trace != off.
    trace_spool_flush_s: float = 0.25  # spool flush cadence (also flushes
                                       # at the 512-event watermark)
    trace_spool_fsync: bool = False    # fsync each spool flush: survives
                                       # power loss, not just process death
                                       # (costs flush latency)
    elastic: str = "off"               # "on"|"off": elastic world size
                                       # (ISSUE 6). on: a per-worker health
                                       # monitor (runtime/health.py) feeds
                                       # the engine's recovery path — a
                                       # CONFIRMED-lost worker is dropped,
                                       # the partition re-solved over
                                       # survivors (the same solver code
                                       # path as a straggler re-route),
                                       # data re-sharded, executables for
                                       # the new world size warmed through
                                       # the AOT service, and training
                                       # continues from the epoch-start
                                       # consistent snapshot; a recovered
                                       # worker is readmitted at the next
                                       # epoch boundary with a probe-seeded
                                       # share. Costs one host snapshot of
                                       # the TrainState per epoch while on.
                                       # Single-process recovery only
                                       # (multi-host runs get detection +
                                       # a diagnosable abort; see README
                                       # "Fault tolerance").
    elastic_detect_misses: int = 2     # consecutive missed liveness checks
                                       # that CONFIRM a worker loss (1 miss
                                       # is indistinguishable from jitter —
                                       # same two-strike hysteresis as the
                                       # adaptive probe scheduler)
    elastic_latency_factor: float = 8.0  # probe latency over this multiple
                                       # of the fleet median marks a worker
                                       # SUSPECT (observability; the solver
                                       # already re-routes data away)
    elastic_readmit: str = "epoch"     # "epoch": recovered workers rejoin
                                       # at the next epoch boundary with a
                                       # probe-seeded share; "off": once
                                       # lost, a worker stays out (strictly
                                       # shrinking fleet)
    elastic_max_recoveries: int = 8    # recovery attempts before the run
                                       # gives up (a fleet losing workers
                                       # faster than this is not a fleet)
    rebalance: str = "epoch"           # "epoch"|"window": DBS control-loop
                                       # cadence (ISSUE 11). epoch: the
                                       # reference semantics — one inverse-
                                       # time re-solve per epoch boundary.
                                       # window: an online hysteresis
                                       # controller (balance/controller.py)
                                       # re-evaluates every rebalance_every
                                       # windows inside the elastic epoch,
                                       # and retires the remaining windows
                                       # under a new plan when the predicted
                                       # remaining-epoch win beats the
                                       # measured switch cost — the time-
                                       # varying straggler scenario
                                       # (sin/ramp schedules) the epoch
                                       # cadence cannot touch. Elastic
                                       # dispatch paths only; single-process
                                       # only (the switch decision folds
                                       # locally measured walls).
    rebalance_every: int = 1           # window cadence: evaluate the online
                                       # controller every K dispatch windows
    rebalance_hysteresis: float = 0.1  # relative hysteresis: switch only
                                       # when the predicted win is at least
                                       # this fraction of the predicted
                                       # remaining-epoch time
    rebalance_margin: float = 3.0      # absolute hysteresis: predicted win
                                       # must exceed margin x the measured
                                       # (EMA) switch cost
    rebalance_budget_frac: float = 0.5 # regret-style budget: cumulative
                                       # switch spend may never exceed this
                                       # fraction of cumulative banked wins
                                       # (+ the pending win) — the no-thrash
                                       # brake when costs drift above
                                       # estimates. Needs margin >= 1/frac
                                       # for the first switch to be
                                       # admissible.
    rebalance_rate_alpha: float = 0.5  # EMA weight on the newest per-worker
                                       # rate sample in the controller
    fault_schedule: str = "none"       # "none"|"sin"|"ramp"|"spike"|
                                       # "diurnal"|"brownout"|"killstorm":
                                       # time-VARYING straggler schedule over
                                       # the --straggler factors (faults.py
                                       # ScheduledStragglerInjector): factors
                                       # follow the schedule gain within
                                       # epochs — the scenario the window-
                                       # cadence controller exists for.
                                       # none = the static profile; brownout/
                                       # killstorm draw per-worker victim
                                       # sets from --seed.
    fault_period: float = 2.0          # schedule period in epochs (sin:
                                       # full cycle; ramp: rise time)
    packed: str = "auto"               # "auto"|"on"|"off": single-device
                                       # packed epochs — when every worker
                                       # lives on ONE chip (the contention
                                       # topology, e.g. the reference's
                                       # -gpu 0,0,0,0), concatenate the
                                       # workers' true-width batches into one
                                       # compiled whole-epoch scan (psum on a
                                       # 1-chip mesh is identity, so the
                                       # weighted-sum combine is unchanged).
                                       # True per-worker batch sizes — only
                                       # <= ws*bucket rows of padding, vs the
                                       # capacity layout's 2x — and zero
                                       # per-step Python dispatch. Balancer
                                       # signal still comes from the
                                       # standalone per-worker probes.

    def __post_init__(self):
        if self.model not in MODELS:
            raise ValueError(f"invalid model {self.model!r}; choose from {MODELS}")
        if self.dataset not in DATASETS:
            raise ValueError(f"invalid dataset {self.dataset!r}; choose from {DATASETS}")
        if self.world_size < 1:
            raise ValueError("world_size must be >= 1")
        if isinstance(self.device, list) and len(self.device) != self.world_size:
            raise ValueError("device map length must equal world_size")
        if self.fault_mode not in ("virtual", "compute"):
            raise ValueError("fault_mode must be 'virtual' or 'compute'")
        if self.probe_mode not in ("adaptive", "always"):
            raise ValueError("probe_mode must be 'adaptive' or 'always'")
        if self.straggler and len(self.straggler_factors()) != self.world_size:
            raise ValueError("straggler factor list length must equal world_size")
        if self.compress_grads not in ("", "int8"):
            raise ValueError("compress_grads must be '' or 'int8'")
        if self.grad_comm not in ("flat", "hier"):
            raise ValueError("grad_comm must be 'flat' or 'hier'")
        if self.grad_comm_wire not in ("fp32", "int8", "int4"):
            raise ValueError("grad_comm_wire must be 'fp32', 'int8' or 'int4'")
        if self.hier_hosts < 0:
            raise ValueError("hier_hosts must be >= 0 (0 = real topology)")
        if self.hier_levels:
            from dynamic_load_balance_distributeddnn_tpu.parallel.topology import (
                parse_hier_levels,
            )

            spec = self.hier_levels.strip()
            if spec == "learned" or spec.startswith("learned,"):
                spec = spec[len("learned"):].lstrip(",")
            parse_hier_levels(spec)  # raises on malformed entries
        if self.grad_comm_wires and self.grad_comm_wires != "auto":
            for w in self.grad_comm_wires.split(","):
                if w.strip() not in ("fp32", "int8", "int4"):
                    raise ValueError(
                        f"grad_comm_wires entry {w.strip()!r} must be "
                        "'fp32', 'int8' or 'int4' (or the whole flag "
                        "'auto')"
                    )
        if not (0.0 < self.dcn_probe_gate <= 1.5):
            raise ValueError("dcn_probe_gate must be in (0, 1.5]")
        if self.grad_comm == "hier" and self.compress_grads:
            raise ValueError(
                "grad_comm=hier subsumes compress_grads: the cross-host hop "
                "already rides --grad_comm_wire (the flat int8 collective "
                "stays available via compress_grads with grad_comm=flat)"
            )
        if self.grad_comm == "hier" and self.seq_parallel:
            raise ValueError(
                "grad_comm=hier applies to the data-parallel gradient "
                "combine; the sequence-parallel modes shard the sequence "
                "axis instead"
            )
        if self.device_cache not in ("auto", "on", "off"):
            raise ValueError("device_cache must be 'auto', 'on' or 'off'")
        if self.packed not in ("auto", "on", "off"):
            raise ValueError("packed must be 'auto', 'on' or 'off'")
        if self.superstep not in ("auto", "on", "off"):
            raise ValueError("superstep must be 'auto', 'on' or 'off'")
        if self.elastic not in ("on", "off"):
            raise ValueError("elastic must be 'on' or 'off'")
        if self.elastic_detect_misses < 1:
            raise ValueError("elastic_detect_misses must be >= 1")
        if self.elastic_readmit not in ("epoch", "off"):
            raise ValueError("elastic_readmit must be 'epoch' or 'off'")
        if self.rebalance not in ("epoch", "window"):
            raise ValueError("rebalance must be 'epoch' or 'window'")
        if self.rebalance_every < 1:
            raise ValueError("rebalance_every must be >= 1")
        if self.rebalance_hysteresis < 0 or self.rebalance_margin < 0:
            raise ValueError("rebalance hysteresis/margin must be >= 0")
        if self.rebalance_budget_frac <= 0:
            raise ValueError("rebalance_budget_frac must be > 0")
        if not 0.0 < self.rebalance_rate_alpha <= 1.0:
            raise ValueError("rebalance_rate_alpha must be in (0, 1]")
        if self.fault_schedule not in (
            "none", "sin", "ramp", "spike", "diurnal", "brownout", "killstorm"
        ):
            raise ValueError(
                "fault_schedule must be 'none', 'sin', 'ramp', 'spike', "
                "'diurnal', 'brownout' or 'killstorm'"
            )
        if self.fault_period <= 0:
            raise ValueError("fault_period must be > 0 epochs")
        if self.fault_schedule != "none" and not self.straggler:
            raise ValueError(
                "fault_schedule needs --straggler factors to modulate"
            )
        if self.rebalance == "window" and not self.dynamic_batch_size:
            raise ValueError(
                "rebalance=window is a DBS control-loop cadence; it needs "
                "dynamic_batch_size on"
            )
        if self.rebalance == "window" and self.fused_dbs:
            raise ValueError(
                "rebalance=window retires windows mid-epoch on the elastic "
                "dispatch paths; the fused-DBS whole-epoch scan has no "
                "window boundary to act at"
            )
        if self.trace not in ("on", "off", "ring"):
            raise ValueError("trace must be 'on', 'off' or 'ring'")
        if self.trace_ring < 1:
            raise ValueError("trace_ring must be >= 1")
        if self.trace_spool_flush_s <= 0:
            raise ValueError("trace_spool_flush_s must be > 0")
        if self.trace_spool and self.trace == "off":
            # the flight recorder streams TRACER events — with tracing off
            # it would silently record nothing for exactly the chaos run it
            # was configured to protect
            raise ValueError(
                "trace_spool requires tracing: set --trace ring (or on)"
            )
        if self.superstep_window < 1:
            raise ValueError("superstep_window must be >= 1")
        if self.aot_pool < 0:
            raise ValueError("aot_pool must be >= 0 (0 = auto)")
        if self.aot_backend not in ("thread", "process"):
            raise ValueError("aot_backend must be 'thread' or 'process'")
        if self.aot_workers < 0:
            raise ValueError("aot_workers must be >= 0 (0 = auto)")
        if self.compress_grads and self.dynamic_batch_size and not self.fused_dbs:
            raise ValueError(
                "compress_grads rides a fused path (the elastic DBS combine "
                "keeps exact f32 gradients); enable fused_dbs to combine it "
                "with the balancer"
            )
        if self.grad_accum > 1 and self.dynamic_batch_size and not self.fused_dbs:
            raise ValueError(
                "grad_accum rides a fused path; the elastic DBS path controls "
                "memory by shrinking per-worker batches instead"
            )

    def straggler_factors(self) -> List[float]:
        return [float(x) for x in self.straggler.split(",")] if self.straggler else []

    @property
    def num_classes(self) -> int:
        # dbs.py:333-335
        return 100 if self.dataset == "cifar100" else 10

    def worker_device_ids(self, n_devices: int) -> List[int]:
        """Resolve the worker→device map. An int (including 0, like the
        reference's `-gpu 0`) pins every worker to that device; a list is
        used verbatim; None (the default) round-robins workers over the
        available devices (one worker per chip when ws == n_devices)."""
        if isinstance(self.device, list):
            return [d % n_devices for d in self.device]
        if isinstance(self.device, int):
            return [self.device % n_devices] * self.world_size
        return [r % n_devices for r in range(self.world_size)]

    def base_filename(self) -> str:
        """Config-encoded artifact name, same fields as the reference
        (dbs.py:54-61); `{}` is the worker-rank placeholder."""
        name = (
            f"{self.model}-{self.dataset}-debug{int(self.debug)}-n{self.world_size}"
            f"-bs{self.batch_size}-lr{self.learning_rate:.4f}-ep{self.epoch_size}"
            f"-dbs{int(self.dynamic_batch_size)}-ft{int(self.fault_tolerance)}"
            f"-ftc{self.fault_tolerance_chance:f}-node{{}}"
            f"-ocp{int(self.one_cycle_policy)}"
        )
        if self.disable_enhancements:
            name = "puredbs=" + name
        if self.seq_parallel:
            name = f"sp_{self.seq_parallel}=" + name  # distinct artifact lineage
        return name

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def get_parser() -> argparse.ArgumentParser:
    """CLI with the reference's 13 flags (same short names/defaults,
    parser.py:40-80) plus this framework's TPU knobs."""
    p = argparse.ArgumentParser(
        description="Dynamic Batch Size for Distributed DNN Training — TPU-native"
    )
    d = Config()
    p.add_argument("-d", "--debug", type=str2bool, default=d.debug,
                   help="Debug mode: small run on whatever backend is present.")
    p.add_argument("-ws", "--world_size", type=int, default=d.world_size)
    p.add_argument("-b", "--batch_size", type=int, default=d.batch_size)
    p.add_argument("-lr", "--learning_rate", type=float, default=d.learning_rate)
    p.add_argument("-e", "--epoch_size", type=int, default=d.epoch_size)
    p.add_argument("-ds", "--dataset", type=str, default=d.dataset, choices=DATASETS)
    p.add_argument("-dbs", "--dynamic_batch_size", type=str2bool, default=d.dynamic_batch_size)
    p.add_argument("-gpu", "-dev", "--device", type=device_map, default=None,
                   help="Worker→device map, e.g. '0,0,0,1', or a single ordinal "
                        "to pin all workers (reference -gpu). Default: "
                        "round-robin, one worker per device.")
    p.add_argument("-m", "--model", type=str, default=d.model, choices=MODELS)
    p.add_argument("-ft", "--fault_tolerance", type=str2bool, default=d.fault_tolerance)
    p.add_argument("-ftc", "--fault_tolerance_chance", type=float, default=d.fault_tolerance_chance)
    p.add_argument("-ocp", "--one_cycle_policy", type=str2bool, default=d.one_cycle_policy)
    p.add_argument("-de", "--disable_enhancements", type=str2bool, default=d.disable_enhancements)
    # TPU-native extras
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--n_train", type=int, default=d.n_train,
                   help="Truncate the train split to N examples (LM: tokens); 0 = full.")
    p.add_argument("--momentum", type=float, default=d.momentum)
    p.add_argument("--bucket", type=int, default=d.bucket)
    p.add_argument("--capacity_factor", type=float, default=d.capacity_factor)
    p.add_argument("--snap_to_bucket", type=str2bool, default=d.snap_to_bucket)
    p.add_argument("--remat", type=str2bool, default=d.remat,
                   help="Rematerialize activations in the backward "
                        "(jax.checkpoint; exact, saves HBM).")
    p.add_argument("--fused_dbs", type=str2bool, default=d.fused_dbs,
                   help="DBS on the fused capacity-padded SPMD scan (one "
                        "compiled step for every plan; probe-measured times).")
    p.add_argument("--grad_comm", type=str, default=d.grad_comm,
                   choices=["flat", "hier"],
                   help="Gradient combine structure: flat single psum, or "
                        "the hierarchical ICI/DCN collective (in-host "
                        "reduce-scatter, compressed cross-host hop with "
                        "error-feedback residuals, in-host all-gather).")
    p.add_argument("--grad_comm_wire", type=str, default=d.grad_comm_wire,
                   choices=["fp32", "int8", "int4"],
                   help="Wire format of the hierarchical cross-host hop: "
                        "fp32 exact, int8 stochastic-rounded (unbiased, "
                        "int16 wire sum), int4 nearest-rounded (biased, "
                        "error feedback corrects; int8 wire sum).")
    p.add_argument("--dcn_bandwidth_probe", type=str2bool,
                   default=d.dcn_bandwidth_probe,
                   help="Probe both link classes at init and fall back to "
                        "the flat combine when the hierarchical structure "
                        "does not beat one flat psum on this fabric.")
    p.add_argument("--hier_hosts", type=int, default=d.hier_hosts,
                   help="Synthetic host-axis size for single-process meshes "
                        "(CPU tiers/tests); 0 = real process topology.")
    p.add_argument("--hier_levels", type=str, default=d.hier_levels,
                   help="N-level topology declaration for the tree combine: "
                        "comma list of name:size outer levels, outermost "
                        "first (e.g. 'pod:2,host:2'); prefix 'learned' to "
                        "merge probe-indistinguishable levels; '' = the "
                        "two-level host/device split.")
    p.add_argument("--grad_comm_wires", type=str, default=d.grad_comm_wires,
                   help="Per-hop wire codecs, outermost first (e.g. "
                        "'int4,int8,fp32'; innermost must be fp32); 'auto' "
                        "= choose per hop from measured link rates; '' = "
                        "grad_comm_wire on the outermost hop only.")
    p.add_argument("--dcn_probe_gate", type=float, default=d.dcn_probe_gate,
                   help="Bandwidth-probe verdict ratio: hier wins when its "
                        "wall < gate * flat wall.")
    p.add_argument("--compress_grads", type=str, default=d.compress_grads,
                   choices=["", "int8"],
                   help="Quantized gradient collective (stochastic rounding, "
                        "int16 wire sum): half the collective bytes.")
    p.add_argument("--grad_accum", type=int, default=d.grad_accum,
                   help="Fused-path micro-batching factor (activation memory "
                        "/ N, grads summed before the collective; exact).")
    p.add_argument("--shard_update", type=str2bool, default=d.shard_update,
                   help="ZeRO-1-style sharded optimizer update, generic over "
                        "optax transforms (reduce_scatter grads / tx.update "
                        "on the 1/n chunk / all_gather delta); composes "
                        "with elastic, hier and the quantized wires.")
    p.add_argument("--stream_chunk_steps", type=int, default=d.stream_chunk_steps,
                   help="Stream the host data path in windows of N steps "
                        "(prefetch overlaps compute); 0 = materialize whole epochs.")
    p.add_argument("--time_smoothing", type=float, default=d.time_smoothing)
    p.add_argument("--probe_overhead_correction", type=str2bool,
                   default=d.probe_overhead_correction,
                   help="Subtract measured per-device dispatch overhead from "
                        "standalone probe walls (tunneled-device hygiene; "
                        "negligible on local backends).")
    p.add_argument("--probe_mode", type=str, default=d.probe_mode,
                   choices=["adaptive", "always"],
                   help="adaptive: skip per-worker probe steps once the "
                        "cost model is anchored (re-probe on schedule/episode "
                        "change/wall deviation); always: probe every epoch.")
    p.add_argument("--probe_every", type=int, default=d.probe_every)
    p.add_argument("--probe_wall_tol", type=float, default=d.probe_wall_tol)
    p.add_argument("--fault_mode", type=str, default=d.fault_mode, choices=["virtual", "compute"])
    p.add_argument("--straggler", type=str, default=d.straggler,
                   help="Deterministic per-worker slowdown factors, e.g. '3,1,1,1' "
                        "(the reference's contended -gpu 0,0,0,1 profile); "
                        "fault_mode picks virtual vs real injected compute.")
    p.add_argument("--precision", type=str, default=d.precision, choices=["float32", "bfloat16"])
    p.add_argument("--data_dir", type=str, default=d.data_dir)
    p.add_argument("--lm_data_dir", type=str, default=d.lm_data_dir)
    p.add_argument("--log_dir", type=str, default=d.log_dir)
    p.add_argument("--stat_dir", type=str, default=d.stat_dir)
    p.add_argument("--ckpt_dir", type=str, default=d.ckpt_dir)
    p.add_argument("--bptt", type=int, default=d.bptt)
    p.add_argument("--seq_parallel", type=str, default=d.seq_parallel,
                   choices=["", "ring", "ulysses"],
                   help="Long-context LM mode: shard the sequence axis over "
                        "the mesh (ring ppermute pipeline or Ulysses head "
                        "all-to-all attention).")
    p.add_argument("--grad_clip", type=float, default=d.grad_clip)
    p.add_argument("--profile_dir", type=str, default=d.profile_dir)
    p.add_argument("--use_pallas", type=str2bool, default=d.use_pallas)
    p.add_argument("--use_flash_attention", type=str2bool, default=d.use_flash_attention)
    p.add_argument("--warm_start", type=str2bool, default=d.warm_start)
    p.add_argument("--aot_warm", type=str2bool, default=d.aot_warm,
                   help="Warm + dispatch through the async AOT compile "
                        "service (lower(abstract).compile() on a thread "
                        "pool; zero execute-to-compile). off = legacy "
                        "execute-to-compile warm loop.")
    p.add_argument("--aot_pool", type=int, default=d.aot_pool,
                   help="AOT compile pool width (0 = auto).")
    p.add_argument("--aot_backend", type=str, default=d.aot_backend,
                   choices=["thread", "process"],
                   help="Where AOT backend compiles run: in-process threads, "
                        "or subprocess workers feeding the persistent cache "
                        "(replayed in-process as guaranteed cache hits; "
                        "scales multi-program compile throughput on "
                        "many-core hosts).")
    p.add_argument("--aot_workers", type=int, default=d.aot_workers,
                   help="Process-backend compile worker count (0 = auto).")
    p.add_argument("--aot_speculate", type=str2bool, default=d.aot_speculate,
                   help="Background-compile adjacent ladder rungs during "
                        "epochs so mid-run rebalances never block on XLA.")
    p.add_argument("--speculate_scan", type=str2bool, default=d.speculate_scan,
                   help="Scan mode: predict the solver's next share vector "
                        "and background-compile the predicted superstep "
                        "shape-tuple keys in the untimed epoch tail.")
    p.add_argument("--device_cache", type=str, default=d.device_cache,
                   choices=["auto", "on", "off"],
                   help="Keep train arrays HBM-resident and feed epochs by "
                        "index (on-device gather): per-epoch reshard costs an "
                        "index upload instead of re-transferring the dataset.")
    p.add_argument("--device_cache_mb", type=int, default=d.device_cache_mb)
    p.add_argument("--superstep", type=str, default=d.superstep,
                   choices=["auto", "on", "off"],
                   help="Elastic-path supersteps: windowed executables (one "
                        "compiled scan per window on single-device groups) "
                        "plus the per-device double-buffered transfer "
                        "pipeline; off = legacy per-step dispatch.")
    p.add_argument("--superstep_window", type=int, default=d.superstep_window,
                   help="Max steps per compiled superstep window (scan mode "
                        "unrolls fully for bitwise parity; compile time "
                        "scales with this).")
    p.add_argument("--trace", type=str, default=d.trace,
                   choices=["on", "off", "ring"],
                   help="graftscope span tracing: on = full buffer, ring = "
                        "last trace_ring events; Chrome-trace JSON saved "
                        "under trace_dir (summarize with `graftscope`).")
    p.add_argument("--trace_ring", type=int, default=d.trace_ring)
    p.add_argument("--trace_dir", type=str, default=d.trace_dir)
    p.add_argument("--trace_annotations", type=str2bool,
                   default=d.trace_annotations,
                   help="Bridge spans into jax.profiler.TraceAnnotation so "
                        "host phases line up with device timelines in a "
                        "--profile_dir trace.")
    p.add_argument("--trace_spool", type=str, default=d.trace_spool,
                   help="Flight recorder: directory for a crash-durable "
                        "per-process trace spool (background flusher; a "
                        "SIGKILL loses at most the last flush interval). "
                        "Merge post-mortem with `graftscope postmortem`.")
    p.add_argument("--trace_spool_flush_s", type=float,
                   default=d.trace_spool_flush_s,
                   help="Spool flush cadence in seconds (also flushes at "
                        "the event watermark).")
    p.add_argument("--trace_spool_fsync", type=str2bool,
                   default=d.trace_spool_fsync,
                   help="fsync each spool flush (power-loss durability at "
                        "the cost of flush latency).")
    p.add_argument("--elastic", type=str, default=d.elastic,
                   choices=["on", "off"],
                   help="Elastic world size: survive confirmed worker loss "
                        "by re-solving the partition over survivors "
                        "(re-shard + AOT re-warm + continue from the "
                        "epoch-start snapshot); readmit recovered workers "
                        "at epoch boundaries.")
    p.add_argument("--elastic_detect_misses", type=int,
                   default=d.elastic_detect_misses,
                   help="Consecutive missed liveness checks that confirm a "
                        "worker loss.")
    p.add_argument("--elastic_latency_factor", type=float,
                   default=d.elastic_latency_factor,
                   help="Probe latency over this multiple of the fleet "
                        "median marks a worker SUSPECT.")
    p.add_argument("--elastic_readmit", type=str, default=d.elastic_readmit,
                   choices=["epoch", "off"],
                   help="Readmission policy for recovered workers: at the "
                        "next epoch boundary (probe-seeded share), or never.")
    p.add_argument("--elastic_max_recoveries", type=int,
                   default=d.elastic_max_recoveries)
    p.add_argument("--rebalance", type=str, default=d.rebalance,
                   choices=["epoch", "window"],
                   help="DBS control-loop cadence: epoch = one re-solve per "
                        "epoch (reference semantics); window = the online "
                        "hysteresis controller re-solves every "
                        "rebalance_every windows and switches plans "
                        "MID-epoch when the predicted remaining-epoch win "
                        "beats the measured switch cost.")
    p.add_argument("--rebalance_every", type=int, default=d.rebalance_every,
                   help="Window cadence: evaluate the online controller "
                        "every K dispatch windows.")
    p.add_argument("--rebalance_hysteresis", type=float,
                   default=d.rebalance_hysteresis,
                   help="Relative switch threshold: predicted win as a "
                        "fraction of predicted remaining-epoch time.")
    p.add_argument("--rebalance_margin", type=float,
                   default=d.rebalance_margin,
                   help="Absolute switch threshold: win must exceed margin "
                        "x the measured (EMA) switch cost.")
    p.add_argument("--rebalance_budget_frac", type=float,
                   default=d.rebalance_budget_frac,
                   help="Regret budget: cumulative switch spend capped at "
                        "this fraction of cumulative banked wins.")
    p.add_argument("--rebalance_rate_alpha", type=float,
                   default=d.rebalance_rate_alpha,
                   help="EMA weight on the newest per-worker rate sample.")
    p.add_argument("--fault_schedule", type=str, default=d.fault_schedule,
                   choices=["none", "sin", "ramp", "spike", "diurnal",
                            "brownout", "killstorm"],
                   help="Time-varying straggler schedule over the "
                        "--straggler factors (sin: smooth appear/disappear "
                        "per period; ramp: rise once and hold; spike: full "
                        "factor for the duty fraction of each period; "
                        "diurnal: day/night load plateau; brownout: seeded "
                        "contiguous multi-worker slowdowns per period; "
                        "killstorm: seeded random victim stalls per period).")
    p.add_argument("--fault_period", type=float, default=d.fault_period,
                   help="Schedule period in epochs.")
    p.add_argument("--packed", type=str, default=d.packed,
                   choices=["auto", "on", "off"],
                   help="Single-device packed epochs: concat all workers' "
                        "true-width batches into one compiled whole-epoch "
                        "scan when every worker shares one chip.")
    p.add_argument("--coordinator", type=str,
                   default=os.environ.get("DBS_COORDINATOR", d.coordinator),
                   help="Multi-host: coordinator host:port for "
                        "jax.distributed.initialize (the reference's "
                        "MASTER_ADDR/PORT rendezvous, dbs.py:513-515). "
                        "Empty = single-host.")
    p.add_argument("--num_processes", type=int,
                   default=_env_int("DBS_NUM_PROCESSES", d.num_processes),
                   help="Multi-host: total number of processes (0 = let JAX "
                        "autodetect, TPU pods).")
    p.add_argument("--process_id", type=int,
                   default=_env_int("DBS_PROCESS_ID", d.process_id),
                   help="Multi-host: this process's id (-1 = let JAX "
                        "autodetect, TPU pods).")
    return p


def config_from_args(argv: Optional[Sequence[str]] = None) -> Config:
    ns = get_parser().parse_args(argv)
    return Config(**vars(ns))
