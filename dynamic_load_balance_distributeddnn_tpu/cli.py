"""Command-line entry point.

The analogue of ``python dbs.py <flags>`` (dbs.py:527-544): parse the 13
reference flags (+ TPU extras), skip runs whose completion sentinel already
exists (idempotence probe, hardened from the reference's log-file check,
dbs.py:528-534), then run the training engine. No process
forking — the SPMD controller drives all logical workers from one process per
host (SURVEY §7.1).
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from dynamic_load_balance_distributeddnn_tpu.config import config_from_args
from dynamic_load_balance_distributeddnn_tpu.obs.logging import (
    mark_run_done,
    run_already_done,
)


def _maybe_init_distributed(cfg) -> None:
    """Multi-host rendezvous from the shipped entry point — the analogue of
    the reference's MASTER_ADDR/MASTER_PORT + init_process_group('gloo')
    (dbs.py:513-515). One process per HOST (SPMD across its chips), not one
    per worker: the rendezvous makes every host see the global device mesh,
    and the engines' collectives ride it. On TPU pods the coordinator can be
    given alone (process count/id autodetected); on the CPU tier (tests) all
    three are explicit and gloo backs the collectives."""
    if not cfg.coordinator:
        return
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        initialize_multihost,
    )

    initialize_multihost(
        cfg.coordinator,
        num_processes=cfg.num_processes if cfg.num_processes > 0 else None,
        process_id=cfg.process_id if cfg.process_id >= 0 else None,
    )


def _run_already_done_global(cfg) -> bool:
    """The idempotence probe, made collective: per-process filesystems can
    disagree (non-shared log_dirs, a config completed on one host only), and
    a rank that skips while its peers train leaves the peers hung in their
    first collective. Process 0 decides; everyone follows."""
    skip = run_already_done(cfg)
    if cfg.coordinator:
        import jax
        import numpy as np
        from jax.experimental import multihost_utils

        if jax.process_count() > 1:
            skip = bool(
                multihost_utils.broadcast_one_to_all(np.asarray(skip))
            )
    return skip


def main(argv: Optional[Sequence[str]] = None) -> int:
    cfg = config_from_args(argv)
    _maybe_init_distributed(cfg)
    if _run_already_done_global(cfg):
        print("\n===========================")
        print("Had finished this experiment, skipping...")
        print("===========================\n")
        return 0

    if cfg.model == "transformer" and cfg.seq_parallel:
        from dynamic_load_balance_distributeddnn_tpu.train.sp_engine import (
            SeqParallelLMTrainer,
        )

        trainer = SeqParallelLMTrainer(cfg)
    elif cfg.model == "transformer":
        from dynamic_load_balance_distributeddnn_tpu.train.lm_engine import LMTrainer

        trainer = LMTrainer(cfg)
    else:
        from dynamic_load_balance_distributeddnn_tpu.train.engine import Trainer

        trainer = Trainer(cfg)
    trainer.run()
    mark_run_done(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
