"""Command-line entry point.

The analogue of ``python dbs.py <flags>`` (dbs.py:527-544): parse the 13
reference flags (+ TPU extras), skip runs whose completion sentinel already
exists (idempotence probe, hardened from the reference's log-file check,
dbs.py:528-534), then run the training engine. No process
forking — the SPMD controller drives all logical workers from one process per
host (SURVEY §7.1).
"""

from __future__ import annotations

import sys
from typing import Optional, Sequence

from dynamic_load_balance_distributeddnn_tpu.config import config_from_args
from dynamic_load_balance_distributeddnn_tpu.obs.logging import (
    mark_run_done,
    run_already_done,
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    cfg = config_from_args(argv)
    if run_already_done(cfg):
        print("\n===========================")
        print("Had finished this experiment, skipping...")
        print("===========================\n")
        return 0

    if cfg.model == "transformer" and cfg.seq_parallel:
        from dynamic_load_balance_distributeddnn_tpu.train.sp_engine import (
            SeqParallelLMTrainer,
        )

        trainer = SeqParallelLMTrainer(cfg)
    elif cfg.model == "transformer":
        from dynamic_load_balance_distributeddnn_tpu.train.lm_engine import LMTrainer

        trainer = LMTrainer(cfg)
    else:
        from dynamic_load_balance_distributeddnn_tpu.train.engine import Trainer

        trainer = Trainer(cfg)
    trainer.run()
    mark_run_done(cfg)
    return 0


if __name__ == "__main__":
    sys.exit(main())
