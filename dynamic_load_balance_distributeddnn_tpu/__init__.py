"""Dynamic Load Balance Distributed DNN — TPU-native framework.

A from-scratch JAX/XLA/pjit re-design of the capabilities of
``Soptq/Dynamic_Load_Balance_DistributedDNN`` ("DBS: Dynamic Batch Size for
Distributed Deep Neural Network Training", arXiv 2007.11831): synchronous
data-parallel training where, every epoch, the dataset partition and the
per-worker batch sizes are re-balanced in inverse proportion to each worker's
measured compute time, so stragglers receive less work and all workers finish
each step together.

Where the reference (see /root/reference, cited per-module as file:line) runs
one Python process per worker over a gloo ring, this framework runs a single
controller process per host and maps *logical workers* onto the devices of a
``jax.sharding.Mesh`` — either one worker per chip (the pure SPMD case) or
several workers time-sharing a chip (the analogue of the reference's
``-gpu 0,0,0,1`` contention map, README.md:28).

Subpackages
-----------
- ``balance``   — the DBS partition solver + per-worker time exchange
- ``data``      — dataset readers, the dynamic partitioner, LM corpus
- ``models``    — Flax model zoo (MnistNet, ResNet, DenseNet, GoogLeNet,
                  RegNet, Transformer LM), GroupNorm throughout
- ``ops``       — weighted per-example losses, grad utilities, Pallas kernels
- ``parallel``  — mesh/topology, collectives, ring-attention seq parallelism
- ``train``     — pjit train steps (fused SPMD + elastic per-worker), engine
- ``obs``       — logging + the 9-series metrics recorder
"""

from dynamic_load_balance_distributeddnn_tpu.version import __version__

__all__ = ["__version__"]
