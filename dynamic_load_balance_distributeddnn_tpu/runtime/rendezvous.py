"""Epoch-boundary re-rendezvous over survivors (ISSUE 14).

PR 6 made in-process worker loss survivable; the *cross-process* story was
"detect via heartbeat files, diagnose via exit tags, abort and resume from
checkpoint" — jax cannot shrink a live multi-host mesh. This module closes
that gap: when a peer PROCESS is confirmed gone, the survivors reach
consensus on the survivor roster through the heartbeat-file directory
(propose -> agree), tear down ``jax.distributed``, and re-initialize a
smaller world over a fresh coordinator port (barrier -> establish). The
engine then rebuilds topology/mesh/StepLibrary over the survivor fleet and
resumes from its epoch-start snapshot.

Mechanism notes — every line of this was established empirically against
jax 0.4.37 / its bundled XLA coordination service, because the obvious
routes are all fatal:

* The coordination service hard-aborts survivors (``LOG(QFATAL)`` in
  pjrt/distributed/client.h) the moment a peer is declared unhealthy or the
  coordinator socket closes. The pybind ``missed_heartbeat_callback`` that
  would make this non-fatal cannot be used: this jaxlib's
  ``absl::Status -> Python`` caster throws ``std::bad_cast`` (-> terminate)
  before any Python callback runs.
* Therefore coordination-service HEARTBEATS ARE DISABLED (interval pushed to
  a day) — peer liveness is the file-beacon layer's job
  (:class:`runtime.health.ProcessHeartbeat`), which is faster anyway
  (seconds, not the service's 100s default window).
* A client whose peer died can never be shut down cleanly: ``shutdown()``
  runs a barrier the dead peer will not answer, and the barrier failure is
  routed to the fatal error poller. Dropping Python references does not
  help — the C++ error-polling thread pins the object. Retired clients and
  services are therefore DELIBERATELY LEAKED (:data:`_RETIRED`): a few
  threads + buffers per fleet generation, bounded by the recovery budget.
  Their pollers only watch the generation-0 coordinator process, so they
  stay silent until that process exits.
* Consequence: COORDINATOR-PROCESS DEATH IS NOT SURVIVABLE — the poll RPC
  errors instantly on its closed socket and every survivor aborts. That is
  the documented remaining non-goal (README "Fault tolerance"), handled by
  the watchdog/abort-and-resume ladder like before this PR.
* ``xla_bridge``'s module-level ``@lru_cache``\\ s (``process_count`` et al.)
  survive ``_clear_backends`` and must be cleared explicitly, or the new
  world inherits the old world's process count.

Every blocking phase is armored: bounded timeouts raise
:class:`RendezvousTimeout` tagged with the phase that died (the engine falls
back to today's abort-and-resume and logs it), and the wait loops tick the
stall watchdog so a slow rendezvous never reads as a device hang.
"""

from __future__ import annotations

import dataclasses
import gc
import glob
import json
import os
import re
import socket
import time
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer
from dynamic_load_balance_distributeddnn_tpu.runtime.health import retry_transient
from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import heartbeat

# Coordination-service heartbeats OFF (see module docstring): liveness is
# the file-beacon layer's job, and an enabled service window would abort
# the survivors it notices a death before we finish re-rendezvousing.
_HB_DISABLED = dict(heartbeat_interval=86400, max_missing_heartbeats=1000)
_SHUTDOWN_TIMEOUT_S = 10

# Deliberately leaked retired runtime objects (clients/services of previous
# fleet generations) — see the module docstring for why they cannot be
# destroyed. Bounded: one client (+ one service on the coordinator) per
# recovery, and recoveries are budgeted (cfg.elastic_max_recoveries).
_RETIRED: List[object] = []

_POLL_S = 0.05
_TICK_EVERY_S = 1.0


def _env_timeout(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def rendezvous_timeout_s() -> float:
    """Per-phase rendezvous timeout (env ``DBS_RDZV_TIMEOUT_S``)."""
    return _env_timeout("DBS_RDZV_TIMEOUT_S", 120.0)


class RendezvousError(RuntimeError):
    """Rendezvous failed; ``phase`` names the phase that died. The engine
    degrades to the abort-and-resume ladder instead of hanging."""

    def __init__(self, phase: str, message: str = ""):
        self.phase = phase
        super().__init__(message or f"rendezvous failed in phase '{phase}'")


class RendezvousTimeout(RendezvousError):
    """A blocking rendezvous phase exceeded its hard timeout."""


@dataclasses.dataclass(frozen=True)
class Agreement:
    """The consensus a survivor set reached: who survives, which generation
    this is, where the new coordinator listens, and which epoch training
    resumes at."""

    gen: int
    roster: Tuple[int, ...]  # ORIGINAL process ids, sorted
    rank: int                # my process id in the NEW world
    address: str
    epoch: int

    @property
    def leader(self) -> bool:
        return self.rank == 0


# --------------------------------------------------------------------------
# Machine-readable protocol annotation (graftrdzv, ISSUE 16).
#
# This table IS the rendezvous automaton, declared next to the code that
# implements it. It must stay a PURE literal: `analysis/flow/proto.py`
# loads it with `ast.literal_eval` (no runtime import, no jax), extracts
# the same facts from the IR, and cross-checks the two — a writer added
# below without a row here (or vice versa) is a lint finding, not a code
# review hope. The small-scope model checker and the `graftscope
# conformance` replay both interpret this table, so the file-name
# patterns, phases and instants below are load-bearing, not documentation.
#
# File-name patterns use `{hole}` for interpolated fields; `proto.py`
# matches them against both the IR's f-string skeletons and real
# directory listings / trace payloads.
PROTOCOL = {
    "version": 1,
    # attribute/parameter tokens that name the shared protocol directory
    "dir_tokens": ("rdzv_dir", "hb_dir", "heartbeat_dir"),
    # every JSON protocol write goes through this atomic tmp+replace
    # helper; every JSON protocol read through this tolerant reader
    "atomic_writer": "_write_json",
    "tolerant_reader": "_read_json",
    # stale-state wipe at gen-0 bring-up (coordinator only, BEFORE ack_g0)
    "wipe": "reset_rendezvous_dir",
    # protocol files: name pattern, payload format, sanctioned writers
    # (qualnames local to this module), and what readers must tolerate
    "files": {
        "ack": {
            "pattern": "ack_g{gen}.json",
            "format": "json",
            "writers": ("elastic_initialize", "RendezvousStateMachine.establish"),
            "tolerate": "missing-or-torn",
        },
        "propose": {
            "pattern": "propose_g{gen}_r{rnd}_p{ident}.json",
            "format": "json",
            "writers": ("RendezvousStateMachine.agree",),
            "tolerate": "missing-or-torn",
        },
        "torn": {
            "pattern": "torn_g{gen}_p{ident}",
            "format": "marker",
            "writers": ("RendezvousStateMachine.establish",),
            "tolerate": "missing",
        },
        "loss": {
            "pattern": "loss_g{gen}_p{ident}.json",
            "format": "json",
            "writers": ("RendezvousStateMachine.claim_loss",),
            "tolerate": "missing-or-torn",
        },
        "join": {
            "pattern": "join_p{ident}.json",
            "format": "json",
            "writers": ("RendezvousStateMachine.offer_join",),
            "tolerate": "missing-or-torn",
        },
        "probe": {
            "pattern": "probe_g{gen}_p{ident}.json",
            "format": "json",
            "writers": ("RendezvousStateMachine.publish_probe",),
            "tolerate": "missing-or-torn",
        },
        "rebuild": {
            "pattern": "rebuild_g{gen}_a{attempt}_p{ident}.json",
            "format": "json",
            "writers": ("RendezvousStateMachine.rebuild_vote",),
            "tolerate": "missing-or-torn",
        },
        "done": {
            "pattern": "done_p{ident}",
            "format": "marker",
            "writers": ("RendezvousStateMachine.finalize",),
            "tolerate": "missing",
        },
    },
    # per-process phase automaton; a recovery walks these edges in order
    "phases": ("running", "agree", "teardown", "establish", "established"),
    "edges": (
        ("running", "agree", "detect-or-join"),
        ("agree", "teardown", "rdzv_agreed"),
        ("teardown", "establish", "rdzv_torn"),
        ("establish", "established", "rdzv_established"),
        ("established", "running", "resume"),
    ),
    # flight-recorder instants -> the phase that emits them ("*" = any)
    "instants": {
        "rdzv_init": "established",
        "rdzv_offer_join": "running",
        "rdzv_claim_loss": "running",
        "rdzv_agreed": "agree",
        "rdzv_torn": "teardown",
        "rdzv_established": "established",
        "rdzv_timeout": "*",
        "rdzv_drain_timeout": "teardown",
        "rdzv_quarantine_rebuild": "establish",
        "rdzv_rebuild_vote": "establish",
    },
    # engine recovery spine: callee tail -> phase index. G018 checks that
    # recovery paths never call a lower phase after a higher one
    # (flush -> agree -> drain/retire -> establish -> reshard -> restore).
    "recovery_order": {
        "flush_checkpoints": 0,
        "agree": 1,
        "drain_collective_chain": 2,
        "retire_runtime": 2,
        "establish": 3,
        "_reshard_world": 4,
        "_state_from_host": 5,
    },
    # tails that mark a function as a recovery path at all (the G018 gate:
    # ordering is only checked where the rendezvous spine is in play)
    "recovery_core": ("flush_checkpoints", "retire_runtime", "establish",
                      "_reshard_world"),
}


def _write_json(path: str, obj: Dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)  # atomic: readers never see a partial file


def _read_json(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _global_state():
    from jax._src import distributed

    return distributed.global_state


def _xla_extension():
    from jax._src.lib import xla_extension

    return xla_extension


def _make_service(address: str, num_processes: int):
    bind = "[::]:" + address.rsplit(":", 1)[1]
    return _xla_extension().get_distributed_runtime_service(
        bind, num_processes,
        shutdown_timeout=_SHUTDOWN_TIMEOUT_S, **_HB_DISABLED,
    )


def _make_client(address: str, process_id: int, timeout_s: float):
    return _xla_extension().get_distributed_runtime_client(
        address, process_id,
        init_timeout=max(int(timeout_s), 1),
        shutdown_timeout=_SHUTDOWN_TIMEOUT_S,
        # dtor must never run the shutdown barrier: a dead peer turns it
        # into a fatal error (module docstring)
        shutdown_on_destruction=False,
        use_compression=True,
        **_HB_DISABLED,
    )


def _arm_preemption_sync(gs, client) -> None:
    # orbax's multihost save path gates every step on the preemption sync
    # point; the stock initializer arms this, so the elastic bring-up must
    # too (it rides the coordination client, NOT the disabled heartbeats)
    mgr = _xla_extension().create_preemption_sync_manager()
    mgr.initialize(client)
    gs.preemption_sync_manager = mgr


def elastic_initialize(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    rdzv_dir: Optional[str] = None,
    timeout_s: Optional[float] = None,
) -> None:
    """Generation-0 ``jax.distributed`` bring-up for an ELASTIC multi-host
    run — same contract as ``jax.distributed.initialize`` but with the
    coordination service configured so peer-process death is survivable
    (heartbeats disabled, no shutdown-on-destruction; see module
    docstring). Workers that may need to re-rendezvous must start through
    here: a world built by the stock initializer aborts all survivors the
    moment any peer dies."""
    timeout_s = rendezvous_timeout_s() if timeout_s is None else timeout_s
    gs = _global_state()
    if gs.client is not None:
        raise RuntimeError("distributed runtime already initialized")
    # fields first: the lazily built CPU backend reads them (node id /
    # world size) the moment anything touches jax.devices()
    gs.process_id = process_id
    gs.num_processes = num_processes
    gs.coordinator_address = coordinator_address
    if process_id == 0:
        gs.service = _make_service(coordinator_address, num_processes)
        if rdzv_dir:
            os.makedirs(rdzv_dir, exist_ok=True)
            # a REUSED directory (abort-and-resume restarts the fleet in
            # the same DBS_PEER_HB_DIR) still holds the dead run's protocol
            # files: the newest stale ack would win current_roster()'s
            # generation adoption, and that generation's loss claims would
            # mark freshly restarted peers down at the first boundary.
            # Clear them BEFORE publishing ack_g0 — peers connect (and
            # first read the directory) only after this process's service
            # is up, so the wipe cannot race a live writer.
            reset_rendezvous_dir(rdzv_dir)
            _write_json(
                os.path.join(rdzv_dir, "ack_g0.json"),
                {
                    "address": coordinator_address,
                    "roster": list(range(num_processes)),
                    "epoch": 0,
                    "payload": {},
                },
            )
    client = _make_client(coordinator_address, process_id, timeout_s)
    retry_transient(
        client.connect, retries=2, desc="gen-0 distributed connect",
        tick=heartbeat,
    )
    gs.client = client
    _arm_preemption_sync(gs, client)
    get_tracer().instant(
        "rdzv_init", cat="rdzv",
        args={"gen": 0, "processes": int(num_processes), "id": int(process_id)},
    )
    heartbeat()


def local_canary_launch() -> None:
    """One sacrificial multi-device launch over this process's LOCAL
    devices, blocked to completion. Shared by the drain/quarantine/rebuild
    canaries: it serializes behind the process-local collective-launch
    chain (a wedged or inherited dispatch surfaces HERE, not in the next
    stage's launches), and it deliberately touches no peer — in a
    multi-process world a device_put to a sharding spanning other
    processes runs a hidden gloo broadcast that an asymmetric recovery
    would mispair. The fresh put + compile each call is the mechanism, not
    a leak (graftlint G001/G006 sanctioned here, once)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devs = sorted(jax.local_devices(), key=lambda d: d.id)
    mesh = Mesh(np.array(devs), ("canary",))
    x = jax.device_put(  # graftlint: disable=G006
        np.ones((max(len(devs), 1),), np.float32),
        NamedSharding(mesh, PartitionSpec()),
    )
    jax.block_until_ready(jax.jit(lambda a: a + 1.0)(x))  # graftlint: disable=G001


def reset_rendezvous_dir(rdzv_dir: str) -> int:
    """Remove a PREVIOUS run's rendezvous protocol files from a reused
    directory (acks, loss claims, proposals, teardown/exit barriers, join
    offers) so a fresh generation-0 bring-up cannot adopt a dead run's
    generation or its loss verdicts. Beacon/marker files are left alone —
    live processes overwrite their own beacons at arm time. Returns the
    number of files removed. Only the gen-0 COORDINATOR may call this, and
    only before publishing ``ack_g0`` (peers first read the directory
    after connecting to its service)."""
    removed = 0
    for pat in (
        "ack_g*.json",
        "loss_g*.json",
        "propose_g*.json",
        "probe_g*.json",
        "torn_g*",
        "done_p*",
        "join_p*.json",
    ):
        for path in glob.glob(os.path.join(rdzv_dir, pat)):
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


def drain_collective_chain(
    timeout_s: Optional[float] = None, logger=None, tick: Callable = heartbeat
) -> bool:
    """Force the CURRENT (about-to-be-retired) world's wedged in-flight
    collectives to resolve before the next world is built. Returns True if
    the chain drained inside the budget.

    Mechanism: XLA:CPU serializes every multi-device launch behind the
    last collective-launch event, and collective participants meet in a
    PROCESS-GLOBAL refcounted rendezvous map. A peer dying mid-collective
    leaves launches wedged on half-dead gloo ops; until they resolve
    (socket teardown — async, seconds), their entries poison launches of
    the NEXT world through that global map. A sacrificial LOCAL-devices
    launch dispatched here serializes behind every wedged launch, so
    blocking on it (in a side thread, bounded — gloo's own timeout can be
    minutes) ensures the chain has fully resolved; its error, if any, is
    the dead world's and is swallowed. A chain that outlives the budget is
    left to the post-establish quarantine/rebuild retries."""
    import threading

    if timeout_s is None:
        timeout_s = _env_timeout("DBS_RDZV_DRAIN_S", 12.0)
    done = threading.Event()

    def _drain() -> None:
        try:
            local_canary_launch()
        except Exception:  # noqa: BLE001 — the dead world's error, expected
            pass
        finally:
            done.set()

    # the drain is the incident timeline's second act (detection -> DRAIN ->
    # rebuild): span it so the postmortem stitcher can show how long the
    # dead world's wedged collectives held the survivor up
    with get_tracer().span("rdzv_drain", cat="recover"):
        t = threading.Thread(target=_drain, daemon=True, name="rdzv-drain")
        t.start()
        deadline = time.monotonic() + timeout_s
        while not done.is_set():
            if time.monotonic() >= deadline:
                if logger is not None:
                    logger.warning(
                        f"rendezvous: old collective chain did not drain in "
                        f"{timeout_s:.0f}s — proceeding (quarantine retries "
                        "cover a late resolution)"
                    )
                get_tracer().instant(
                    "rdzv_drain_timeout", cat="rdzv",
                    args={"timeout_s": float(timeout_s)},
                )
                return False
            tick()
            done.wait(0.25)
    return True


def reset_backend() -> None:
    """Tear down the process's XLA backends and every jax-level cache that
    pins them or their world shape. Safe to call repeatedly; the next jax
    device access rebuilds against the CURRENT ``global_state`` fields."""
    import jax
    import jax._src.xla_bridge as xb

    jax.clear_caches()
    # EVERY module-level @lru_cache accessor survives _clear_backends and
    # must be cleared by hand — sweep dynamically rather than naming them:
    # missing even one (jax 0.4.36 caches ``local_devices``!) silently
    # hands the RETIRED client's devices to the next world, and every
    # launch built on them chains behind the dead world's poisoned
    # dispatch events
    for name in dir(xb):
        fn = getattr(xb, name, None)
        if callable(fn) and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    xb._clear_backends()
    gc.collect()


def _reset_orbax_barrier_counters() -> None:
    """Zero orbax's process-local barrier-key counters on EVERY member of a
    freshly established world. Orbax bakes ``itertools.count()`` ordinals
    into its cross-process barrier keys (``AsyncCheckpointer.__init__``
    takes one per construction, saves take one per operation) — a survivor
    that has built managers in earlier generations carries a higher count
    than a just-spawned joiner, so their keys for the SAME logical barrier
    hash differently and the sync fails (observed:
    ``'0_Checkpointer:restore.2'`` vs the survivor's ``'2_…'``). After this
    reset both sides perform identical checkpoint-operation sequences, so
    the counters advance in lockstep."""
    try:
        import itertools

        from orbax.checkpoint.multihost import counters
    except Exception:  # noqa: BLE001 — orbax optional / layout drift
        return
    for name in dir(counters):
        if name.startswith("_") and name.endswith("_counter"):
            try:
                setattr(counters, name, itertools.count())
            except Exception:  # noqa: BLE001 — never block a rendezvous on this
                pass


def retire_runtime() -> None:
    """Leak the current distributed client (and service, on the
    coordinator) into :data:`_RETIRED` and purge every jax-level cache that
    pins the old backend or its world shape. After this the process holds
    no usable jax runtime until :meth:`RendezvousStateMachine.establish`
    builds the next one — callers must have snapshotted any device state
    to host first."""
    gs = _global_state()
    if gs.client is not None:
        _RETIRED.append(gs.client)
        gs.client = None
    if gs.service is not None:
        # the old service must OUTLIVE the old clients' error pollers
        # (they poll this process's socket); leaked alongside them
        _RETIRED.append(gs.service)
        gs.service = None
    if gs.preemption_sync_manager is not None:
        # rides the retired client's channel; leaked alongside it
        _RETIRED.append(gs.preemption_sync_manager)
        gs.preemption_sync_manager = None
    reset_backend()
    heartbeat()


def retired_count() -> int:
    """How many runtime objects previous generations leaked (observability
    + tests)."""
    return len(_RETIRED)


def quarantine_runtime(logger=None, tick: Callable = heartbeat) -> int:
    """Verify the re-initialized world's XLA backend dispatches multi-device
    work cleanly, rebuilding it until it does. Returns the number of extra
    rebuilds that were needed.

    Why this exists (empirical, jax 0.4.36 XLA:CPU): a peer dying MID-
    COLLECTIVE leaves the old client's collective-launch serialization chain
    wedged on the half-dead gloo op. The first backend built after
    ``retire_runtime`` can inherit that chain — its very first multi-device
    dispatch then fails with the dead world's error (``Error dispatching
    computation: … Gloo all-reduce failed``) and every later dispatch chains
    one layer deeper, poisoning the new world permanently. A canary dispatch
    detects the inheritance up front, and a fresh clear+rebuild once the old
    chain has resolved comes up clean (observed reliably within a rebuild or
    two). Armored like every other phase: bounded attempts, watchdog ticks,
    and a :class:`RendezvousError` (-> abort-and-resume) when the runtime
    never settles.

    With MULTIPLE surviving processes a canary-driven rebuild re-runs the
    CPU topology exchange against the generation's KV store, so survivors
    must not diverge on their rebuild count. The engine's rebuild-retry
    loop keeps them in lockstep by voting each attempt through
    :meth:`RendezvousStateMachine.rebuild_vote` /
    :meth:`RendezvousStateMachine.rebuild_settled`: a round stands only
    when every survivor's rebuild succeeded, and any failure sends ALL of
    them back around together."""
    gs = _global_state()
    attempts = 4 if gs.num_processes in (None, 1) else 2
    last: Optional[Exception] = None
    for i in range(attempts):
        tick()
        try:
            local_canary_launch()
            if i and logger is not None:
                logger.info(
                    f"rendezvous: runtime quarantine settled after {i} "
                    "extra rebuild(s)"
                )
            return i
        except Exception as e:  # noqa: BLE001 — inherited-chain canary
            last = e
            if logger is not None:
                logger.warning(
                    f"rendezvous: rebuilt runtime inherited the dead "
                    f"world's dispatch chain (attempt {i + 1}/{attempts}): "
                    f"{str(e)[:200]}"
                )
            get_tracer().instant(
                "rdzv_quarantine_rebuild", cat="rdzv",
                args={"attempt": i + 1, "error": str(e)[:160]},
            )
            reset_backend()
            time.sleep(0.5 * (i + 1))
    raise RendezvousError(
        "quarantine",
        f"rebuilt runtime never dispatched cleanly: {last!r}",
    )


class RendezvousStateMachine:
    """File-based propose -> agree -> barrier -> establish consensus over
    the heartbeat directory.

    One instance per process, identified by its ORIGINAL process id (the
    ident its heartbeat beacon file carries). All files live in
    ``rdzv_dir`` = the peer-heartbeat directory, so the failure detector
    and the recovery protocol share one channel:

    * ``propose_g{gen}_r{round}_p{id}.json`` — a survivor's roster view
      (+ the would-be leader's port pick and resume epoch);
    * ``torn_g{gen}_p{id}`` — "my old client is destroyed" (the barrier
      that orders every client teardown before the new service exists);
    * ``ack_g{gen}.json`` — the leader's "service is up" (address + an
      opaque payload, e.g. the deterministically seeded controller
      vectors every process must adopt);
    * ``join_p{id}.json`` — a (re)spawned process offering to join at the
      next epoch boundary;
    * ``loss_g{gen}_p{id}.json`` — a survivor's published loss verdict, so
      detection is coherent across survivors whose beacon scans lag;
    * ``done_p{id}`` — clean-exit ordering (the coordinator process exits
      last: retired clients' error pollers watch its sockets).
    """

    def __init__(
        self,
        rdzv_dir: str,
        ident: int,
        gen: int = 0,
        logger=None,
        tick: Callable = heartbeat,
    ):
        self.rdzv_dir = rdzv_dir
        self.ident = int(ident)
        self.gen = int(gen)
        self.logger = logger
        self.tick = tick
        os.makedirs(rdzv_dir, exist_ok=True)

    # ------------------------------------------------------------- scanning

    def alive_procs(self, stale_s: Optional[float] = None) -> Set[int]:
        """Process ids with a fresh beacon and no watchdog exit tag (self
        included — its own beacon thread keeps it fresh)."""
        from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
            ProcessHeartbeat,
        )

        if stale_s is None:
            stale_s = _env_timeout("DBS_PEER_HB_STALE_S", 10.0)
        out: Set[int] = set()
        for ident, info in ProcessHeartbeat.scan(self.rdzv_dir).items():
            m = re.fullmatch(r"proc(\d+)", ident)
            if m and not ProcessHeartbeat.is_stale(info, stale_s):
                out.add(int(m.group(1)))
        out.add(self.ident)
        return out

    def offer_join(self) -> None:
        """(Re)spawned process: offer to join at the next epoch boundary.
        Idempotent; survivors pick it up via :meth:`pending_joins`."""
        _write_json(
            os.path.join(self.rdzv_dir, f"join_p{self.ident}.json"),
            {"ident": self.ident},
        )
        get_tracer().instant(
            "rdzv_offer_join", cat="rdzv", args={"ident": self.ident}
        )

    def pending_joins(self) -> Set[int]:
        out: Set[int] = set()
        for path in glob.glob(os.path.join(self.rdzv_dir, "join_p*.json")):
            info = _read_json(path)
            if info is not None:
                out.add(int(info["ident"]))
        return out

    def clear_join(self, ident: Optional[int] = None) -> None:
        ident = self.ident if ident is None else int(ident)
        try:
            os.remove(os.path.join(self.rdzv_dir, f"join_p{ident}.json"))
        except OSError:
            pass

    def current_roster(self) -> List[int]:
        """ORIGINAL process ids of the newest ESTABLISHED generation (the
        newest ack file), adopting that generation as :attr:`gen`. Empty
        when no ack exists — a world brought up by the stock initializer
        writes none; callers fall back to ``range(num_processes)``."""
        best: Optional[Dict] = None
        best_gen = -1
        for path in glob.glob(os.path.join(self.rdzv_dir, "ack_g*.json")):
            m = re.search(r"ack_g(\d+)\.json$", path)
            if not m or int(m.group(1)) <= best_gen:
                continue
            info = _read_json(path)
            if info is not None:
                best, best_gen = info, int(m.group(1))
        if best is None:
            return []
        self.gen = max(self.gen, best_gen)
        return [int(p) for p in best.get("roster", ())]

    # ----------------------------------------------------- loss coherence

    def claim_loss(self, dead: Iterable[int], epoch: int) -> None:
        """Publish this survivor's loss verdict so peers whose beacon scan
        lags adopt it at their next boundary instead of dispatching one
        more collective against the dead process."""
        dead = sorted(int(d) for d in dead)
        _write_json(
            os.path.join(self.rdzv_dir, f"loss_g{self.gen}_p{self.ident}.json"),
            {"dead": dead, "epoch": int(epoch)},
        )
        get_tracer().instant(
            "rdzv_claim_loss", cat="rdzv",
            args={"gen": self.gen, "dead": dead, "epoch": int(epoch)},
        )

    def claimed_losses(self) -> Set[int]:
        """Union of every survivor's published loss verdict for the CURRENT
        generation (older generations' claims are resolved history)."""
        out: Set[int] = set()
        pat = os.path.join(self.rdzv_dir, f"loss_g{self.gen}_p*.json")
        for path in glob.glob(pat):
            info = _read_json(path)
            if info is not None:
                out.update(int(d) for d in info.get("dead", ()))
        return out

    # ------------------------------------------------------- probe exchange

    def publish_probe(self, costs: Dict[int, float]) -> None:
        """Publish this process's measured per-worker compute costs
        (seconds/example, keyed by ORIGINAL worker rank) for the CURRENT
        generation — the grow-path share-seeding exchange (ISSUE 17): after
        a join rendezvous every member publishes what it measured locally
        and reads everyone else's, so survivors and the joiner all seed the
        SAME equilibrium share vector instead of guessing the joiner in at
        the survivor mean. Gen-tagged like every consensus file (a stale
        generation's costs must never seed a newer fleet) and atomic like
        every JSON write. An empty map is a valid publication: "I measured
        nothing" is itself the signal peers must not wait on."""
        _write_json(
            os.path.join(
                self.rdzv_dir, f"probe_g{self.gen}_p{self.ident}.json"
            ),
            {
                "ident": self.ident,
                "costs": {str(r): float(c) for r, c in costs.items()},
            },
        )

    def collect_probes(
        self, procs: Iterable[int], timeout_s: Optional[float] = None
    ) -> Optional[Dict[int, float]]:
        """Read every listed process's probe publication for the CURRENT
        generation, waiting (bounded, ``DBS_RDZV_PROBE_S``) for stragglers.
        Returns the merged rank -> cost map only when EVERY process's file
        arrived — a partial exchange returns None and the caller keeps its
        deterministic fallback seeding: all members must assemble the
        identical vector or none of them use the exchange."""
        if timeout_s is None:
            timeout_s = _env_timeout("DBS_RDZV_PROBE_S", 20.0)
        want = sorted(int(p) for p in procs)
        merged: Dict[int, float] = {}
        got: Set[int] = set()
        deadline = time.monotonic() + timeout_s
        last_tick = 0.0
        while True:
            for p in want:
                if p in got:
                    continue
                info = _read_json(
                    os.path.join(self.rdzv_dir, f"probe_g{self.gen}_p{p}.json")
                )
                if info is not None:
                    got.add(p)
                    for r, c in (info.get("costs") or {}).items():
                        merged[int(r)] = float(c)
            if len(got) == len(want):
                return merged
            now = time.monotonic()
            if now >= deadline:
                return None
            if now - last_tick >= _TICK_EVERY_S:
                last_tick = now
                self.tick()
            time.sleep(_POLL_S)

    # --------------------------------------------------- rebuild coherence

    def rebuild_vote(self, attempt: int, ok: bool) -> None:
        """Publish this survivor's verdict on rebuild round ``attempt`` of
        the CURRENT generation (ISSUE 18: the multi-survivor lift of the
        rebuild retry loop). The engine's post-establish world rebuild —
        quarantine canary, re-shard, state re-placement — retries locally
        when the new backend inherited the dead world's dispatch chain;
        with several survivors those retry counts used to be process-local,
        so one survivor could advance to the next attempt's collectives
        while a peer was still tearing its backend down. Votes make the
        round a unit: every survivor publishes ok/failed, and the round
        only stands when ALL of them succeeded."""
        _write_json(
            os.path.join(
                self.rdzv_dir,
                f"rebuild_g{self.gen}_a{int(attempt)}_p{self.ident}.json",
            ),
            {"ident": self.ident, "ok": bool(ok)},
        )
        get_tracer().instant(
            "rdzv_rebuild_vote", cat="rdzv",
            args={"gen": self.gen, "attempt": int(attempt), "ok": bool(ok)},
        )

    def rebuild_settled(
        self,
        procs: Iterable[int],
        attempt: int,
        timeout_s: Optional[float] = None,
    ) -> bool:
        """Collect every listed survivor's vote for rebuild round
        ``attempt``: True only when ALL voted ok (the callers may adopt the
        rebuilt world), False when any voted failed (every caller — the
        locally-successful ones included — tears down and retries the next
        round in lockstep). A survivor whose vote never lands within
        ``DBS_RDZV_REBUILD_S`` raises :class:`RendezvousTimeout` — it died
        or wedged mid-rebuild, and waiting longer just hides a second
        failure inside the first recovery."""
        if timeout_s is None:
            timeout_s = _env_timeout("DBS_RDZV_REBUILD_S", 60.0)
        want = sorted(int(p) for p in procs)
        votes: Dict[int, bool] = {}

        def _collected() -> bool:
            for p in want:
                if p in votes:
                    continue
                info = _read_json(
                    os.path.join(
                        self.rdzv_dir,
                        f"rebuild_g{self.gen}_a{int(attempt)}_p{p}.json",
                    )
                )
                if info is not None:
                    votes[p] = bool(info.get("ok"))
            return len(votes) == len(want)

        self._wait(_collected, timeout_s, f"rebuild-vote[{int(attempt)}]")
        return all(votes.values())

    # ----------------------------------------------------------- consensus

    def _disk_gen(self) -> int:
        gens = [0]
        for path in glob.glob(os.path.join(self.rdzv_dir, "ack_g*.json")):
            m = re.search(r"ack_g(\d+)\.json$", path)
            if m:
                gens.append(int(m.group(1)))
        return max(gens)

    def _wait(
        self, cond: Callable[[], bool], timeout_s: float, phase: str
    ) -> None:
        """Poll ``cond`` until true; tick the stall watchdog about once a
        second so the wait never reads as a device hang; hard-timeout into
        :class:`RendezvousTimeout` tagged with the phase."""
        deadline = time.monotonic() + timeout_s
        last_tick = 0.0
        while not cond():
            now = time.monotonic()
            if now >= deadline:
                get_tracer().instant(
                    "rdzv_timeout", cat="rdzv", args={"phase": phase}
                )
                raise RendezvousTimeout(phase)
            if now - last_tick >= _TICK_EVERY_S:
                last_tick = now
                self.tick()
            time.sleep(_POLL_S)

    def agree(
        self,
        alive_fn: Callable[[], Set[int]],
        epoch: int,
        timeout_s: Optional[float] = None,
    ) -> Agreement:
        """Roster consensus for the next generation: every member of the
        agreed roster posted an identical roster view. Divergent views
        (peers dying DURING the rendezvous, joiners racing in) converge by
        intersecting the posted views with the live beacon scan and
        advancing to a new proposal round; bounded rounds + a hard
        timeout, so a wedged peer degrades the rendezvous instead of
        hanging it."""
        timeout_s = rendezvous_timeout_s() if timeout_s is None else timeout_s
        tracer = get_tracer()
        with tracer.span("rdzv_agree", cat="recover"):
            gen = max(self.gen, self._disk_gen()) + 1
            my_port = _pick_port()
            deadline = time.monotonic() + timeout_s
            roster = sorted(alive_fn())
            for rnd in range(8):
                if self.ident not in roster or not roster:
                    raise RendezvousError(
                        "propose", f"evicted from roster {roster}"
                    )
                _write_json(
                    os.path.join(
                        self.rdzv_dir,
                        f"propose_g{gen}_r{rnd}_p{self.ident}.json",
                    ),
                    {"roster": roster, "port": my_port, "epoch": int(epoch)},
                )
                views: Dict[int, Dict] = {}
                advance = False
                last_tick = 0.0
                while True:
                    now = time.monotonic()
                    if now >= deadline:
                        missing = [p for p in roster if p not in views]
                        raise RendezvousTimeout(
                            f"propose[r{rnd}] waiting for proc(s) {missing}"
                        )
                    if now - last_tick >= _TICK_EVERY_S:
                        last_tick = now
                        self.tick()
                    for p in roster:
                        if p in views:
                            continue
                        got = _read_json(
                            os.path.join(
                                self.rdzv_dir,
                                f"propose_g{gen}_r{rnd}_p{p}.json",
                            )
                        )
                        if got is not None:
                            views[p] = got
                    if len(views) == len(roster):
                        rosters = {tuple(v["roster"]) for v in views.values()}
                        if len(rosters) == 1 and next(iter(rosters)) == tuple(
                            roster
                        ):
                            leader = roster[0]
                            agreed_epoch = max(
                                int(v["epoch"]) for v in views.values()
                            )
                            port = int(views[leader]["port"])
                            self.log(
                                f"rendezvous g{gen}: roster {roster} agreed "
                                f"(round {rnd}, leader proc{leader}, "
                                f"port {port}, epoch {agreed_epoch})"
                            )
                            tracer.instant(
                                "rdzv_agreed", cat="rdzv",
                                args={
                                    "gen": gen,
                                    "roster": list(roster),
                                    "round": rnd,
                                    "leader": leader,
                                    "epoch": agreed_epoch,
                                },
                            )
                            return Agreement(
                                gen=gen,
                                roster=tuple(roster),
                                rank=roster.index(self.ident),
                                address=f"localhost:{port}",
                                epoch=agreed_epoch,
                            )
                        advance = True
                    else:
                        # a peer we wait on may have died mid-rendezvous:
                        # refresh the live view and re-round without it
                        live = alive_fn()
                        if sorted(set(roster) & live) != roster:
                            advance = True
                    if advance:
                        merged: Set[int] = set(roster)
                        for v in views.values():
                            merged &= set(v["roster"])
                        merged &= alive_fn()
                        merged.add(self.ident)
                        roster = sorted(merged)
                        break
                    time.sleep(_POLL_S)
            raise RendezvousError("propose", "no roster consensus in 8 rounds")

    def establish(
        self,
        agreement: Agreement,
        payload: Optional[Dict] = None,
        timeout_s: Optional[float] = None,
    ) -> Dict:
        """Bring up the agreed world: barrier on every member's client
        teardown (``torn`` files — the old clients' error pollers must all
        be gone before any new-world traffic), leader starts the new
        coordination service and publishes the ack (+ ``payload``, the
        replicated-controller seed every process adopts), everyone
        connects. The caller must have called :func:`retire_runtime` (or
        never held a runtime: joiners). Returns the ack payload."""
        timeout_s = rendezvous_timeout_s() if timeout_s is None else timeout_s
        tracer = get_tracer()
        with tracer.span("rdzv_establish", cat="recover"):
            gen, roster = agreement.gen, list(agreement.roster)
            gs = _global_state()
            if gs.client is not None:
                raise RuntimeError(
                    "establish() with a live distributed client — call "
                    "retire_runtime() first"
                )
            open(
                os.path.join(self.rdzv_dir, f"torn_g{gen}_p{self.ident}"), "w"
            ).close()
            tracer.instant(
                "rdzv_torn", cat="rdzv", args={"gen": gen, "ident": self.ident}
            )
            self._wait(
                lambda: all(
                    os.path.exists(
                        os.path.join(self.rdzv_dir, f"torn_g{gen}_p{p}")
                    )
                    for p in roster
                ),
                timeout_s,
                f"teardown barrier g{gen}",
            )
            gs.process_id = agreement.rank
            gs.num_processes = len(roster)
            gs.coordinator_address = agreement.address
            ack_path = os.path.join(self.rdzv_dir, f"ack_g{gen}.json")
            if agreement.leader:
                gs.service = retry_transient(
                    lambda: _make_service(agreement.address, len(roster)),
                    retries=2,
                    desc="rendezvous service bring-up",
                    tick=self.tick,
                )
                _write_json(
                    ack_path,
                    {
                        "address": agreement.address,
                        "roster": roster,
                        "epoch": agreement.epoch,
                        "payload": payload or {},
                    },
                )
                ack = _read_json(ack_path)
            else:
                self._wait(
                    lambda: _read_json(ack_path) is not None,
                    timeout_s,
                    f"service ack g{gen}",
                )
                ack = _read_json(ack_path)
            client = _make_client(
                agreement.address, agreement.rank, timeout_s
            )
            try:
                retry_transient(
                    client.connect,
                    retries=1,
                    desc=f"rendezvous g{gen} connect",
                    tick=self.tick,
                )
            except Exception as e:  # noqa: BLE001 — degrade, don't hang
                raise RendezvousError(
                    f"connect g{gen}", f"connect to {agreement.address}: {e!r}"
                )
            gs.client = client
            _arm_preemption_sync(gs, client)
            _reset_orbax_barrier_counters()
            self.gen = gen
            self.tick()
            self.log(
                f"rendezvous g{gen}: world established over {roster} "
                f"(rank {agreement.rank}/{len(roster)} at {agreement.address})"
            )
            tracer.instant(
                "rdzv_established", cat="rdzv",
                args={
                    "gen": gen,
                    "roster": list(roster),
                    "rank": agreement.rank,
                    "address": agreement.address,
                },
            )
            return dict(ack.get("payload") or {}) if ack else {}

    # -------------------------------------------------------- exit protocol

    def finalize(self, timeout_s: float = 30.0) -> None:
        """Clean-exit ordering: every process drops a ``done`` file; the
        generation-0 COORDINATOR process (ident 0 — retired clients' error
        pollers point at its sockets) waits for every still-live peer's
        done file plus a short grace before returning, so it is the last
        to exit and no peer's poller ever sees its sockets close."""
        open(os.path.join(self.rdzv_dir, f"done_p{self.ident}"), "w").close()
        if self.ident != 0:
            return
        peers = self.alive_procs() - {self.ident}
        try:
            self._wait(
                lambda: all(
                    os.path.exists(
                        os.path.join(self.rdzv_dir, f"done_p{p}")
                    )
                    for p in self.alive_procs() - {self.ident}
                ),
                timeout_s,
                "exit drain",
            )
        except RendezvousTimeout:
            self.log(f"exit drain timed out waiting for {sorted(peers)}")
        time.sleep(0.5)  # grace: peers' interpreters finish exiting

    def log(self, msg: str) -> None:
        if self.logger is not None:
            self.logger.info(msg)


def join_elastic_world(
    rdzv_dir: str,
    ident: int,
    timeout_s: Optional[float] = None,
    logger=None,
    tick: Callable = heartbeat,
) -> Tuple[RendezvousStateMachine, Agreement, Dict]:
    """A (re)spawned process joins the running fleet at the survivors' next
    epoch boundary: beacon first (the survivors' boundary scan must see a
    FRESH pulse or the roster intersection evicts us), offer the join, then
    enter the same propose → agree → barrier protocol the survivors run —
    their boundary-side :meth:`pending_joins` check is what starts the
    round, so the join timeout must cover at least one of their epochs
    (``DBS_RDZV_JOIN_TIMEOUT_S``, default 600s). The caller must NOT have a
    live ``jax.distributed`` runtime yet; after this returns, jax sees the
    grown world and the caller builds its engine over it (restoring
    training state from the shared checkpoint directory). Returns
    ``(state_machine, agreement, ack payload)``."""
    if timeout_s is None:
        timeout_s = _env_timeout("DBS_RDZV_JOIN_TIMEOUT_S", 600.0)
    sm = RendezvousStateMachine(rdzv_dir, ident, logger=logger, tick=tick)
    sm.current_roster()  # adopt the live generation before proposing past it
    sm.offer_join()
    agreement = sm.agree(
        lambda: sm.alive_procs() - sm.claimed_losses(),
        epoch=0,
        timeout_s=timeout_s,
    )
    payload = sm.establish(agreement, timeout_s=timeout_s)
    sm.clear_join()
    return sm, agreement, payload
