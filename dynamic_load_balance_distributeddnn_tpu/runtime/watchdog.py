"""Stall watchdog for device-blocking host loops.

This host's TPU attaches through a tunnel that can drop mid-run. When it
does, a blocked PJRT call (compile RPC, ``device_put``, ``block_until_ready``)
hangs *inside C++* where Python signal handlers never run — the process sits
at 0% CPU until an outer timeout fires, burning the whole budget (observed:
the round-3 bench's on-arm warm loop hung ~45 min against a dead tunnel).

The reference has no analogue (its gloo backend raises on peer loss); this is
tunnel-environment armor. Mechanism: host-side loops call :func:`heartbeat`
whenever control returns from the device (one warm compile done, one step
dispatched, one epoch recorded). :func:`arm_stall_watchdog` starts a daemon
thread that hard-exits the process (``os._exit``, the only reliable abort for
a C++-blocked process) when the heartbeat file goes stale — turning a silent
multi-hour hang into a bounded, retryable subprocess failure.

Opt-in: nothing is armed unless a caller arms it, and ``heartbeat()`` is a
no-op unless ``DBS_HEARTBEAT_FILE`` is set (one getenv + utime when active).
"""

from __future__ import annotations

import os
import sys
import threading
import time

from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer

_ENV = "DBS_HEARTBEAT_FILE"


def heartbeat() -> None:
    """Touch the heartbeat file, if one is configured. With graftscope
    tracing on, each heartbeat additionally lands as an instant event in the
    trace — the device-answered pulse train, visible between spans."""
    tracer = get_tracer()
    if tracer.enabled:
        tracer.instant("heartbeat", cat="heartbeat")
    path = os.environ.get(_ENV)
    if not path:
        return
    try:
        os.utime(path, None)
    except OSError:
        try:
            with open(path, "a"):
                pass
        except OSError:
            pass


def arm_stall_watchdog(
    hb_path: str,
    stall_s: float,
    extra_paths: tuple = (),
    exit_code: int = 19,
    poll_s: float = 15.0,
    first_grace_s: float | None = None,
) -> threading.Thread:
    """Arm a daemon thread that ``os._exit(exit_code)``s this process when
    ``hb_path`` (and every path in ``extra_paths``) has not been touched for
    ``stall_s`` seconds. Sets ``DBS_HEARTBEAT_FILE`` so in-process
    :func:`heartbeat` calls (and those of any child sharing the env) land on
    ``hb_path``. Returns the thread (daemon; dies with the process).

    ``first_grace_s``: stall threshold applied until the FIRST heartbeat
    lands after arming. Heartbeats fire when control returns from the
    device, and the very first unit of work includes the cold XLA compile —
    which through the tunnel can legitimately exceed ``stall_s`` (observed:
    the packed DenseNet epoch-0 compile ran past the 900s default and a
    healthy run was killed, wasting the compile AND re-paying it on retry,
    since a killed compile writes nothing to the persistent cache — a
    compile slower than ``stall_s`` would dead-loop every retry). Default:
    ``DBS_WATCHDOG_FIRST_GRACE_S`` env, else 1800s, floored at ``stall_s``.
    Once any heartbeat arrives the tight ``stall_s`` applies."""
    os.environ[_ENV] = hb_path
    if first_grace_s is None:
        first_grace_s = float(os.environ.get("DBS_WATCHDOG_FIRST_GRACE_S", 1800))
    first_grace_s = max(float(first_grace_s), float(stall_s))
    armed_at = time.time()
    hb_baseline: float | None = None
    try:
        parent = os.path.dirname(hb_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(hb_path, "a"):
            pass
        # backdate the arm-time touch so a real heartbeat strictly advances
        # the mtime even on filesystems with coarse (1-2s) granularity;
        # staleness itself is governed by max(armed_at, mtimes), which the
        # backdating cannot lower
        os.utime(hb_path, (armed_at - 10.0, armed_at - 10.0))
        hb_baseline = os.path.getmtime(hb_path)
    except OSError:
        pass

    def _newest_mtime() -> float:
        # fall back to the arm timestamp so the watchdog fails CLOSED even if
        # no watched path could be created (it must still catch a hang that
        # starts before the first heartbeat lands)
        newest = armed_at
        for p in (hb_path, *extra_paths):
            try:
                newest = max(newest, os.path.getmtime(p))
            except OSError:
                pass
        return newest

    def _watch() -> None:
        # cold-start grace: until the heartbeat file itself has been touched
        # after arming (i.e. the device has answered once), allow the longer
        # first_grace_s — the first unit of work carries the cold compile,
        # which is slow but healthy. Keyed to hb_path's mtime advancing past
        # the arm-time touch: extra_paths get administrative writes (e.g.
        # the bench's initial incremental-result dump) before any device
        # work, which must not end the grace. If the hb file could not be
        # created at all, heartbeats can never land, so the grace could
        # never end — skip it entirely (fail closed at the tight stall_s).
        grace_active = hb_baseline is not None
        while True:
            time.sleep(poll_s)
            if grace_active:
                try:
                    if os.path.getmtime(hb_path) > hb_baseline:
                        grace_active = False
                except OSError:
                    pass
            last = _newest_mtime()
            threshold = first_grace_s if grace_active else stall_s
            if time.time() - last > threshold:
                sys.stderr.write(
                    f"[watchdog] no heartbeat for {threshold:.0f}s "
                    f"(device RPC hang?); aborting\n"
                )
                sys.stderr.flush()
                os._exit(exit_code)

    t = threading.Thread(target=_watch, daemon=True, name="stall-watchdog")
    t.start()
    return t
