"""Stall watchdog for device-blocking host loops.

This host's TPU attaches through a tunnel that can drop mid-run. When it
does, a blocked PJRT call (compile RPC, ``device_put``, ``block_until_ready``)
hangs *inside C++* where Python signal handlers never run — the process sits
at 0% CPU until an outer timeout fires, burning the whole budget (observed:
the round-3 bench's on-arm warm loop hung ~45 min against a dead tunnel).

The reference has no analogue (its gloo backend raises on peer loss); this is
tunnel-environment armor. Mechanism: host-side loops call :func:`heartbeat`
whenever control returns from the device (one warm compile done, one step
dispatched, one epoch recorded). :func:`arm_stall_watchdog` starts a daemon
thread that hard-exits the process (``os._exit``, the only reliable abort for
a C++-blocked process) when the heartbeat file goes stale — turning a silent
multi-hour hang into a bounded, retryable subprocess failure.

Opt-in: nothing is armed unless a caller arms it, and ``heartbeat()`` is a
no-op unless ``DBS_HEARTBEAT_FILE`` is set (one getenv + utime when active).
"""

from __future__ import annotations

import os
import sys
import threading
import time

_ENV = "DBS_HEARTBEAT_FILE"


def heartbeat() -> None:
    """Touch the heartbeat file, if one is configured."""
    path = os.environ.get(_ENV)
    if not path:
        return
    try:
        os.utime(path, None)
    except OSError:
        try:
            with open(path, "a"):
                pass
        except OSError:
            pass


def arm_stall_watchdog(
    hb_path: str,
    stall_s: float,
    extra_paths: tuple = (),
    exit_code: int = 19,
    poll_s: float = 15.0,
) -> threading.Thread:
    """Arm a daemon thread that ``os._exit(exit_code)``s this process when
    ``hb_path`` (and every path in ``extra_paths``) has not been touched for
    ``stall_s`` seconds. Sets ``DBS_HEARTBEAT_FILE`` so in-process
    :func:`heartbeat` calls (and those of any child sharing the env) land on
    ``hb_path``. Returns the thread (daemon; dies with the process)."""
    os.environ[_ENV] = hb_path
    armed_at = time.time()
    try:
        parent = os.path.dirname(hb_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(hb_path, "a"):
            pass
        os.utime(hb_path, None)
    except OSError:
        pass

    def _newest_mtime() -> float:
        # fall back to the arm timestamp so the watchdog fails CLOSED even if
        # no watched path could be created (it must still catch a hang that
        # starts before the first heartbeat lands)
        newest = armed_at
        for p in (hb_path, *extra_paths):
            try:
                newest = max(newest, os.path.getmtime(p))
            except OSError:
                pass
        return newest

    def _watch() -> None:
        while True:
            time.sleep(poll_s)
            last = _newest_mtime()
            if time.time() - last > stall_s:
                sys.stderr.write(
                    f"[watchdog] no heartbeat for {stall_s:.0f}s "
                    f"(device RPC hang?); aborting\n"
                )
                sys.stderr.flush()
                os._exit(exit_code)

    t = threading.Thread(target=_watch, daemon=True, name="stall-watchdog")
    t.start()
    return t
