"""Stall watchdog for device-blocking host loops.

This host's TPU attaches through a tunnel that can drop mid-run. When it
does, a blocked PJRT call (compile RPC, ``device_put``, ``block_until_ready``)
hangs *inside C++* where Python signal handlers never run — the process sits
at 0% CPU until an outer timeout fires, burning the whole budget (observed:
the round-3 bench's on-arm warm loop hung ~45 min against a dead tunnel).

The reference has no analogue (its gloo backend raises on peer loss); this is
tunnel-environment armor. Mechanism: host-side loops call :func:`heartbeat`
whenever control returns from the device (one warm compile done, one step
dispatched, one epoch recorded). :func:`arm_stall_watchdog` starts a daemon
thread that hard-exits the process (``os._exit``, the only reliable abort for
a C++-blocked process) when the heartbeat file goes stale — turning a silent
multi-hour hang into a bounded, retryable subprocess failure.

Opt-in: nothing is armed unless a caller arms it, and ``heartbeat()`` is a
no-op unless ``DBS_HEARTBEAT_FILE`` is set (one getenv + utime when active).
"""

from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time

from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer

_ENV = "DBS_HEARTBEAT_FILE"
_EXIT_TAG = "DBS_WATCHDOG_EXIT "

# Extra files the abort path tags alongside its own heartbeat file — the
# per-process PEER beacon (runtime/health.py) registers here, so a watchdog
# abort is readable by the peers scanning DBS_PEER_HB_DIR, not just by the
# parent watching this process's own heartbeat file.
_EXTRA_TAG_PATHS: set = set()


def register_exit_tag_path(path: str) -> None:
    """Tag ``path`` too when the stall watchdog aborts this process."""
    _EXTRA_TAG_PATHS.add(path)


def unregister_exit_tag_path(path: str) -> None:
    """Drop a registered tag path (the owning run ended: its beacon file
    must not be rewritten by a later run's abort)."""
    _EXTRA_TAG_PATHS.discard(path)


def tag_exit_all(hb_path: str, reason: str) -> None:
    """Tag the watchdog's own heartbeat file AND every registered peer
    beacon file with the abort reason. Last-breath code: a concurrent
    register/unregister (a finalizer on another thread) must not raise out
    of the watchdog thread — that would leave the wedged process it exists
    to abort hanging forever."""
    try:
        paths = {hb_path} | set(tuple(_EXTRA_TAG_PATHS))
    except RuntimeError:  # set mutated mid-copy: settle for our own file
        paths = {hb_path}
    for p in paths:
        tag_exit_reason(p, reason)


def tag_exit_reason(hb_path: str, reason: str) -> None:
    """Write the abort reason INTO the heartbeat file, so the parent (bench
    retry loop, multi-host peer scanning the heartbeat dir) can tell a
    watchdog abort apart from a silent freeze or an OOM kill. The tag
    replaces the file's (empty) pulse content; the mtime pulse semantics are
    moot once the process is about to ``os._exit``."""
    try:
        with open(hb_path, "w") as f:
            f.write(f"{_EXIT_TAG}{reason}\n")
    except OSError:
        pass


def read_exit_reason(hb_path: str):
    """The exit-reason tag a watchdog left in ``hb_path``, or None (absent
    file, unreadable file, or a plain pulse file with no tag)."""
    try:
        with open(hb_path) as f:
            head = f.read(4096)
    except OSError:
        return None
    if head.startswith(_EXIT_TAG):
        return head[len(_EXIT_TAG):].strip()
    return None


def _dump_all_stacks(reason: str) -> None:
    """Post-mortem for the C++-blocked hang: Python-level stacks of every
    thread, via faulthandler (safe to call with the GIL held by *this*
    thread while another is wedged in a PJRT RPC). Lands on stderr, which
    the run log / parent subprocess captures — the only diagnosable record
    of WHERE the process was stuck, since ``os._exit`` skips every
    destructor and atexit hook."""
    try:
        sys.stderr.write(f"[watchdog] {reason}; all-thread stacks:\n")
        sys.stderr.flush()
        faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        sys.stderr.flush()
    except Exception:  # noqa: BLE001 — last-breath diagnostics must not mask the exit
        pass


def heartbeat() -> None:
    """Touch the heartbeat file, if one is configured. With graftscope
    tracing on, each heartbeat additionally lands as an instant event in the
    trace — the device-answered pulse train, visible between spans."""
    tracer = get_tracer()
    if tracer.enabled:
        tracer.instant("heartbeat", cat="heartbeat")
    path = os.environ.get(_ENV)
    if not path:
        return
    try:
        os.utime(path, None)
    except OSError:
        try:
            with open(path, "a"):
                pass
        except OSError:
            pass


def arm_stall_watchdog(
    hb_path: str,
    stall_s: float,
    extra_paths: tuple = (),
    exit_code: int = 19,
    poll_s: float = 15.0,
    first_grace_s: float | None = None,
) -> threading.Thread:
    """Arm a daemon thread that ``os._exit(exit_code)``s this process when
    ``hb_path`` (and every path in ``extra_paths``) has not been touched for
    ``stall_s`` seconds. Sets ``DBS_HEARTBEAT_FILE`` so in-process
    :func:`heartbeat` calls (and those of any child sharing the env) land on
    ``hb_path``. Returns the thread (daemon; dies with the process).

    ``first_grace_s``: stall threshold applied until the FIRST heartbeat
    lands after arming. Heartbeats fire when control returns from the
    device, and the very first unit of work includes the cold XLA compile —
    which through the tunnel can legitimately exceed ``stall_s`` (observed:
    the packed DenseNet epoch-0 compile ran past the 900s default and a
    healthy run was killed, wasting the compile AND re-paying it on retry,
    since a killed compile writes nothing to the persistent cache — a
    compile slower than ``stall_s`` would dead-loop every retry). Default:
    ``DBS_WATCHDOG_FIRST_GRACE_S`` env, else 1800s, floored at ``stall_s``.
    Once any heartbeat arrives the tight ``stall_s`` applies."""
    os.environ[_ENV] = hb_path
    if first_grace_s is None:
        first_grace_s = float(os.environ.get("DBS_WATCHDOG_FIRST_GRACE_S", 1800))
    first_grace_s = max(float(first_grace_s), float(stall_s))
    armed_at = time.time()
    hb_baseline: float | None = None
    try:
        parent = os.path.dirname(hb_path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(hb_path, "a"):
            pass
        # backdate the arm-time touch so a real heartbeat strictly advances
        # the mtime even on filesystems with coarse (1-2s) granularity;
        # staleness itself is governed by max(armed_at, mtimes), which the
        # backdating cannot lower
        os.utime(hb_path, (armed_at - 10.0, armed_at - 10.0))
        hb_baseline = os.path.getmtime(hb_path)
    except OSError:
        pass

    def _newest_mtime() -> float:
        # fall back to the arm timestamp so the watchdog fails CLOSED even if
        # no watched path could be created (it must still catch a hang that
        # starts before the first heartbeat lands)
        newest = armed_at
        for p in (hb_path, *extra_paths):
            try:
                newest = max(newest, os.path.getmtime(p))
            except OSError:
                pass
        return newest

    def _watch() -> None:
        # cold-start grace: until the heartbeat file itself has been touched
        # after arming (i.e. the device has answered once), allow the longer
        # first_grace_s — the first unit of work carries the cold compile,
        # which is slow but healthy. Keyed to hb_path's mtime advancing past
        # the arm-time touch: extra_paths get administrative writes (e.g.
        # the bench's initial incremental-result dump) before any device
        # work, which must not end the grace. If the hb file could not be
        # created at all, heartbeats can never land, so the grace could
        # never end — skip it entirely (fail closed at the tight stall_s).
        grace_active = hb_baseline is not None
        while True:
            time.sleep(poll_s)
            if grace_active:
                try:
                    if os.path.getmtime(hb_path) > hb_baseline:
                        grace_active = False
                except OSError:
                    pass
            last = _newest_mtime()
            threshold = first_grace_s if grace_active else stall_s
            if time.time() - last > threshold:
                reason = (
                    f"stall: no heartbeat for {threshold:.0f}s "
                    "(device RPC hang?)"
                )
                # post-mortem first (stderr -> run log), then the tag the
                # parent reads, then the only reliable abort for a
                # C++-blocked process
                _dump_all_stacks(reason)
                tag_exit_all(hb_path, f"{reason}; exit_code={exit_code}")
                sys.stderr.write(f"[watchdog] {reason}; aborting\n")
                sys.stderr.flush()
                os._exit(exit_code)

    t = threading.Thread(target=_watch, daemon=True, name="stall-watchdog")
    t.start()
    return t
