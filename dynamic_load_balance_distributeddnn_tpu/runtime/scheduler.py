"""Many-stream training engine (ISSUE 18): nested inverse-time DBS
scheduling of concurrent jobs over one device pool.

A job is a VALUE, not a process: :class:`JobSpec` packages everything the
engine's plan→dispatch→record loop needs (config, data bundle, injector,
deterministic timing model) and :class:`MultiStreamEngine` multiplexes many
of them over a single :class:`DevicePool`, admitting and retiring tenants
at outer *window* boundaries (one inner epoch per live job per window).

Two nested solvers share one spine (balance/solver.py):

- **inner** — each tenant's own DBS loop partitions its *examples* over its
  allotted devices, bit-for-bit unchanged from the single-stream engine;
- **outer** — the scheduler partitions the *device pool* over tenants from
  their measured per-example costs. The coupling is inverted relative to
  the inner problem: more devices SHORTEN a tenant's epoch where more
  examples LENGTHEN a worker's step, so the outer solve feeds the solver
  *reciprocal* epoch walls — ``rebalance(1/t, p, P)`` updates device share
  r_j ∝ p_j·t_j, whose fixed point equalizes per-tenant epoch walls at
  d_j ∝ c_j·E_j (device-seconds of demand). ``quantize_batches(·, 1, P)``
  then snaps shares to integer device counts with every tenant kept ≥ 1
  device and the counts summing to the pool.

Actuation rides the engine's planned-re-shard spine: a pool re-allocation
is the ``_maybe_readmit`` recipe (state→host, ``_reshard_world`` to the
new rank set, state→device, comm-residual fix, cost-anchor carry), not a
fault. Admission compiles OFF the critical path: the tenant's trainer is
constructed and warmed at the window boundary, so steady-state windows
dispatch only registry-resolved executables.

Thread/topology discipline: every tenant runs its epochs on its own
``_job_worker`` thread (discovered by the G012 thread inventory); all
cross-thread state is guarded by ONE engine lock. The pool's ordinal→tenant
map is deliberately stored under ``_mesh`` so the allocator sits on the
same analysis surface (``reshard_surface`` / G019 quiesce discipline) as
the engine's mesh rebuilds — re-allocations must be preceded by the pool
quiesce gate, which only opens between windows.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dynamic_load_balance_distributeddnn_tpu.balance.controller import (
    JOURNAL_CAP,
)
from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    equilibrium_shares,
    initial_partition,
    integer_batch_split,
    quantize_batches,
    rebalance,
)
from dynamic_load_balance_distributeddnn_tpu.config import Config
from dynamic_load_balance_distributeddnn_tpu.obs.registry import (
    MetricsRegistry,
)
from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer
from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
    retry_transient,
)

__all__ = ["JobSpec", "JobState", "DevicePool", "MultiStreamEngine"]


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One training job as a value.

    ``config`` describes the job's FULL-FLEET shape: ``world_size`` workers
    mapped onto device ordinals by ``config.worker_device_ids`` (the
    canonical many-stream shape is one worker per pool device:
    ``world_size == pool size``, ``device=None``). The pool allots a subset
    of ordinals; the scheduler activates exactly the ranks living on them
    via the planned-re-shard spine, so a tenant's device footprint can grow
    and shrink across windows without the job ever restarting.

    ``epochs`` caps the job at that many epochs (default: the config's
    ``epoch_size``); ``arrival_window`` delays admission until that outer
    window; ``max_devices`` bounds the tenant's allotment (excess devices
    go to other tenants, or idle)."""

    job_id: str
    config: Config
    bundle: Optional[Any] = None
    injector: Optional[Any] = None
    timing_model: Optional[Callable] = None
    epochs: Optional[int] = None
    arrival_window: int = 0
    max_devices: Optional[int] = None

    def total_epochs(self) -> int:
        return self.config.epoch_size if self.epochs is None else int(self.epochs)


class JobState:
    """Mutable runtime record of one tenant. Every field written after
    admission is guarded by the owning engine's ``_lock`` (the worker
    thread and the scheduler loop both touch it)."""

    def __init__(self, spec: JobSpec):
        self.spec = spec
        self.status = "pending"  # pending→running→finishing→done | failed
        self.trainer = None
        self.devices: Tuple[int, ...] = ()
        self.epochs_done = 0
        self.epoch_walls: List[float] = []
        self.wall_ema: Optional[float] = None
        self.last_wall_s: Optional[float] = None
        self.migrations = 0
        self.admitted_window: Optional[int] = None
        self.makespan_s: Optional[float] = None
        self.recorder = None
        self.retired = False
        self.error: Optional[BaseException] = None
        self.worker_thread: Optional[threading.Thread] = None
        self._go = False
        self._t_admit: Optional[float] = None

    def demand_s(self) -> Optional[float]:
        """Device-seconds of work per epoch (wall × devices) — the
        allocation-invariant cost c_j·E_j the outer solve partitions on."""
        if self.wall_ema is None or not self.devices:
            return None
        return float(self.wall_ema) * len(self.devices)


class DevicePool:
    """Exclusive ordinal→tenant allocator over one accelerator pool.

    The assignment map is deliberately stored under ``self._mesh``: a pool
    re-allocation IS a topology write, so the allocator lands on the same
    analysis surface (``reshard_surface`` discovery, G019 quiesce
    discipline) as the engine's mesh rebuilds. Every ``_mesh`` access holds
    ``self._lock``, and every write is additionally gated by
    :meth:`_quiesce_pool` — re-allocating while any tenant is inside a
    window is a hard error, not a race."""

    def __init__(self, n_devices: int):
        if n_devices < 1:
            raise ValueError("DevicePool needs at least one device")
        self._lock = threading.RLock()
        self._quiesced = True
        self._mesh: Dict[int, Optional[str]] = {
            d: None for d in range(int(n_devices))
        }

    @property
    def n_devices(self) -> int:
        with self._lock:
            return len(self._mesh)

    def allocation(self) -> Dict[str, Tuple[int, ...]]:
        """Current tenant→ordinals view (snapshot, sorted)."""
        with self._lock:
            out: Dict[str, List[int]] = {}
            for d, owner in self._mesh.items():
                if owner is not None:
                    out.setdefault(owner, []).append(d)
            return {job: tuple(sorted(ds)) for job, ds in out.items()}

    def devices_of(self, job_id: str) -> Tuple[int, ...]:
        return self.allocation().get(job_id, ())

    def free_devices(self) -> Tuple[int, ...]:
        with self._lock:
            return tuple(sorted(d for d, o in self._mesh.items() if o is None))

    def begin_window(self) -> None:
        """Tenants are (about to be) inside a window: topology writes are
        now illegal until :meth:`end_window`."""
        with self._lock:
            self._quiesced = False

    def end_window(self) -> None:
        with self._lock:
            self._quiesced = True

    def _quiesce_pool(self) -> None:
        """Topology-write gate (G019 quiesce discipline): a re-allocation
        is legal only while no tenant is mid-window — the scheduler loop
        closes the window (every worker thread parked at the boundary
        barrier) before it re-partitions the pool."""
        if not self._quiesced:
            raise RuntimeError(
                "DevicePool: re-allocation attempted while a window is "
                "open — pool topology writes are only legal between windows"
            )

    def reallocate(
        self, counts: Dict[str, int]
    ) -> Dict[str, Tuple[int, ...]]:
        """Re-partition the pool to ``counts`` devices per tenant with
        minimal movement: each tenant keeps as many of its current ordinals
        as its new count allows before drawing from the freed set. Tenants
        absent from ``counts`` are evicted. Returns tenant→ordinals."""
        with self._lock:
            self._quiesce_pool()
            total = sum(int(c) for c in counts.values())
            if total > len(self._mesh):
                raise ValueError(
                    f"counts sum to {total} devices but the pool has "
                    f"{len(self._mesh)}"
                )
            if any(int(c) < 0 for c in counts.values()):
                raise ValueError("device counts must be non-negative")
            current: Dict[str, List[int]] = {}
            for d, owner in self._mesh.items():
                if owner is not None:
                    current.setdefault(owner, []).append(d)
            new_mesh: Dict[int, Optional[str]] = {d: None for d in self._mesh}
            assigned: Dict[str, List[int]] = {}
            for job, want in counts.items():
                keep = sorted(current.get(job, ()))[: int(want)]
                for d in keep:
                    new_mesh[d] = job
                assigned[job] = keep
            free = iter(sorted(d for d, o in new_mesh.items() if o is None))
            for job, want in counts.items():
                while len(assigned[job]) < int(want):
                    d = next(free)
                    new_mesh[d] = job
                    assigned[job].append(d)
            self._mesh = new_mesh
            return {job: tuple(sorted(ds)) for job, ds in assigned.items()}

    def release(self, job_id: str) -> None:
        """Retire a tenant: free its ordinals (window-boundary only)."""
        with self._lock:
            self._quiesce_pool()
            self._mesh = {
                d: (None if owner == job_id else owner)
                for d, owner in self._mesh.items()
            }


class MultiStreamEngine:
    """Multiplex many :class:`JobSpec` values over one device pool.

    The loop is window-lockstep: per outer window the scheduler (1) admits
    arrivals (trainer construction + warm — ALL compiles off the timed
    path), (2) runs the outer inverse-time solve and actuates any
    re-partition through each affected tenant's planned-re-shard recipe,
    (3) releases every live tenant's worker thread for exactly one inner
    epoch — tenants run concurrently on disjoint device subsets — and
    barriers on the window, (4) retires finished tenants (per-job artifact
    save mirrors the single-stream ``run()`` tail) and frees their devices.

    Hysteresis keeps steady-state re-shards honest: with unchanged
    membership a proposed re-partition only actuates when the modeled
    makespan improvement clears ``outer_margin`` AND the per-run
    ``migration_budget`` is not exhausted; membership changes (admission /
    departure) always re-partition.

    ``wall_model`` (tests): callable(JobState) → synthetic epoch wall
    seconds, replacing the measured wall exactly like the inner loop's
    ``timing_model`` replaces probe walls."""

    #: EMA weight of the newest per-epoch wall in the tenant cost track
    WALL_ALPHA = 0.5

    def __init__(
        self,
        n_devices: Optional[int] = None,
        *,
        outer_margin: float = 0.1,
        migration_budget: Optional[int] = 8,
        wall_model: Optional[Callable[[JobState], float]] = None,
        logger=None,
        log_to_file: bool = False,
    ):
        if n_devices is None:
            import jax

            n_devices = len(jax.local_devices())
        self.pool = DevicePool(n_devices)
        self.outer_margin = float(outer_margin)
        self.migration_budget = migration_budget
        self.wall_model = wall_model
        self.log_to_file = log_to_file
        self.logger = logger or logging.getLogger("graft.scheduler")
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._jobs: Dict[str, JobState] = {}
        self._window = 0
        self._window_done = 0
        self._stop = False
        self._migrations_spent = 0
        self._membership_dirty = False
        self.windows: List[Dict] = []
        # outer decision journal (ISSUE 19): EVERY per-window allocation
        # verdict — hold or migrate — with the inputs it was decided on
        # (epoch-wall EMAs, modeled gain, migration-budget state), in the
        # same journal shape as the inner controller's so the controller
        # lab and `graftscope decisions` cover BOTH nested DBS loops
        self.evals = 0
        self.actuations = 0
        self.journal: deque = deque(maxlen=JOURNAL_CAP)
        self.journal_dropped = 0
        # the scheduler's own registry view: `obs.snapshot()["scheduler"]`
        # is the outer journal's live surface, the pool twin of the inner
        # controller's `["controller"]` section
        self.obs = MetricsRegistry().attach(scheduler=self)

    # ------------------------------------------------------------ submit

    def submit(self, spec: JobSpec) -> JobState:
        if spec.config.elastic == "on":
            raise ValueError(
                "pool tenants must run with elastic=off — the pool "
                "re-allocation IS the elasticity (planned re-shards at "
                "window boundaries)"
            )
        with self._lock:
            if spec.job_id in self._jobs:
                raise ValueError(f"duplicate job id {spec.job_id!r}")
            js = JobState(spec)
            self._jobs[spec.job_id] = js
            return js

    # --------------------------------------------------------------- run

    def run(self, raise_on_failure: bool = True) -> Dict[str, JobState]:
        """Multiplex every submitted job to completion; returns the job
        table. The caller thread is the scheduler."""
        t0 = time.monotonic()
        while True:
            with self._lock:
                jobs = list(self._jobs.values())
                status = {js.spec.job_id: js.status for js in jobs}
            pending = [js for js in jobs if status[js.spec.job_id] == "pending"]
            live = [js for js in jobs if status[js.spec.job_id] == "running"]
            stale = [
                js
                for js in jobs
                if status[js.spec.job_id] in ("finishing", "failed")
                and not js.retired
            ]
            if stale:
                # boundary departures (final epoch done / failed / admitted
                # with zero epochs): retire before the next allocation
                self._retire(stale)
                continue
            if not pending and not live:
                break
            changed = self._membership_dirty
            self._membership_dirty = False
            for js in pending:
                if (
                    js.spec.arrival_window <= self._window
                    and len(live) < self.pool.n_devices
                ):
                    self._admit(js)
                    if js.status == "running":
                        live.append(js)
                    changed = True
            if not live:
                # arrivals gated on a future window — advance time
                self._window += 1
                continue
            self._solve_and_actuate(live, membership_changed=changed)
            self._run_window(live)
            self._window += 1
        self.total_wall_s = time.monotonic() - t0
        failed = [js for js in self._jobs.values() if js.status == "failed"]
        if failed and raise_on_failure:
            raise RuntimeError(
                "job(s) failed: "
                + "; ".join(f"{js.spec.job_id}: {js.error!r}" for js in failed)
            ) from failed[0].error
        return dict(self._jobs)

    # --------------------------------------------------------- admission

    def _admit(self, js: JobState) -> None:
        """Construct + warm the tenant's trainer at the window boundary
        (compiles land OFF the timed window) and start its worker thread.
        Reuses the engine verbatim: the single-stream ``run()`` preamble is
        ``_maybe_warm`` followed by ``run_epoch`` per epoch, and that is
        exactly the sequence a sole tenant sees — the bitwise-parity
        contract of tests/test_scheduler.py rides on it."""
        from dynamic_load_balance_distributeddnn_tpu.train.engine import (
            Trainer,
        )

        spec = js.spec
        get_tracer().instant(
            "job_admitted",
            cat="scheduler",
            args={"job": spec.job_id, "window": int(self._window)},
        )
        self.logger.info(
            f"scheduler: admitting job {spec.job_id!r} at window "
            f"{self._window}"
        )
        tr = Trainer(
            spec.config,
            bundle=spec.bundle,
            injector=spec.injector,
            timing_model=spec.timing_model,
            log_to_file=self.log_to_file,
            job_id=spec.job_id,
        )
        # warm happens in _apply_allotment, AFTER the initial allotment is
        # known — compiling the full-fleet shapes of a tenant about to be
        # shrunk onto a pool slice would be pure waste
        thread = None
        if spec.total_epochs() > 0:
            thread = threading.Thread(
                target=self._job_worker,
                args=(js,),
                name=f"graft-job-{spec.job_id}",
                daemon=True,
            )
        with self._lock:
            js.trainer = tr
            js.status = "running" if thread is not None else "finishing"
            js.admitted_window = self._window
            js._t_admit = time.monotonic()
            js.worker_thread = thread
        if thread is not None:
            thread.start()

    # ------------------------------------------------------- outer solve

    def _outer_counts(self, live: List[JobState]) -> Dict[str, int]:
        """Device counts per tenant from the outer inverse-time solve.

        Measured tenants go through the solver spine with RECIPROCAL epoch
        walls — ``rebalance(1/t, p, P)`` is the share update r_j ∝ p_j·t_j
        whose fixed point equalizes tenant walls (see module docstring);
        tenants without a measured wall yet (fresh admissions) are seeded
        at the median demand, the outer twin of probe-seeded readmission.
        ``quantize_batches(·, bucket=1, global_batch=P)`` snaps to integer
        counts with every tenant ≥ 1 device and the counts summing to P;
        per-spec ``max_devices`` caps are applied last (freed devices go to
        uncapped tenants, else idle)."""
        P = self.pool.n_devices
        n = len(live)
        if n > P:
            raise RuntimeError(
                f"{n} live jobs exceed the {P}-device pool"
            )
        with self._lock:
            walls = [js.wall_ema for js in live]
            cur = [max(len(js.devices), 1) for js in live]
        if all(w is not None and w > 0 for w in walls):
            t = np.asarray(walls, dtype=np.float64)
            p = np.asarray(cur, dtype=np.float64)
            p = p / p.sum()
            new_shares, _ = rebalance(1.0 / t, p, P)
            counts = integer_batch_split(new_shares, P)
        else:
            demands = [
                js.demand_s()
                for js in live
                if js.demand_s() is not None and js.demand_s() > 0
            ]
            seed = float(np.median(demands)) if demands else 1.0
            d = np.array(
                [
                    js.demand_s() if (js.demand_s() or 0) > 0 else seed
                    for js in live
                ],
                dtype=np.float64,
            )
            counts = integer_batch_split(d / d.sum(), P)
        counts = quantize_batches(counts, 1, P)
        out = {js.spec.job_id: int(c) for js, c in zip(live, counts)}
        # per-tenant caps: clip, then hand the excess to uncapped tenants
        # (largest first); devices nobody can take stay idle
        excess = 0
        for js in live:
            cap = js.spec.max_devices
            if cap is not None and out[js.spec.job_id] > cap:
                excess += out[js.spec.job_id] - int(cap)
                out[js.spec.job_id] = int(cap)
        while excess > 0:
            takers = [
                js
                for js in live
                if js.spec.max_devices is None
                or out[js.spec.job_id] < js.spec.max_devices
            ]
            if not takers:
                break
            tgt = min(takers, key=lambda js: out[js.spec.job_id])
            out[tgt.spec.job_id] += 1
            excess -= 1
        return out

    def _record_outer_decision(
        self,
        live: List[JobState],
        proposed: Dict[str, int],
        current: Dict[str, int],
        gain: Optional[float],
        *,
        switch: bool,
        reason: str,
        outcome: str,
        membership_changed: bool,
    ) -> None:
        """Journal one outer evaluation (the many-stream twin of the inner
        controller's ``_record_decision``) and mirror it as a graftscope
        ``decision`` instant. Unlike the inner journal the outcome is known
        at record time — actuation happens inline, there is no warm-gate
        veto between verdict and execution."""
        with self._lock:
            walls = {
                js.spec.job_id: (
                    round(float(js.wall_ema), 6)
                    if js.wall_ema is not None
                    else None
                )
                for js in live
            }
            spent = int(self._migrations_spent)
        ev: Dict = {
            "eval": int(self.evals),
            "switch": bool(switch),
            "reason": reason,
            "outcome": outcome,
            "window": int(self._window),
            "membership_changed": bool(membership_changed),
            "wall_emas": walls,
            "cur_counts": {k: int(v) for k, v in current.items()},
            "proposed_counts": {k: int(v) for k, v in proposed.items()},
            "modeled_gain": round(float(gain), 6) if gain is not None else None,
            "outer_margin": self.outer_margin,
            "migration_budget": self.migration_budget,
            "migrations_spent": spent,
        }
        if len(self.journal) == self.journal.maxlen:
            self.journal_dropped += 1
        self.journal.append(ev)
        tracer = get_tracer()
        if tracer.enabled:
            args = dict(ev)
            if self.journal_dropped:
                args["journal_dropped"] = self.journal_dropped
            tracer.instant("pool_decision", cat="decision", args=args)

    def decision_journal(self) -> List[Dict]:
        """The outer journal as a JSON-safe list (oldest first)."""
        return [dict(ev) for ev in self.journal]

    def _solve_and_actuate(
        self, live: List[JobState], membership_changed: bool
    ) -> None:
        proposed = self._outer_counts(live)
        with self._lock:
            current = {js.spec.job_id: len(js.devices) for js in live}
        gain = self._modeled_gain(live, proposed)
        self.evals += 1
        record = lambda **kw: self._record_outer_decision(  # noqa: E731
            live, proposed, current, gain,
            membership_changed=membership_changed, **kw
        )
        if proposed == current:
            record(switch=False, reason="same-counts", outcome="hold")
            return
        if not membership_changed:
            if (
                self.migration_budget is not None
                and self._migrations_spent >= self.migration_budget
            ):
                record(
                    switch=False, reason="budget-exhausted", outcome="hold"
                )
                return
            if gain is None:
                # an unmeasured tenant means the gain model has no wall to
                # stand on: only membership changes may actuate
                record(
                    switch=False, reason="unmeasured-hold", outcome="hold"
                )
                return
            if gain <= self.outer_margin:
                record(switch=False, reason="below-margin", outcome="hold")
                return
        assigned = self.pool.reallocate(proposed)
        self.actuations += 1
        record(
            switch=True,
            reason="membership" if membership_changed else "migrate",
            outcome="committed",
        )
        get_tracer().instant(
            "pool_repartition",
            cat="scheduler",
            args={
                "window": int(self._window),
                "counts": {k: int(v) for k, v in proposed.items()},
            },
        )
        for js in live:
            self._apply_allotment(js, assigned[js.spec.job_id])

    def _modeled_gain(
        self, live: List[JobState], proposed: Dict[str, int]
    ) -> Optional[float]:
        """Relative drop of the modeled worst tenant wall under the
        proposed counts (demand_j / d_j wall model) — None when any tenant
        is unmeasured (then only membership changes actuate)."""
        with self._lock:
            demands = {js.spec.job_id: js.demand_s() for js in live}
            cur = {js.spec.job_id: max(len(js.devices), 1) for js in live}
        if any(d is None or d <= 0 for d in demands.values()):
            return None
        now = max(demands[j] / cur[j] for j in demands)
        then = max(demands[j] / max(proposed[j], 1) for j in demands)
        if now <= 0:
            return None
        return 1.0 - then / now

    # --------------------------------------------------------- actuation

    def _ranks_on(self, js: JobState, ordinals: Tuple[int, ...]) -> List[int]:
        """The job-config ranks living on the allotted pool ordinals."""
        import jax

        cfg = js.trainer.cfg
        ids = cfg.worker_device_ids(len(jax.local_devices()))
        active = [r for r in range(cfg.world_size) if ids[r] in set(ordinals)]
        if not active:
            raise RuntimeError(
                f"job {js.spec.job_id!r}: no worker of its config maps onto "
                f"allotted devices {list(ordinals)}"
            )
        return active

    def _apply_allotment(
        self, js: JobState, ordinals: Tuple[int, ...]
    ) -> None:
        """Point a tenant at its new device subset — the planned-re-shard
        recipe of the engine's epoch-boundary readmission (``state → host →
        _reshard_world → host → state``, comm-residual fix, cost-anchor
        carry, re-warm), applied to a POOL decision instead of a fault."""
        import jax

        tr = js.trainer
        new_active = self._ranks_on(js, ordinals)
        if sorted(tr.active_ranks) == new_active:
            # allotment covers the tenant's whole footprint: the trainer is
            # untouched (the single-tenant bitwise-parity contract), only
            # warmed — the exact `run()` preamble sequence
            tr._maybe_warm()
            with self._lock:
                js.devices = tuple(sorted(ordinals))
            return
        t0 = time.monotonic()
        with get_tracer().span("pool_reshard", cat="recover"):
            host_state = tr._state_to_host(tr.state)
            prev_active = list(tr.active_ranks)
            prev_cost = tr.per_example_cost.copy()
            retry_transient(
                lambda: tr._reshard_world(new_active),
                logger=self.logger,
                desc=f"pool re-shard ({js.spec.job_id})",
            )
            tr.state = retry_transient(
                lambda: tr._state_from_host(host_state),
                logger=self.logger,
                desc=f"state re-placement ({js.spec.job_id})",
            )
            tr._fix_comm_residual()
            jax.block_until_ready(tr.state.params)
            # carry survivors' cost anchors to their compact slots; fill
            # newly-activated ranks from the survivor mean (the readmission
            # recipe's fallback — the next measured epoch re-anchors them)
            cost = np.full(tr.world_size, np.nan)
            for i, r in enumerate(tr.active_ranks):
                if r in prev_active:
                    cost[i] = prev_cost[prev_active.index(r)]
            if np.isfinite(prev_cost).any():
                cost = np.where(
                    np.isfinite(cost), cost, float(np.nanmean(prev_cost))
                )
            if np.isfinite(cost).all() and (cost > 0).all():
                tr.per_example_cost = cost
                tr.shares = equilibrium_shares(cost)
                tr.node_times = np.maximum(cost * tr.shares, 1e-9)
            else:
                tr.shares = initial_partition(tr.world_size)
                tr.node_times = np.ones(tr.world_size, dtype=np.float64)
            # re-warm against the new world at the boundary, so the next
            # window's dispatch stays compile-free
            tr._warmed = False
            tr._maybe_warm()
        dt = time.monotonic() - t0
        with self._lock:
            had = bool(js.devices)
            js.devices = tuple(sorted(ordinals))
            if had:
                js.migrations += 1
                self._migrations_spent += 1
        self.logger.info(
            f"scheduler: job {js.spec.job_id!r} -> devices "
            f"{sorted(ordinals)} ({len(new_active)} active ranks, "
            f"{dt:.3f}s re-shard)"
        )

    # ------------------------------------------------------ window drive

    def _run_window(self, live: List[JobState]) -> None:
        self.pool.begin_window()
        t0 = time.monotonic()
        with self._lock:
            self._window_done = 0
            for js in live:
                js._go = True
            self._cv.notify_all()
            while self._window_done < len(live):
                self._cv.wait()
        wall = time.monotonic() - t0
        self.pool.end_window()
        with self._lock:
            rec = {
                "window": int(self._window),
                "wall_s": float(wall),
                "jobs": {
                    js.spec.job_id: {
                        "devices": len(js.devices),
                        "epoch_wall_s": js.last_wall_s,
                        "epochs_done": js.epochs_done,
                        "status": js.status,
                    }
                    for js in live
                },
            }
        self.windows.append(rec)

    def _job_worker(self, js: JobState) -> None:
        """Per-tenant driver thread: park at the boundary barrier, run ONE
        inner epoch per released window, report the measured wall. The
        epoch runs under the tenant's graftscope job tag, so every span it
        emits attributes to this tenant (`graftscope summarize --by-job`)."""
        tracer = get_tracer()
        tracer.set_job(js.spec.job_id)
        try:
            while True:
                with self._lock:
                    while not js._go and not self._stop:
                        self._cv.wait()
                    if self._stop:
                        break
                    js._go = False
                    epoch = js.epochs_done
                    trainer = js.trainer
                t0 = time.monotonic()
                err: Optional[BaseException] = None
                try:
                    trainer.run_epoch(epoch)
                except BaseException as e:  # noqa: BLE001 — reported, re-raised at run()
                    err = e
                wall = time.monotonic() - t0
                with self._lock:
                    if err is not None:
                        js.status = "failed"
                        js.error = err
                    else:
                        js.epochs_done += 1
                        w = (
                            float(self.wall_model(js))
                            if self.wall_model is not None
                            else wall
                        )
                        js.last_wall_s = w
                        js.epoch_walls.append(w)
                        js.wall_ema = (
                            w
                            if js.wall_ema is None
                            else self.WALL_ALPHA * w
                            + (1.0 - self.WALL_ALPHA) * js.wall_ema
                        )
                        if js.epochs_done >= js.spec.total_epochs():
                            js.status = "finishing"
                    self._window_done += 1
                    self._cv.notify_all()
                    if js.status != "running":
                        break
        finally:
            tracer.set_job(None)

    # -------------------------------------------------------- retirement

    def _retire(self, live: List[JobState]) -> None:
        for js in live:
            with self._lock:
                st = js.status
                if st == "running" or js.retired:
                    continue
                js.retired = True
            if js.worker_thread is not None:
                js.worker_thread.join(timeout=60.0)
            if st == "finishing":
                self._finalize(js)
                with self._lock:
                    js.status = "done"
            self.pool.release(js.spec.job_id)
            with self._lock:
                js.devices = ()
                self._membership_dirty = True
            get_tracer().instant(
                "job_retired",
                cat="scheduler",
                args={
                    "job": js.spec.job_id,
                    "window": int(self._window),
                    "status": js.status,
                },
            )
            self.logger.info(
                f"scheduler: job {js.spec.job_id!r} retired "
                f"({js.status}, {js.epochs_done} epochs)"
            )

    def _finalize(self, js: JobState) -> None:
        """The single-stream ``run()`` tail, per tenant: save the metrics
        artifact (proc 0) and the graftscope trace."""
        tr = js.trainer
        with self._lock:
            js.makespan_s = time.monotonic() - js._t_admit
            js.recorder = tr.recorder
        if tr.proc_id == 0:
            tr.recorder.save(tr.cfg.stat_dir, tr.cfg.base_filename())
        tr.save_trace()

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict:
        """Aggregate pool utilization + per-tenant summary: window count,
        total scheduler wall, the device-idle fraction (1 − busy
        device-seconds / pool capacity over the windows), per-job makespan
        and migration counts — the quantities bench.py's multistream A/B
        reports."""
        cap = 0.0
        busy = 0.0
        for w in self.windows:
            cap += self.pool.n_devices * w["wall_s"]
            for j in w["jobs"].values():
                if j["epoch_wall_s"] is not None:
                    busy += j["devices"] * j["epoch_wall_s"]
        with self._lock:
            jobs = {
                js.spec.job_id: {
                    "status": js.status,
                    "epochs": js.epochs_done,
                    "makespan_s": js.makespan_s,
                    "migrations": js.migrations,
                    "mean_epoch_wall_s": (
                        float(np.mean(js.epoch_walls))
                        if js.epoch_walls
                        else None
                    ),
                }
                for js in self._jobs.values()
            }
        return {
            "windows": len(self.windows),
            "pool_devices": self.pool.n_devices,
            "window_wall_s": float(sum(w["wall_s"] for w in self.windows)),
            "device_idle_fraction": (
                float(1.0 - busy / cap) if cap > 0 else None
            ),
            "migrations": self._migrations_spent,
            "jobs": jobs,
        }

    def snapshot(self, include_journal: bool = False) -> Dict:
        """JSON-safe outer-controller observability, shaped like the inner
        controller's ``snapshot()`` (registry ``scheduler`` section)."""
        out = {
            "evals": self.evals,
            "actuations": self.actuations,
            "migrations_spent": int(self._migrations_spent),
            "migration_budget": self.migration_budget,
            "outer_margin": self.outer_margin,
            "pool_devices": self.pool.n_devices,
            "decisions": len(self.journal),
            "journal_dropped": self.journal_dropped,
            "last_decision": dict(self.journal[-1]) if self.journal else None,
        }
        if include_journal:
            out["journal"] = self.decision_journal()
        return out
