"""Native host runtime: C++ gather/pack + solver behind a ctypes bridge."""

from dynamic_load_balance_distributeddnn_tpu.runtime.native import (
    native_available,
    native_integer_batch_split,
    native_rebalance,
    take_rows,
)

__all__ = [
    "native_available",
    "native_integer_batch_split",
    "native_rebalance",
    "take_rows",
]
