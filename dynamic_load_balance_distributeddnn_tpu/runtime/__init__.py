"""Host runtime: C++ gather/pack + solver bridge, watchdog, AOT compiler."""

from dynamic_load_balance_distributeddnn_tpu.runtime.compiler import (
    AOTCompileService,
    default_pool_size,
)
from dynamic_load_balance_distributeddnn_tpu.runtime.native import (
    native_available,
    native_integer_batch_split,
    native_rebalance,
    take_rows,
)

__all__ = [
    "AOTCompileService",
    "default_pool_size",
    "native_available",
    "native_integer_batch_split",
    "native_rebalance",
    "take_rows",
]
