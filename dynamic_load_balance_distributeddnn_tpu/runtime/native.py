"""ctypes bridge to the C++ host runtime (native/src/dbs_native.cpp).

The reference's host runtime is PyTorch's native machinery: the DataLoader
worker pool packs per-step batches and gloo's C++ rings move bytes
(reference dbs.py:511-515, dataloader.py:105-117). This framework's
equivalents: the TPU compute/collective path is XLA; the *host* path —
epoch materialization (gather/pack of every worker's step batches) and the
replicated DBS solver — is first-party C++ here, loaded via ctypes (no
pybind11 in this environment, SURVEY §2.2).

Everything degrades gracefully: if the shared library is absent and cannot
be built (no compiler), callers fall back to the numpy implementations with
identical semantics. Parity is enforced by tests/test_native.py.

Env knobs:
  DBS_NATIVE=0        disable the native path entirely (forces numpy)
  DBS_NATIVE_THREADS  gather thread count (default: hardware concurrency)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_NAME = "libdbs_native.so"
_ABI_VERSION = 1

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _build(src_dir: str) -> Optional[str]:
    src = os.path.join(src_dir, "src", "dbs_native.cpp")
    out = os.path.join(src_dir, _LIB_NAME)
    if not os.path.exists(src):
        return None
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    # Compile to a process-unique temp file and rename atomically: concurrent
    # processes (e.g. multi-host workers) may race to build, and rewriting a
    # .so another process has dlopen'd is undefined behavior.
    tmp = f"{out}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            [
                os.environ.get("CXX", "g++"),
                "-O3",
                "-std=c++17",
                "-fPIC",
                "-shared",
                "-pthread",
                "-o",
                tmp,
                src,
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return out


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    with _lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("DBS_NATIVE", "1") == "0":
            return None
        path = _build(_REPO_NATIVE_DIR)
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        try:
            lib.dbs_native_abi_version.restype = ctypes.c_int
            if lib.dbs_native_abi_version() != _ABI_VERSION:
                return None
            lib.dbs_gather_rows.restype = ctypes.c_int
            lib.dbs_gather_rows.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int,
            ]
            lib.dbs_integer_batch_split.restype = ctypes.c_int
            lib.dbs_integer_batch_split.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.dbs_rebalance.restype = ctypes.c_int
            lib.dbs_rebalance.argtypes = [
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_double),
                ctypes.c_int,
                ctypes.c_int64,
                ctypes.c_double,
                ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64),
            ]
        except AttributeError:
            return None
        _lib = lib
        return _lib


def native_available() -> bool:
    """True when the C++ runtime is loaded (or loadable)."""
    return _load() is not None


# ------------------------------------------------------------------- gather


def take_rows(data: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``data[idx]`` along axis 0 via the multithreaded C++ gather.

    ``data`` must be C-contiguous; ``idx`` may have any shape. The result has
    shape ``idx.shape + data.shape[1:]`` — exactly ``np.take(data, idx, 0)``,
    which is also the fallback when the native library is unavailable.
    """
    lib = _load()
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    # Uniform bounds semantics on both backends: no negative/out-of-range
    # indices (numpy's silent negative-index wrapping would otherwise make the
    # fallback diverge from the C++ bounds check).
    if idx.size and (idx.min() < 0 or idx.max() >= data.shape[0]):
        raise ValueError("take_rows: index out of range")
    if lib is None:
        return np.take(data, idx, axis=0)
    if not data.flags["C_CONTIGUOUS"]:
        data = np.ascontiguousarray(data)
    flat = idx.ravel()
    row_bytes = int(data.dtype.itemsize * int(np.prod(data.shape[1:], dtype=np.int64)))
    out = np.empty((flat.size,) + data.shape[1:], dtype=data.dtype)
    rc = lib.dbs_gather_rows(
        data.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int64(data.shape[0]),
        ctypes.c_int64(row_bytes),
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(flat.size),
        out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_int(int(os.environ.get("DBS_NATIVE_THREADS", "0"))),
    )
    if rc != 0:
        raise ValueError(f"dbs_gather_rows failed with code {rc}")
    return out.reshape(idx.shape + data.shape[1:])


# ------------------------------------------------------------------- solver


def native_integer_batch_split(
    shares: np.ndarray, global_batch: int
) -> Optional[np.ndarray]:
    """C++ integer split; ``None`` when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    s = np.ascontiguousarray(shares, dtype=np.float64)
    out = np.zeros(s.size, dtype=np.int64)
    rc = lib.dbs_integer_batch_split(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int(s.size),
        ctypes.c_int64(int(global_batch)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise ValueError(f"dbs_integer_batch_split failed with code {rc}")
    return out


def native_rebalance(
    node_times: np.ndarray,
    shares: np.ndarray,
    global_batch: int,
    max_share: Optional[float] = None,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """C++ rebalance step; ``None`` when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    t = np.ascontiguousarray(node_times, dtype=np.float64)
    p = np.ascontiguousarray(shares, dtype=np.float64)
    if t.shape != p.shape:
        raise ValueError("node_times and shares must have the same length")
    out_s = np.zeros(t.size, dtype=np.float64)
    out_b = np.zeros(t.size, dtype=np.int64)
    rc = lib.dbs_rebalance(
        t.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int(t.size),
        ctypes.c_int64(int(global_batch)),
        ctypes.c_double(-1.0 if max_share is None else float(max_share)),
        out_s.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        out_b.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc == -2:
        raise ValueError("node_times must be positive")
    if rc == -3:
        raise ValueError("max_share too small to cover the batch")
    if rc == -4:
        raise ValueError("degenerate split: no worker received any batch")
    if rc != 0:
        raise ValueError(f"dbs_rebalance failed with code {rc}")
    return out_s, out_b
