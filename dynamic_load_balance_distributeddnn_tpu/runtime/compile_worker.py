"""Process-parallel XLA compile workers feeding the persistent cache.

The in-process AOT pool (runtime/compiler.py) overlaps compile jobs on
threads, but on XLA:CPU concurrent program compiles contend almost fully on
a shared resource in the emitter — jobs overlap 2x and stretch 2x, so
multi-program compile throughput never scales with cores (ROADMAP open
item, measured in PR 3). This module moves the backend compile itself into
subprocesses:

* The parent still traces and lowers (jitted callables close over live
  models and cannot cross a process boundary), then extracts a
  **self-contained lowering payload**: the StableHLO module as MLIR
  bytecode plus the exact serialized ``CompileOptions`` jax itself would
  build at ``lowered.compile()`` time (``pxla.create_compile_options`` with
  the arguments ``UnloadedMeshExecutable.from_hlo`` derives from
  ``compile_args`` — device assignment, SPMD flags, sharding-propagation
  masks, compiler-option kvs).
* A worker process deserializes the payload and compiles it through
  ``jax._src.compiler.compile_or_get_cached`` — the same entry point the
  parent's ``compile()`` uses — against the run's single pinned persistent
  compilation cache. The cache key is a pure function of (module bytes,
  serialized options, backend/version, XLA flags), all of which are
  byte-identical across the boundary (verified by the replay test), so the
  worker's compile lands in the cache under the key the parent will look
  up.
* The parent then replays ``lowered.compile()`` in-process: a **guaranteed
  persistent-cache hit** — deserialization, not compilation. Every
  process-level concern (executable registration, donation, dispatch)
  stays exactly the in-process path; the subprocess only pre-pays the
  expensive XLA emitter work, on its own core, with its own GIL.

A worker that dies, rejects a payload, or cannot be spawned degrades to
the in-process path for free: the replay IS a full compile when the cache
has no entry. Workers are spawned (never forked — forking a live XLA
runtime is undefined behavior) with the parent's environment, so
``JAX_PLATFORMS`` / ``XLA_FLAGS`` (device counts!) carry over.

Each worker keeps its own graftscope span buffer (one ``worker_compile``
span per job, pid-tagged by the exporter) and writes it as a Chrome-trace
JSON next to the run trace on shutdown; ``graftscope summarize`` and the
engine's end-of-run save stitch those files into the run trace so compile
walls attribute across processes.
"""

from __future__ import annotations

import os
import pickle
import queue
import tempfile
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

_READY = "__ready__"
_POISON = None


def default_worker_count() -> int:
    """Process workers when the config leaves it at 0 (auto). Adaptive on
    many-core hosts (PR 5 follow-up): each worker is a full XLA runtime
    (~100 MB, ~2-4 s spawn), so small hosts keep the old one-per-core cap
    of 4, while hosts with cores to spare scale to half the cores capped at
    8 — the regime the process backend exists for (per-program compiles
    stop sharing an emitter once cores > concurrent programs)."""
    cpus = os.cpu_count() or 2
    if cpus <= 8:
        return max(1, min(4, cpus))
    return min(8, cpus // 2)


def ensure_persistent_cache(logger=None) -> Optional[str]:
    """Pin the run's persistent compilation cache (the channel worker
    compiles travel through). An already-configured dir (bench.py pins an
    absolute one into every subprocess) is respected; otherwise a
    run-scoped temp dir is created. Floors are zeroed so small programs
    persist too. Returns the dir, or None if the cache cannot be enabled."""
    import jax

    try:
        cache_dir = jax.config.jax_compilation_cache_dir or os.environ.get(
            "JAX_COMPILATION_CACHE_DIR"
        )
        if not cache_dir:
            cache_dir = tempfile.mkdtemp(prefix="jax_graft_aot_cache_")
        cache_dir = os.path.abspath(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # jax memoizes BOTH the cache-used decision (_cache_checked) and the
        # cache object itself (_cache_initialized, possibly None when no dir
        # was configured yet) on the FIRST compile of the process; any
        # compile that ran before this configuration freezes the cache off
        # and every replay would silently recompile. Reset so the next
        # compile re-evaluates with the dir in place.
        from jax._src import compilation_cache as _cc

        stale_decision = getattr(_cc, "_cache_checked", False) and not getattr(
            _cc, "_cache_used", False
        )
        stale_cache = (
            getattr(_cc, "_cache_initialized", False)
            and getattr(_cc, "_cache", None) is None
        )
        if stale_decision or stale_cache:
            _cc.reset_cache()
        return cache_dir
    except Exception as e:  # pragma: no cover - config surface drift
        if logger is not None:
            logger.warning(f"compile workers: persistent cache unavailable: {e!r}")
        return None


# ---------------------------------------------------------------------------
# jax-internal-surface pinning (PR 5 follow-up): extract_lowering_payload
# rides on ``pxla.create_compile_options``, a private jax function whose
# signature has no stability contract. Rather than letting a jax upgrade
# silently turn every offload into a blanket ``except Exception`` fallback
# (the process backend would quietly degrade to the thread backend), the
# capability is resolved ONCE per process against a pinned signature table:
# a known surface yields a versioned adapter, drift yields a clear one-time
# diagnostic naming the observed signature. New jax surfaces get a new row
# here, not a rewrite at every call site.

# parameter-name tuple -> adapter version tag. jax 0.4.30-0.5.x surface:
_PAYLOAD_SURFACES: Dict[Tuple[str, ...], str] = {
    (
        "computation", "mesh", "spmd_lowering", "tuple_args",
        "auto_spmd_lowering", "allow_prop_to_inputs",
        "allow_prop_to_outputs", "backend", "np_dev", "pmap_nreps",
        "compiler_options",
    ): "v1",
}
_payload_api_cache: Optional[Dict[str, Any]] = None


def payload_capability() -> Dict[str, Any]:
    """Import-time-style capability check for the lowering-payload
    extraction, resolved once per process: ``{"available", "version",
    "reason"}``. Available means ``pxla.create_compile_options`` exists AND
    its signature matches a pinned surface this module was written against;
    anything else is reported as drift with the observed signature, so a
    jax upgrade fails LOUD (one diagnostic) instead of silently disabling
    the process compile backend."""
    global _payload_api_cache
    if _payload_api_cache is not None:
        return _payload_api_cache
    cap: Dict[str, Any]
    try:
        import inspect

        from jax._src.interpreters import pxla

        fn = getattr(pxla, "create_compile_options", None)
        if fn is None:
            cap = {
                "available": False,
                "version": None,
                "reason": "jax._src.interpreters.pxla.create_compile_options "
                "no longer exists (jax internal surface drift)",
            }
        else:
            params = tuple(inspect.signature(fn).parameters)
            version = _PAYLOAD_SURFACES.get(params)
            if version is None:
                cap = {
                    "available": False,
                    "version": None,
                    "reason": (
                        "pxla.create_compile_options signature drifted: "
                        f"observed {params!r}, known surfaces "
                        f"{sorted(_PAYLOAD_SURFACES.values())} — add the new "
                        "surface to _PAYLOAD_SURFACES in "
                        "runtime/compile_worker.py"
                    ),
                }
            else:
                cap = {"available": True, "version": version, "reason": ""}
    except Exception as e:  # pragma: no cover - import surface drift
        cap = {
            "available": False,
            "version": None,
            "reason": f"jax internals unimportable: {e!r}",
        }
    _payload_api_cache = cap
    return cap


_payload_drift_warned = False


def _warn_payload_drift(reason: str) -> None:
    global _payload_drift_warned
    if _payload_drift_warned:
        return
    _payload_drift_warned = True
    import warnings

    warnings.warn(
        "compile workers: lowering-payload extraction disabled — "
        f"{reason}; AOT jobs degrade to in-process compiles (the thread "
        "backend)",
        RuntimeWarning,
        stacklevel=2,
    )


def extract_lowering_payload(lowered) -> Optional[Dict[str, Any]]:
    """Self-contained compile job from a ``jax.stages.Lowered``: MLIR
    bytecode + the exact serialized ``CompileOptions`` the parent's own
    ``lowered.compile()`` will use, so the worker's cache write and the
    parent's replay share one cache key. Returns None when the program
    cannot be offloaded (host callbacks, AUTO shardings, pmap-style
    replication) — the caller then compiles in-process as before — or when
    the pinned jax internal surface drifted (:func:`payload_capability`;
    one loud diagnostic, then clean degradation)."""
    import numpy as np

    cap = payload_capability()
    if not cap["available"]:
        _warn_payload_drift(cap["reason"])
        return None
    try:
        from jax._src.interpreters import mlir, pxla
        from jax._src.sharding_impls import AUTO, UnspecifiedValue

        lowering = lowered._lowering
        ca = lowering.compile_args
        if ca.get("host_callbacks") or ca.get("ordered_effects"):
            return None
        if int(ca.get("pmap_nreps", 1)) != 1:
            return None
        in_sh, out_sh = ca["in_shardings"], ca["out_shardings"]
        if any(isinstance(s, AUTO) for s in tuple(in_sh) + tuple(out_sh)):
            return None  # auto-SPMD keys depend on the solver's mesh choice
        allow_in = tuple(isinstance(s, (UnspecifiedValue, AUTO)) for s in in_sh)
        allow_out = tuple(isinstance(s, (UnspecifiedValue, AUTO)) for s in out_sh)
        da = ca["device_assignment"]
        dev = np.vectorize(lambda i: da[i], otypes=[object])(np.arange(len(da)))
        kvs = dict(getattr(lowering, "_compiler_options_kvs", ()) or ())
        module = lowering.stablehlo()
        options = pxla.create_compile_options(
            module,
            None,
            ca["spmd_lowering"],
            ca["tuple_args"],
            ca["auto_spmd_lowering"],
            allow_in,
            allow_out,
            ca["backend"],
            dev,
            ca.get("pmap_nreps", 1),
            kvs,
        )
        return {
            "module": mlir.module_to_bytecode(module),
            "options": options.SerializeAsString(),
            "device_ids": [int(d.id) for d in da],
            "platform": ca["backend"].platform,
        }
    except Exception:
        # any internal-surface drift (new jax) degrades to in-process
        # compiles instead of killing the job
        return None


def _worker_main(
    worker_id: int,
    job_q,
    ack_q,
    cache_dir: str,
    trace_path: Optional[str],
) -> None:
    """Worker process body. Spawned (fresh interpreter): configure the
    shared cache BEFORE jax touches any backend, ack readiness once the
    (expensive) jax import is done, then drain jobs until the poison pill.

    Runs in a subprocess — keep stdlib-only until jax is configured."""
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    t_import = time.perf_counter()
    import numpy as np

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    from jax._src import compiler as jax_compiler
    from jax._src import xla_bridge
    from jax._src.interpreters import mlir
    from jax._src.lib import xla_client as xc
    from jax._src.lib.mlir import ir

    from dynamic_load_balance_distributeddnn_tpu.obs.trace import Tracer

    tracer = Tracer(mode="on" if trace_path else "off")
    backend = xla_bridge.get_backend()
    by_id = {d.id: d for d in backend.local_devices()}
    # Pre-warm the compile stack BEFORE acking ready: a process's FIRST
    # compile pays one-time LLVM/autotune initialization (several seconds on
    # the CPU tier — comparable to a real program's compile). Folding it
    # into the spawn window means ready == "full-speed worker", and the
    # engine overlaps spawn with its own warm-up anyway. The dummy program
    # is unique per worker (worker_id in a constant) so it cannot shortcut
    # through a sibling's cache entry.
    try:
        import jax.numpy as jnp

        jax.jit(lambda x: (x * (2.0 + worker_id)).sum()).lower(  # graftlint: disable=G001
            jax.ShapeDtypeStruct((4, 4), jnp.float32)
        ).compile()
    except Exception:  # pragma: no cover - warm is best-effort
        pass
    ack_q.put((_READY, worker_id, time.perf_counter() - t_import, ""))
    try:
        while True:
            item = job_q.get()
            if item is _POISON:
                break
            job_id, name, blob = item
            t0 = time.perf_counter()
            err = ""
            try:
                payload = pickle.loads(blob)
                dev = np.vectorize(lambda i: by_id[i], otypes=[object])(
                    np.asarray(payload["device_ids"])
                )
                options = xc.CompileOptions.ParseFromString(payload["options"])
                with tracer.span(
                    "worker_compile", cat="compile", args={"key": name}
                ):
                    with mlir.make_ir_context() as ctx:
                        module = ir.Module.parse(payload["module"], context=ctx)
                        jax_compiler.compile_or_get_cached(
                            backend, module, dev, options, ()
                        )
            except BaseException as e:  # noqa: BLE001 - reported via the ack
                err = repr(e)
            ack_q.put((job_id, worker_id, time.perf_counter() - t0, err))
    finally:
        if trace_path:
            try:
                tracer.save(trace_path)
            except OSError:
                pass


class CompileWorkerPool:
    """N spawn-based compile worker processes sharing one job queue.

    ``submit`` enqueues a job and returns a handle; ``wait`` blocks until
    that job's ack (or the pool is declared dead). The pool NEVER raises on
    worker failure — a job whose worker died resolves as failed and the
    caller's in-process replay compiles for real (the designed fallback).
    """

    def __init__(
        self,
        workers: int,
        cache_dir: str,
        trace_dir: Optional[str] = None,
        logger=None,
    ):
        import multiprocessing as mp

        self._workers = max(int(workers), 1)
        self._cache_dir = cache_dir
        self._logger = logger
        self._ctx = mp.get_context("spawn")
        self._job_q = self._ctx.Queue()
        self._ack_q = self._ctx.Queue()
        self._procs: List = []
        self._trace_paths: List[str] = []
        self._lock = threading.Lock()
        self._events: Dict[str, threading.Event] = {}
        self._errors: Dict[str, str] = {}
        self._walls: Dict[str, float] = {}
        self._ready = threading.Event()
        self._all_ready = threading.Event()
        self._ready_count = 0
        self._dead = False
        self._startup_s: Optional[float] = None
        self._t_spawn = time.perf_counter()
        for i in range(self._workers):
            trace_path = None
            if trace_dir:
                # parent-pid tag: concurrent runs (multi-host, parallel
                # benches) sharing a trace_dir must not clobber each other's
                # worker files; the glob in scope_cli still matches
                trace_path = os.path.join(
                    trace_dir, f"compile_worker_{os.getpid()}_{i}.trace.json"
                )
                self._trace_paths.append(trace_path)
            p = self._ctx.Process(
                target=_worker_main,
                args=(i, self._job_q, self._ack_q, cache_dir, trace_path),
                daemon=True,
                name=f"aot-compile-worker-{i}",
            )
            p.start()
            self._procs.append(p)
        self._drain_thread = threading.Thread(
            target=self._drain_acks, name="aot-worker-acks", daemon=True
        )
        self._drain_thread.start()

    # ------------------------------------------------------------- internals

    def _drain_acks(self) -> None:
        last_alive = self._workers
        while True:
            try:
                job_id, worker_id, wall, err = self._ack_q.get(timeout=0.5)
            except queue.Empty:
                with self._lock:
                    if self._dead:
                        return
                alive = self.alive()
                if 0 < alive < last_alive:
                    # SOME worker died mid-job (OOM kill, segfault). The
                    # shared job queue cannot say which job it was holding,
                    # so resolve every outstanding job as failed — waiters
                    # fall back to in-process compiles instead of blocking
                    # forever on an ack that will never come. Jobs a live
                    # sibling is still compiling get compiled twice (worker
                    # + fallback): wasted background work, never a hang.
                    with self._lock:
                        pending = [
                            (jid, ev)
                            for jid, ev in self._events.items()
                            if not ev.is_set()
                        ]
                        for jid, ev in pending:
                            self._errors[jid] = "a worker died mid-job"
                            ev.set()
                    if self._logger is not None:
                        self._logger.warning(
                            f"compile worker died ({alive}/{self._workers} "
                            f"still alive); {len(pending)} outstanding "
                            "job(s) fall back to in-process compiles"
                        )
                    last_alive = alive
                    continue
                if not any(p.is_alive() for p in self._procs):
                    # every worker gone: resolve all outstanding jobs as
                    # failed so waiters fall back instead of hanging, and
                    # release wait_ready blockers NOW — a pool whose workers
                    # died at spawn (e.g. a __main__ that cannot be
                    # re-imported) must cost ~0, not one ready-timeout per
                    # job (wait_ready re-checks _dead and returns False)
                    with self._lock:
                        for jid, ev in self._events.items():
                            if not ev.is_set():
                                self._errors[jid] = "worker pool died"
                                ev.set()
                        self._dead = True
                        self._ready.set()
                        self._all_ready.set()
                        ready_count = self._ready_count
                    if self._logger is not None:
                        self._logger.warning(
                            f"compile worker pool died before serving any "
                            f"acks ({ready_count}/{self._workers} "
                            "workers reached ready); every job compiles "
                            "in-process — common cause: a __main__ the "
                            "spawned interpreter cannot re-import"
                        )
                    return
                continue
            except (EOFError, OSError):  # queue torn down at shutdown
                return
            if job_id == _READY:
                with self._lock:
                    self._ready_count += 1
                    if self._ready_count == 1:
                        # one live worker is enough to route jobs
                        self._startup_s = time.perf_counter() - self._t_spawn
                        self._ready.set()
                    if self._ready_count >= self._workers:
                        self._all_ready.set()
                continue
            with self._lock:
                ev = self._events.get(job_id)
                if ev is None:
                    # late ack for a job already resolved (e.g. failed over
                    # after a sibling worker died) — drop it, don't grow the
                    # error/wall maps unboundedly
                    continue
                self._errors[job_id] = err
                self._walls[job_id] = wall
                ev.set()

    # ------------------------------------------------------------ public API

    def wait_ready(self, timeout: float = 120.0, all_workers: bool = False) -> bool:
        """Block until at least one worker finished its jax import (spawn +
        import is the pool's fixed cost, ~3-8 s/worker on the CPU tier).
        ``all_workers=True`` waits for the FULL pool — the bench A/B uses it
        so late-importing workers don't contend with the measured jobs.
        Returns False (immediately, not after the timeout) when the pool
        died before enough workers acked ready."""
        ev = self._all_ready if all_workers else self._ready
        ok = ev.wait(timeout)
        with self._lock:
            need = self._workers if all_workers else 1
            if self._dead and self._ready_count < need:
                return False
        return ok

    @property
    def startup_s(self) -> Optional[float]:
        with self._lock:
            return self._startup_s

    def alive(self) -> int:
        return sum(1 for p in self._procs if p.is_alive())

    def submit(self, name: str, payload: Dict[str, Any]) -> str:
        """Queue one compile job; returns its id (pass to :meth:`wait`)."""
        job_id = uuid.uuid4().hex
        ev = threading.Event()
        with self._lock:
            if self._dead:
                self._errors[job_id] = "worker pool died"
                ev.set()
                self._events[job_id] = ev
                return job_id
            self._events[job_id] = ev
        self._job_q.put((job_id, name, pickle.dumps(payload)))
        return job_id

    def wait(self, job_id: str, timeout: Optional[float] = None) -> Tuple[bool, str]:
        """(ok, error) for one job. ``ok=False`` means the caller's replay
        must compile in-process (worker failed/died/timed out)."""
        with self._lock:
            ev = self._events.get(job_id)
        if ev is None:
            return False, "unknown job"
        if not ev.wait(timeout):
            return False, "timeout"
        with self._lock:
            err = self._errors.pop(job_id, "")
            self._events.pop(job_id, None)
            self._walls.pop(job_id, None)
        return (err == ""), err

    def trace_paths(self) -> List[str]:
        """Worker trace files that exist on disk (written at shutdown)."""
        return [p for p in self._trace_paths if os.path.exists(p)]

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._lock:
            if self._dead:
                # resolve stragglers; processes may already be gone
                for ev in self._events.values():
                    ev.set()
            dead = self._dead
            self._dead = True
            # release any wait_ready blockers (they re-check _dead)
            self._ready.set()
            self._all_ready.set()
        if not dead:
            for _ in self._procs:
                try:
                    self._job_q.put(_POISON)
                except (ValueError, OSError):
                    break
        for p in self._procs:
            p.join(timeout)
        for p in self._procs:
            if p.is_alive():
                p.terminate()
                p.join(1.0)
        # unblock any waiters that raced the shutdown
        with self._lock:
            for jid, ev in self._events.items():
                if not ev.is_set():
                    self._errors[jid] = "worker pool shut down"
                    ev.set()
