"""Async AOT compile service: the warm path off the critical path.

The engine's old warm-start compiled its bucket-ladder executables by
*executing dummy steps* — allocate a zero batch, ``device_put`` five arrays,
dispatch, ``block_until_ready`` — serially, one rung at a time
(engine._warm_shapes, kept behind ``--aot_warm off`` as the A/B reference and
flagged by graftlint G007). On short benchmark runs that warm wall dominated;
two bench rounds died inside it (BENCH_r04/r05, rc=124).

This service compiles the same executables ahead of time:

* ``jit(fn).lower(abstract_args).compile()`` — **no dummy execution, no
  host→device traffic**. Data arguments are :class:`jax.ShapeDtypeStruct`
  specs (shape + dtype + committed sharding); parameter/state trees are
  passed as the live arrays (zero-copy — ``lower`` only reads avals, and a
  concrete leaf carries its exact weak-type/committed-ness, which a spec
  cannot express).
* compile jobs run **concurrently** on a small thread pool — XLA releases
  the GIL during backend compile — with a **single-flight lowering lock**:
  tracing/lowering is GIL-bound Python, so at most one job traces while the
  others sit in backend compile. The pool becomes a software pipeline
  (trace job k+1 under job k's compile) instead of a GIL convoy.
* jobs are **deduped by key**: submitting an already-submitted key returns
  the existing future, so N workers sharing a device (or a warm pass racing
  a speculative compile) never trigger N backend compiles of one program.

In jax 0.4.x an AOT ``Compiled`` does *not* populate the lazy ``jit``
call cache, so the service is also the **executable registry**: the engine
resolves its hot dispatch through :meth:`get` and calls the ``Compiled``
object directly (same HLO, same donation semantics — bitwise-identical to
the lazy path; dispatch overhead is within a few microseconds of the C++
jit cache). A key the service doesn't hold falls back to the lazy wrapper.

Compile events raised by pool threads carry the :data:`AOT_THREAD_PREFIX`
thread name, which analysis/guards.py uses to keep background compiles out
of the engine's recompile sentinel (they are deliberate, overlapped work,
not a shape falling off the ladder) while still counting them in budgets
opened with ``include_background=True``.

``backend="process"`` additionally routes the backend-compile phase of
each job to subprocess workers (runtime/compile_worker.py): the pool
thread lowers, ships the serialized (StableHLO, CompileOptions) payload to
a worker, and — once the worker has compiled it into the run's pinned
persistent cache — replays ``lowered.compile()`` in-process as a
guaranteed cache hit. In-process concurrent compiles contend ~fully on a
shared resource in the XLA:CPU emitter (jobs overlap 2x but stretch 2x);
worker processes each own an emitter, so multi-program compile throughput
finally scales with cores (bench ``compile_workers_ab``). A worker that
dies or rejects a payload costs nothing: the replay compiles in-process,
exactly the ``backend="thread"`` behavior.
"""

from __future__ import annotations

import collections
import concurrent.futures
import os
import threading
import time
import weakref
from typing import Callable, Deque, Dict, Hashable, List, Optional, Sequence, Tuple

# Thread-name prefix for the compile pool — defined in analysis/guards.py
# (the consumer that matches it to attribute backend-compile events to
# background AOT work) and imported here so the two can never drift.
from dynamic_load_balance_distributeddnn_tpu.analysis.guards import (
    AOT_THREAD_PREFIX,
)
from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer


def default_pool_size() -> int:
    """Pool width when the config leaves it at 0 (auto): enough to keep the
    backend compiler busy without convoying tracing threads on the GIL.

    Adaptive on many-core hosts (PR 5 follow-up): the old fixed ``min(8,
    cpus)`` left a 56-core TPU host's compile throughput capped at 8 while
    the warm universe holds dozens of programs. Scale with ~3/4 of the
    cores (the rest keep the controller thread, transfer pipeline and
    allocator responsive), capped at 16 — beyond that, concurrent XLA:CPU
    program compiles contend on shared emitter state instead of speeding
    up (bench compile_workers_ab's thread-leg plateau)."""
    cpus = os.cpu_count() or 2
    return max(2, min(16, (cpus * 3) // 4))


# Ceiling on one worker job's wall (submit -> ack). Generous: the slowest
# single program observed (DenseNet-121 on the CPU tier) compiles in
# minutes, not tens of minutes — hitting this means a wedged worker, and
# the job falls back to an in-process compile instead of hanging the pool
# thread (and the engine's drain barrier) forever.
WORKER_JOB_TIMEOUT_S = float(os.environ.get("GRAFT_WORKER_JOB_TIMEOUT_S", 1800))


# Live pools, drained at interpreter shutdown. The hook registers with
# threading._register_atexit — the same internal mechanism
# concurrent.futures uses — which runs BEFORE the interpreter joins
# non-daemon threads, so it can still cancel queued jobs.
_live_pools: "weakref.WeakSet[_CompilePool]" = weakref.WeakSet()
_exit_hook_installed = False


def _drain_pools_at_exit() -> None:
    for pool in list(_live_pools):
        pool.shutdown(drop_pending=True)


def _install_exit_hook() -> None:
    global _exit_hook_installed
    if _exit_hook_installed:
        return
    _exit_hook_installed = True
    try:
        threading._register_atexit(_drain_pools_at_exit)  # 3.9+
    except AttributeError:  # pragma: no cover - very old Python
        import atexit

        atexit.register(_drain_pools_at_exit)


class _CompilePool:
    """Minimal fixed-size worker pool tuned for XLA compile jobs.

    Threads are NON-daemon: a thread killed mid-backend-compile at
    interpreter exit segfaults or std::terminates inside XLA (measured), so
    in-flight compiles must be allowed to finish. The exit hook above
    cancels everything still QUEUED, so process exit waits for at most one
    in-flight compile per worker instead of the whole backlog (the failure
    mode ThreadPoolExecutor's exit join has: it drains the entire queue)."""

    def __init__(self, workers: int, name_prefix: str):
        self._cv = threading.Condition()
        self._items: Deque = collections.deque()
        self._stop = False
        _install_exit_hook()
        _live_pools.add(self)
        self._threads = [
            threading.Thread(
                target=self._run, name=f"{name_prefix}-{i}", daemon=False
            )
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._items and not self._stop:
                    self._cv.wait()
                if self._items:
                    fut, fn, args = self._items.popleft()
                elif self._stop:
                    return
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn(*args))
            except BaseException as e:  # noqa: BLE001 - delivered via the future
                fut.set_exception(e)

    def submit(self, fn, *args) -> concurrent.futures.Future:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._cv:
            if self._stop:
                fut.cancel()
                return fut
            self._items.append((fut, fn, args))
            self._cv.notify()
        return fut

    def shutdown(self, drop_pending: bool = False) -> None:
        with self._cv:
            self._stop = True
            if drop_pending:
                for fut, _fn, _args in self._items:
                    fut.cancel()
                self._items.clear()
            self._cv.notify_all()


class AOTCompileService:
    """Concurrent ahead-of-time compiler + compiled-executable registry.

    ``workers``: pool width (0 = :func:`default_pool_size`). The pool is
    created lazily on the first ``submit`` — a service used only for
    ``compile_now`` never spawns a thread.

    ``backend``: ``"thread"`` (in-process backend compiles, the default) or
    ``"process"`` (backend compiles run in subprocess workers feeding the
    persistent cache; the in-process step becomes a cache-hit replay — see
    runtime/compile_worker.py). ``process_workers``: subprocess count
    (0 = auto). The worker pool spawns lazily with the thread pool and is
    shared by every job; any worker-side failure degrades that one job to
    an in-process compile.

    ``tick``: optional callback invoked after every finished compile job
    (the engine passes the watchdog heartbeat, so a long TPU compile ladder
    keeps answering the stall watchdog the way the execute-to-compile warm
    loop used to).
    """

    def __init__(
        self,
        workers: int = 0,
        logger=None,
        tick: Optional[Callable[[], None]] = None,
        backend: str = "thread",
        process_workers: int = 0,
        trace_dir: Optional[str] = None,
    ):
        if backend not in ("thread", "process"):
            raise ValueError(f"backend must be 'thread' or 'process', got {backend!r}")
        self._backend = backend
        self._process_workers = int(process_workers)
        self._trace_dir = trace_dir
        self._worker_pool = None  # CompileWorkerPool, spawned lazily
        self._worker_pool_failed = False
        if backend == "process" and not int(workers):
            # keep the workers fed: while worker k compiles job i, thread
            # k should already be lowering job i+1
            from dynamic_load_balance_distributeddnn_tpu.runtime.compile_worker import (
                default_worker_count,
            )

            workers = (self._process_workers or default_worker_count()) + 1
        self._workers = int(workers) or default_pool_size()
        self._logger = logger
        self._tick = tick
        self._pool: Optional[_CompilePool] = None
        self._lock = threading.Lock()
        # Single-flight lowering: tracing is GIL-bound Python; serializing it
        # across jobs turns the pool into a lower/compile pipeline instead of
        # a GIL convoy (measured 2x on the 2-core CPU tier vs naive pooling).
        self._lower_lock = threading.Lock()
        self._jobs: Dict[Hashable, concurrent.futures.Future] = {}
        self._done: Dict[Hashable, object] = {}  # key -> jax.stages.Compiled
        self._stats = {
            "submitted": 0,
            "deduped": 0,
            "compiled": 0,
            "failed": 0,
            "speculative": 0,
            "compile_wall_s": 0.0,
            # process-backend accounting: jobs whose backend compile ran in
            # a worker (replay hit the persistent cache) vs jobs that fell
            # back to a real in-process compile
            "worker_compiled": 0,
            "worker_fallback": 0,
        }

    # ------------------------------------------------------------- internals

    def _ensure_pool_locked(self) -> _CompilePool:
        if self._pool is None:
            self._pool = _CompilePool(self._workers, AOT_THREAD_PREFIX)
        return self._pool

    def _ensure_worker_pool(self):
        """Spawn the subprocess worker pool on first use (process backend).
        Returns the pool or None (spawn failed once → stay degraded: every
        job compiles in-process, which is just the thread backend)."""
        if self._backend != "process":
            return None
        with self._lock:
            if self._worker_pool is not None or self._worker_pool_failed:
                return self._worker_pool
        try:
            from dynamic_load_balance_distributeddnn_tpu.runtime.compile_worker import (
                CompileWorkerPool,
                default_worker_count,
                ensure_persistent_cache,
            )

            cache_dir = ensure_persistent_cache(self._logger)
            if cache_dir is None:
                raise RuntimeError("persistent compilation cache unavailable")
            pool = CompileWorkerPool(
                self._process_workers or default_worker_count(),
                cache_dir,
                trace_dir=self._trace_dir,
                logger=self._logger,
            )
        except Exception as e:
            with self._lock:
                self._worker_pool_failed = True
            if self._logger is not None:
                self._logger.warning(
                    f"compile workers unavailable ({e!r}); AOT service "
                    "degrades to in-process compiles"
                )
            return None
        with self._lock:
            if self._worker_pool is None:
                self._worker_pool = pool
                return pool
        pool.shutdown()  # lost the race to a concurrent spawner
        with self._lock:
            return self._worker_pool

    def _offload_to_worker(self, key: Hashable, lowered, tr, key_args) -> None:
        """Process backend: ship the lowered program to a worker and wait
        for its cache write. Purely best-effort — on ANY failure the
        caller's replay compiles in-process (the designed fallback)."""
        from dynamic_load_balance_distributeddnn_tpu.runtime.compile_worker import (
            extract_lowering_payload,
        )

        pool = self._ensure_worker_pool()
        ok = False
        if pool is not None and pool.wait_ready():
            payload = extract_lowering_payload(lowered)
            if payload is not None:
                with tr.span("aot_worker_wait", cat="compile", args=key_args):
                    job_id = pool.submit(repr(key), payload)
                    # bounded wait: the pool resolves lost jobs when it sees
                    # a worker die, but a wedged (not dead) worker would
                    # otherwise hang this pool thread — and with it the
                    # engine's pre-wall drain barrier — forever
                    ok, err = pool.wait(job_id, timeout=WORKER_JOB_TIMEOUT_S)
                if not ok and self._logger is not None:
                    self._logger.warning(
                        f"compile worker failed for {key}: {err} — "
                        "compiling in-process"
                    )
        with self._lock:
            self._stats["worker_compiled" if ok else "worker_fallback"] += 1

    def _compile_job(self, key: Hashable, fn, args: Sequence):
        t0 = time.perf_counter()
        # graftscope compile track: lower vs backend-compile spans, tagged
        # by pool thread (thread name) and dedup key — the view the PR-3
        # compile-worker-contention question needs. The key is stringified
        # lazily only when tracing is on (span args stay JSON-safe).
        tr = get_tracer()
        key_args = {"key": repr(key)} if tr.enabled else None
        try:
            with self._lower_lock:
                with tr.span("aot_lower", cat="compile", args=key_args):
                    lowered = fn.lower(*args)
            if self._backend == "process":
                # worker pre-pays the XLA emitter work into the persistent
                # cache; the compile() below is then a deserialization
                self._offload_to_worker(key, lowered, tr, key_args)
            with tr.span("aot_compile", cat="compile", args=key_args):
                compiled = lowered.compile()
        except BaseException:
            with self._lock:
                self._stats["failed"] += 1
            raise
        finally:
            if self._tick is not None:
                try:
                    self._tick()
                except Exception:  # pragma: no cover - heartbeat must not kill jobs
                    pass
        with self._lock:
            self._done[key] = compiled
            self._stats["compiled"] += 1
            self._stats["compile_wall_s"] += time.perf_counter() - t0
        return compiled

    # ------------------------------------------------------------ public API

    def submit(
        self, key: Hashable, fn, args: Sequence, speculative: bool = False
    ) -> concurrent.futures.Future:
        """Queue one AOT compile; dedup by ``key``.

        ``fn`` is a jitted callable, ``args`` its lowering arguments
        (ShapeDtypeStruct specs and/or live arrays). Returns the job's
        future; a key submitted before (in flight, done, or failed) returns
        the existing future without queueing anything.
        """
        with self._lock:
            fut = self._jobs.get(key)
            if fut is not None:
                self._stats["deduped"] += 1
                return fut
            pool = self._ensure_pool_locked()
            self._stats["submitted"] += 1
            if speculative:
                self._stats["speculative"] += 1
            fut = pool.submit(self._compile_job, key, fn, args)
            self._jobs[key] = fut
            return fut

    def compile_now(self, key: Hashable, fn, args: Sequence):
        """Blocking compile with the same dedup table as :meth:`submit`.

        A fresh key compiles INLINE on the caller thread (no pool, no queue
        delay — this is the path for one-off executables like the fused
        sync/FLOPs probes); a key already in flight joins that job instead.
        """
        with self._lock:
            fut = self._jobs.get(key)
            if fut is None:
                fut = concurrent.futures.Future()
                self._jobs[key] = fut
                self._stats["submitted"] += 1
                inline = True
            else:
                self._stats["deduped"] += 1
                inline = False
        if not inline:
            return fut.result()
        # Borrow the AOT thread-name prefix for the inline job so guards
        # attributes its backend-compile events as deliberate AOT work —
        # same classification as pool jobs (one compile must not read as a
        # foreground recompile to the sentinel just because it ran inline).
        me = threading.current_thread()
        saved = me.name
        me.name = AOT_THREAD_PREFIX + "-inline"
        try:
            compiled = self._compile_job(key, fn, args)
        except BaseException as e:
            fut.set_exception(e)
            raise
        finally:
            me.name = saved
        fut.set_result(compiled)
        return compiled

    def has(self, key: Hashable) -> bool:
        """Key known (queued, compiling, done, or failed)?"""
        with self._lock:
            return key in self._jobs

    def get(self, key: Hashable):
        """Finished ``Compiled`` for ``key``, or None (absent / in flight /
        failed). Non-blocking — the dispatch-time resolution path."""
        # deliberately lock-free: this sits on the per-step dispatch path;
        # dict.get is GIL-atomic and a racy miss only means one lazy-jit
        # fallback dispatch (bitwise-identical), never a wrong executable
        return self._done.get(key)  # graftlint: disable=G012

    def wait(
        self,
        keys: Optional[Sequence[Hashable]] = None,
        timeout: Optional[float] = None,
    ) -> List[Tuple[Hashable, BaseException]]:
        """Barrier: block until the given keys (default: every submitted job)
        finish. Returns ``(key, exception)`` pairs for failed jobs — the
        caller logs them and falls back to lazy dispatch; the failed key
        stays in the dedup table so it is not endlessly retried."""
        with self._lock:
            if keys is None:
                pending = list(self._jobs.items())
            else:
                pending = [(k, self._jobs[k]) for k in keys if k in self._jobs]
        deadline = None if timeout is None else time.monotonic() + timeout
        failures: List[Tuple[Hashable, BaseException]] = []
        for key, fut in pending:
            left = None if deadline is None else max(deadline - time.monotonic(), 0.0)
            try:
                fut.result(timeout=left)
            except concurrent.futures.TimeoutError:
                raise
            except BaseException as e:
                failures.append((key, e))
        return failures

    def failed(self, key: Hashable) -> bool:
        """Did ``key``'s job finish with an exception? Failed keys stay in
        the dedup table (never retried) and ``get`` returns None for them
        forever — callers that gate on readiness (the online controller's
        warm gate) must distinguish 'still compiling' from 'will never
        arrive', or one failed candidate compile would defer every switch
        for the rest of the run."""
        with self._lock:
            fut = self._jobs.get(key)
        if fut is None or not fut.done():
            return False
        return fut.exception() is not None

    def pending(self) -> int:
        with self._lock:
            return sum(1 for f in self._jobs.values() if not f.done())

    def keys(self) -> List[Hashable]:
        with self._lock:
            return list(self._jobs)

    def count_keys(self, name_prefixes: Tuple[str, ...]) -> int:
        """Compiled executables whose key[0] starts with one of the given
        names — e.g. the superstep variants for the engine's compile-once
        cross-check."""
        with self._lock:
            return sum(
                1
                for k in self._done
                if isinstance(k, tuple)
                and k
                and isinstance(k[0], str)
                and k[0].startswith(name_prefixes)
            )

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._stats)

    def worker_trace_paths(self) -> List[str]:
        """Chrome-trace files written by compile workers (process backend;
        available after close/shutdown — workers save at exit). The engine
        stitches these into the run trace (obs/trace.py merge_trace_files)."""
        with self._lock:
            pool = self._worker_pool
        return pool.trace_paths() if pool is not None else []

    def flush_workers(self) -> List[str]:
        """Shut down the subprocess workers so they write their graftscope
        trace files (saved at worker exit), and return the written paths.
        The service stays usable: later jobs degrade to in-process compiles
        (the thread-backend behavior) — intended only at end of run, before
        the engine saves and stitches the run trace."""
        with self._lock:
            pool = self._worker_pool
        if pool is None:
            return []
        pool.shutdown()
        return pool.trace_paths()

    def close(self, wait: bool = True) -> None:
        with self._lock:
            pool, self._pool = self._pool, None
            # keep _worker_pool set while pending jobs drain (they still
            # offload to live workers), but forbid a respawn: a drain-time
            # job racing _ensure_worker_pool must not spin up a fresh pool
            # that close() would then leak
            self._worker_pool_failed = True
            wpool = self._worker_pool
        if pool is not None:
            pool.shutdown(drop_pending=not wait)
            if wait:
                self.wait()
        if wpool is not None:
            # after the drain: workers idle, shut them down (writes their
            # trace files); the handle stays for trace-path collection
            wpool.shutdown()
