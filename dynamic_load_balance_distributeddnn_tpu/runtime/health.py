"""Per-worker health: liveness/latency verdicts and transient-retry armor.

The stall watchdog (runtime/watchdog.py) answers one binary question — "is
THIS process making device progress?" — and its only remedy is ``os._exit``.
Elastic world size (ISSUE 6) needs a finer instrument: per-LOGICAL-worker
verdicts the engine can act on *without* dying, because a dead or preempted
worker on a preemptible fleet is the common case, not the catastrophe. The
DBS solver already knows how to re-route data away from a slow worker; this
module supplies the missing first half — deciding that a worker is slow,
suspect, or gone — so the engine can run the same re-solve over a *changed*
fleet (balance/solver.py restarts its velocity track on world-size change by
design).

Three surfaces:

* :class:`WorkerHealth` — the verdict state machine. Signals arrive from
  whatever the caller already measures: the engine feeds per-worker probe
  walls (``observe_latency``) and preemption-injector/process-scan outcomes
  (``report_alive`` / ``report_miss``). ``detect_misses`` consecutive misses
  confirm a loss (one missed signal is indistinguishable from jitter — the
  same two-strike hysteresis the adaptive probe scheduler uses for its wall
  trigger); a confirmed-lost worker that signals again becomes
  ``RECOVERING`` and is readmitted by the engine at the next epoch boundary.
* :class:`ProcessHeartbeat` — heartbeat-FILE liveness for real processes
  (the multi-host tier): each process runs a beacon thread touching its own
  file; anyone can ``scan`` the directory for stale peers. This generalizes
  the watchdog's single-file heartbeat to a per-worker pulse train, and
  reads the exit-reason tag the watchdog now leaves behind (a peer that
  *aborted* is diagnosably different from one that merely stopped pulsing).
* :func:`retry_transient` — bounded exponential backoff for the
  collective/compile edges that can fail transiently while the fleet is
  changing shape (a re-shard races a dying runtime's last RPCs).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Optional

import numpy as np

from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer

# Verdicts. Plain strings (not an Enum) so snapshots stay JSON-trivial.
ALIVE = "alive"
SUSPECT = "suspect"
LOST = "lost"
RECOVERING = "recovering"


def _verdict_event(worker: int, verdict: str, **extra) -> None:
    """Flight-recorder instant for a health-verdict TRANSITION (ISSUE 15):
    emitted only when a worker's verdict changes, so the fleet timeline
    shows the detection edges, not the per-window liveness chatter. One
    attribute check when tracing is off."""
    tracer = get_tracer()
    if not tracer.enabled:
        return
    args = {"worker": int(worker), "verdict": verdict}
    args.update(extra)
    tracer.instant("health_verdict", cat="health", args=args)


class WorkerLost(RuntimeError):
    """Raised by the engine's health checks when worker loss is CONFIRMED
    (``detect_misses`` consecutive misses). Carries the lost ranks; the
    run loop catches it and enters the drain → re-solve → re-shard path."""

    def __init__(self, ranks: Iterable[int], message: str = ""):
        self.ranks = sorted(int(r) for r in ranks)
        super().__init__(
            message or f"worker(s) {self.ranks} confirmed lost"
        )


class WorkerHealth:
    """Per-worker liveness/latency verdict machine.

    ``detect_misses``: consecutive missed signals that confirm a loss.
    ``latency_factor``: a worker whose probe latency exceeds this multiple
    of the fleet median is marked SUSPECT — informational (the solver
    already absorbs slow workers by re-routing data; suspicion is the
    observable that says the degradation ladder's next rung is near).

    Not thread-safe by default writes; the engine drives it from the
    controller thread. ``scan`` integration for real processes goes through
    :class:`ProcessHeartbeat`, which IS thread-safe (beacon thread).
    """

    def __init__(
        self,
        world_size: int,
        detect_misses: int = 2,
        latency_factor: float = 8.0,
        logger=None,
    ):
        if world_size < 1:
            raise ValueError("world_size must be >= 1")
        if detect_misses < 1:
            raise ValueError("detect_misses must be >= 1")
        self.world_size = int(world_size)
        self.detect_misses = int(detect_misses)
        self.latency_factor = float(latency_factor)
        self.logger = logger
        self._status: List[str] = [ALIVE] * world_size
        self._misses = np.zeros(world_size, dtype=np.int64)
        self._latency = np.full(world_size, np.nan)  # EMA of probe walls
        # latency-derived suspicion is cleared only by a latency observation
        # back under threshold — NOT by a mere liveness signal (the engine's
        # per-window report_alive would otherwise erase the verdict within
        # one window and elastic_latency_factor would be observably inert)
        self._lat_suspect = [False] * world_size

    # ------------------------------------------------------------- signals

    def observe_latency(self, worker: int, seconds: float) -> None:
        """A measured per-worker probe wall: evidence of life, and the
        latency track behind the SUSPECT verdict."""
        w = int(worker)
        self.report_alive(w)
        prev = self._latency[w]
        self._latency[w] = (
            seconds if np.isnan(prev) else 0.5 * prev + 0.5 * seconds
        )
        med = float(np.nanmedian(self._latency))
        if med > 0 and np.isfinite(med) and self._latency[w] > self.latency_factor * med:
            if self._status[w] == ALIVE:
                self._status[w] = SUSPECT
                if self.logger:
                    self.logger.warning(
                        f"health: worker {w} latency {self._latency[w]:.3f}s "
                        f"is >{self.latency_factor:.0f}x the fleet median "
                        f"{med:.3f}s — SUSPECT (solver re-route territory)"
                    )
                _verdict_event(
                    w, SUSPECT, cause="latency",
                    latency_s=round(float(self._latency[w]), 6),
                    fleet_median_s=round(med, 6),
                )
            self._lat_suspect[w] = True
        elif self._lat_suspect[w]:
            # measured back under threshold: the latency verdict lifts
            self._lat_suspect[w] = False
            if self._status[w] == SUSPECT:
                self._status[w] = ALIVE
                _verdict_event(w, ALIVE, cause="latency-cleared")

    def report_alive(self, worker: int) -> None:
        """Any positive liveness signal. A LOST worker signalling again
        becomes RECOVERING (readmitted by the engine at an epoch boundary,
        never mid-epoch — plans are immutable within an epoch)."""
        w = int(worker)
        self._misses[w] = 0
        if self._status[w] == LOST:
            self._status[w] = RECOVERING
            if self.logger:
                self.logger.info(f"health: worker {w} signalling again — RECOVERING")
            _verdict_event(w, RECOVERING)
        elif self._status[w] == SUSPECT and not self._lat_suspect[w]:
            # miss-derived suspicion clears on any liveness signal;
            # latency-derived suspicion only clears via observe_latency
            self._status[w] = ALIVE
            _verdict_event(w, ALIVE, cause="signal")

    def report_miss(self, worker: int) -> bool:
        """One missed liveness signal. Returns True when this miss CONFIRMS
        the loss (crossed ``detect_misses``)."""
        w = int(worker)
        if self._status[w] == LOST:
            return False
        self._misses[w] += 1
        if self._misses[w] >= self.detect_misses:
            self._status[w] = LOST
            if self.logger:
                self.logger.warning(
                    f"health: worker {w} missed {int(self._misses[w])} "
                    "consecutive liveness checks — LOST"
                )
            _verdict_event(
                w, LOST, cause="misses", misses=int(self._misses[w])
            )
            return True
        if self._status[w] == ALIVE:
            self._status[w] = SUSPECT
            _verdict_event(w, SUSPECT, cause="miss")
        return False

    def mark_down(self, worker: int) -> None:
        """Administrative removal (the engine dropped the worker from the
        active fleet): further misses are expected and not news."""
        if self._status[int(worker)] != LOST:
            _verdict_event(int(worker), LOST, cause="mark_down")
        self._status[int(worker)] = LOST
        self._misses[int(worker)] = self.detect_misses

    def readmit(self, worker: int) -> None:
        """The engine re-added the worker to the active fleet."""
        w = int(worker)
        if self._status[w] != ALIVE:
            _verdict_event(w, ALIVE, cause="readmit")
        self._status[w] = ALIVE
        self._misses[w] = 0
        self._latency[w] = np.nan  # stale latency track: re-anchor on probes
        self._lat_suspect[w] = False

    # ------------------------------------------------------------ verdicts

    def status(self, worker: int) -> str:
        return self._status[int(worker)]

    def lost(self) -> List[int]:
        return [r for r, s in enumerate(self._status) if s == LOST]

    def recovering(self) -> List[int]:
        return [r for r, s in enumerate(self._status) if s == RECOVERING]

    def alive_count(self) -> int:
        return sum(1 for s in self._status if s in (ALIVE, SUSPECT))

    def snapshot(self) -> Dict:
        """JSON-safe view (MetricsRegistry surface)."""
        return {
            "status": list(self._status),
            "misses": [int(m) for m in self._misses],
            "latency_s": [
                None if np.isnan(v) else round(float(v), 6)
                for v in self._latency
            ],
            "alive": self.alive_count(),
        }


class ProcessHeartbeat:
    """Heartbeat-file liveness for real OS processes (multi-host tier).

    ``beacon(dir, ident)`` starts a daemon thread touching
    ``<dir>/<ident>.hb`` every ``period_s`` — process-level liveness (a
    SIGSTOPped or dead process stops all its threads, so the file goes
    stale). ``scan(dir)`` returns every peer's staleness age plus any
    exit-reason tag the stall watchdog wrote before aborting
    (runtime/watchdog.py) — a peer that hard-exited is distinguishable from
    one that silently froze.
    """

    SUFFIX = ".hb"

    def __init__(self, period_s: float = 1.0):
        self.period_s = float(period_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beacon(self, hb_dir: str, ident: str) -> str:
        """Start touching ``<hb_dir>/<ident>.hb``; returns the path."""
        os.makedirs(hb_dir, exist_ok=True)
        path = os.path.join(hb_dir, f"{ident}{self.SUFFIX}")
        with open(path, "a"):
            pass

        def _beat() -> None:
            while not self._stop.wait(self.period_s):
                try:
                    os.utime(path, None)
                except OSError:
                    pass

        self._thread = threading.Thread(
            target=_beat, daemon=True, name=f"hb-beacon-{ident}"
        )
        self._thread.start()
        return path

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.period_s)

    def watch(
        self,
        hb_dir: str,
        idents: Iterable[str],
        stale_s: float,
        on_stale: Callable[[str, Dict], None],
    ) -> threading.Thread:
        """Daemon scanner: polls ``scan(hb_dir)`` every ``period_s`` and
        fires ``on_stale(ident, info)`` ONCE per watched ident whose pulse
        goes stale (or that left a watchdog exit-reason tag). Runs on its
        own thread because the interesting case is precisely when the main
        thread is wedged in a collective against the dead peer."""
        idents = list(idents)
        fired: set = set()

        def _watch() -> None:
            while not self._stop.wait(self.period_s):
                found = self.scan(hb_dir)
                for ident in idents:
                    if ident in fired:
                        continue
                    info = found.get(ident)
                    if info is None:
                        continue
                    if self.is_stale(info, stale_s):
                        fired.add(ident)
                        try:
                            on_stale(ident, info)
                        except Exception:  # noqa: BLE001 — detection must outlive a bad callback
                            pass

        t = threading.Thread(target=_watch, daemon=True, name="hb-watch")
        t.start()
        return t

    @staticmethod
    def is_stale(info: Dict, stale_s: float) -> bool:
        """THE unreachable-peer verdict — one predicate shared by the
        watcher thread and the engine's window-boundary scan, so detection
        semantics cannot diverge between them: a pulse older than
        ``stale_s``, or any watchdog exit-reason tag (an aborted peer is
        unreachable no matter how fresh the tag write left the mtime)."""
        return info["age_s"] > stale_s or bool(info["exit_reason"])

    @staticmethod
    def stale_reason(info: Dict) -> str:
        return info["exit_reason"] or f"stale {info['age_s']:.1f}s"

    @classmethod
    def scan(cls, hb_dir: str) -> Dict[str, Dict]:
        """``{ident: {age_s, exit_reason}}`` for every heartbeat file in
        ``hb_dir``. ``exit_reason`` is the watchdog's tag (None for a file
        that is a plain mtime pulse)."""
        from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
            read_exit_reason,
        )

        out: Dict[str, Dict] = {}
        try:
            names = os.listdir(hb_dir)
        except OSError:
            return out
        now = time.time()
        for name in names:
            if not name.endswith(cls.SUFFIX):
                continue
            path = os.path.join(hb_dir, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            out[name[: -len(cls.SUFFIX)]] = {
                "age_s": age,
                "exit_reason": read_exit_reason(path),
            }
        return out


def retry_transient(
    fn: Callable,
    *,
    retries: int = 3,
    base_s: float = 0.05,
    max_s: float = 2.0,
    logger=None,
    desc: str = "",
    tick: Optional[Callable] = None,
) -> object:
    """Run ``fn()`` with bounded exponential backoff on transient failure.

    The collective/compile edges of a fleet change can fail once and succeed
    on retry (a re-shard racing a dying runtime's teardown, a compile RPC
    interrupted by the same preemption that killed the worker). Backoff
    doubles from ``base_s`` up to ``max_s``; ``tick`` (the watchdog's
    ``heartbeat``) is called between attempts so a retry loop never reads as
    a stall. The LAST failure re-raises — retries armor transience, they
    must not convert a real error into silence."""
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — transient surface is broad
            attempt += 1
            if attempt > retries:
                raise
            delay = min(base_s * (2 ** (attempt - 1)), max_s)
            if logger:
                logger.warning(
                    f"transient failure{f' in {desc}' if desc else ''} "
                    f"(attempt {attempt}/{retries}): {e!r} — retrying in "
                    f"{delay:.2f}s"
                )
            if tick is not None:
                tick()
            time.sleep(delay)
