"""Pallas TPU kernels for the framework's hot ops.

The reference delegates all device math to cuDNN via ``model.to("cuda:N")``
(dbs.py:66-68, 363); on TPU the equivalent default is XLA codegen, and these
kernels are the "only where XLA underperforms" layer (SURVEY §2.2): fused
GroupNorm (the normalization every CNN in the zoo uses, Net/Resnet.py:11
et al.) and fused softmax cross-entropy (the CNN criterion, dbs.py:374).

Kernels run as real Mosaic kernels on TPU and in interpreter mode elsewhere
(CPU tests), selected automatically. The module-level toggle gates whether
model builders and step libraries route through them; default off so the
pure-XLA path stays the reference numerical baseline.
"""

from __future__ import annotations

import jax

_USE_PALLAS = False


def set_use_pallas(flag: bool) -> None:
    global _USE_PALLAS
    _USE_PALLAS = bool(flag)


def use_pallas() -> bool:
    return _USE_PALLAS


def interpret_default() -> bool:
    """Real kernels on TPU, interpreter everywhere else."""
    return jax.default_backend() != "tpu"


from dynamic_load_balance_distributeddnn_tpu.ops.pallas.flash_attention import (  # noqa: E402
    flash_attention,
)
from dynamic_load_balance_distributeddnn_tpu.ops.pallas.groupnorm import (  # noqa: E402
    fused_group_norm,
)
from dynamic_load_balance_distributeddnn_tpu.ops.pallas.xent import (  # noqa: E402
    fused_softmax_xent,
)

__all__ = [
    "set_use_pallas",
    "use_pallas",
    "interpret_default",
    "flash_attention",
    "fused_group_norm",
    "fused_softmax_xent",
]
