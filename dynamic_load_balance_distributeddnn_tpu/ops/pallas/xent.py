"""Fused softmax cross-entropy — per-example loss without materialized softmax.

The reference's CNN criterion is ``nn.CrossEntropyLoss`` (dbs.py:374). The
generic JAX spelling (logsumexp + gather) materializes intermediates over the
full [rows, classes] block twice (forward exp, backward softmax). This kernel
keeps a row-block of logits in VMEM and produces the per-example loss in one
pass; the backward kernel recomputes softmax from the same logits block, so
no softmax residual is ever written to HBM — the win grows with the class
count (vocab-sized logits in the LM path).

Label gather is expressed as an iota==label masked reduction (TPU has no
cheap dynamic gather along lanes).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamic_load_balance_distributeddnn_tpu.ops import pallas as _pk

_ROW_BLOCK = 8


def _xent_fwd_kernel(logits_ref, labels_ref, loss_ref):
    x = logits_ref[...].astype(jnp.float32)      # [R, V]
    lbl = labels_ref[...]                        # [R, 1] int32
    m = jnp.max(x, axis=-1, keepdims=True)
    logz = jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    gold = jnp.sum(jnp.where(iota == lbl, x, 0.0), axis=-1, keepdims=True)
    loss_ref[...] = logz - gold


def _xent_bwd_kernel(logits_ref, labels_ref, g_ref, dx_ref):
    x = logits_ref[...].astype(jnp.float32)
    lbl = labels_ref[...]
    g = g_ref[...]                               # [R, 1]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)
    iota = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (iota == lbl).astype(jnp.float32)
    dx_ref[...] = (g * (p - onehot)).astype(dx_ref.dtype)


def _pad_rows(a, rb):
    r = a.shape[0]
    pad = (-r) % rb
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
    return a


def _fwd_impl(logits, labels2, interpret):
    r, v = logits.shape
    grid = (r // _ROW_BLOCK,)
    return pl.pallas_call(
        _xent_fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, v), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_BLOCK, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_ROW_BLOCK, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, 1), jnp.float32),
        interpret=interpret,
    )(logits, labels2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_xent(logits, labels2, interpret):
    return _fwd_impl(logits, labels2, interpret)


def _fused_xent_fwd(logits, labels2, interpret):
    return _fwd_impl(logits, labels2, interpret), (logits, labels2)


def _fused_xent_bwd(interpret, res, dloss):
    logits, labels2 = res
    r, v = logits.shape
    grid = (r // _ROW_BLOCK,)
    dx = pl.pallas_call(
        _xent_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_ROW_BLOCK, v), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_BLOCK, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_ROW_BLOCK, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_ROW_BLOCK, v), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, v), logits.dtype),
        interpret=interpret,
    )(logits, labels2, dloss)
    return dx, None


_fused_xent.defvjp(_fused_xent_fwd, _fused_xent_bwd)


def fused_softmax_xent(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Per-example softmax cross-entropy. logits: [..., C]; labels: [...] int.

    Drop-in for ops.losses.per_example_cross_entropy (same contract,
    dbs.py:374's criterion), differentiable w.r.t. logits.
    """
    if interpret is None:
        interpret = _pk.interpret_default()
    shape = labels.shape
    v = logits.shape[-1]
    flat = logits.reshape(-1, v)
    lbl = labels.reshape(-1, 1).astype(jnp.int32)
    r = flat.shape[0]
    flat_p = _pad_rows(flat, _ROW_BLOCK)
    lbl_p = _pad_rows(lbl, _ROW_BLOCK)
    loss = _fused_xent(flat_p, lbl_p, interpret)[:r, 0]
    return loss.reshape(shape)
