"""Flash attention — blockwise streaming-softmax attention as Pallas kernels.

The reference's attention is stock ``nn.TransformerEncoder`` (reference
Net/Transformer.py:57-64), which materializes the full [T, T] score matrix in
HBM. This kernel is the TPU-native replacement for long sequences: the grid
iterates (batch·head, query tile, key tile); each program holds one
[block_q, D] query tile and one [block_k, D] key/value tile in VMEM — VMEM
use is O(block·D), independent of T — and softmax is accumulated across key
tiles in VMEM scratch with the numerically stable running (max, sum)
recurrence. Causally dead tiles (whole key block above the diagonal) skip
their matmuls via predication.

Backward is the standard flash recomputation: the forward saves only the
per-row log-sum-exp; dK/dV and dQ are computed by two kernels that replay the
score tiles (grid over KV tiles for dK/dV, over Q tiles for dQ) using the
delta = rowsum(dO ∘ O) trick.

Shapes: q, k, v are [B, H, T, D]. T and D are padded internally to tile
multiples; padded key rows are masked out of the softmax, padded query rows
produce garbage that is sliced away. Accumulation is f32 regardless of input
dtype.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamic_load_balance_distributeddnn_tpu.ops import pallas as _pk

_NEG_INF = -1e30
_LANES = 128  # stat scratch lane width (min TPU lane tile)


def _scores(q, k, scale, q_tile, k_tile, block_q, block_k, causal, t_real):
    """Masked scaled scores for one (q tile, k tile) pair, f32 [BQ, BK]."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    q_pos = q_tile * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = k_tile * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < t_real
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    return jnp.where(mask, s, _NEG_INF)


def _attn_fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref,
    *, scale: float, causal: bool, t_real: int, block_q: int, block_k: int
):
    i = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def tile():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = _scores(q, k, scale, i, j, block_q, block_k, causal, t_real)
        m_prev = m_ref[:, :1]  # [BQ, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip tiles entirely above the diagonal: no q position can see them
        @pl.when(i * block_q + block_q - 1 >= j * block_k)
        def _():
            tile()
    else:
        tile()

    @pl.when(j == nk - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        # lse is [bh, 1, t_pad]: the singleton sublane keeps the block's
        # last-two dims (1, block_q) legal under Mosaic tiling (sublane dim
        # equals the array dim; block_q is lane-aligned by _tpu_block_sizes)
        lse_ref[0, 0] = (m_ref[:, 0] + jnp.log(l_safe[:, 0])).astype(jnp.float32)


def _attn_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc,
    *, scale: float, causal: bool, t_real: int, block_q: int, block_k: int
):
    j = pl.program_id(1)  # kv tile
    i = pl.program_id(2)  # q tile
    nq = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def tile():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].astype(jnp.float32)
        delta = delta_ref[0, 0].astype(jnp.float32)
        s = _scores(q, k, scale, i, j, block_q, block_k, causal, t_real)
        p = jnp.exp(s - lse[:, None])
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_acc[:] = dk_acc[:] + scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(i * block_q + block_q - 1 >= j * block_k)
        def _():
            tile()
    else:
        tile()

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _attn_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale: float, causal: bool, t_real: int, block_q: int, block_k: int
):
    i = pl.program_id(1)  # q tile
    j = pl.program_id(2)  # kv tile
    nk = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def tile():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0].astype(jnp.float32)
        delta = delta_ref[0, 0].astype(jnp.float32)
        s = _scores(q, k, scale, i, j, block_q, block_k, causal, t_real)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dq_acc[:] = dq_acc[:] + scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    if causal:
        @pl.when(i * block_q + block_q - 1 >= j * block_k)
        def _():
            tile()
    else:
        tile()

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _pad_to(x: jnp.ndarray, axis: int, mult: int) -> jnp.ndarray:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _fwd_impl(q, k, v, causal, block_q, block_k, interpret):
    bh, t_real, d_real = q.shape
    scale = 1.0 / (d_real ** 0.5)
    # one padded time axis divisible by BOTH tile sizes
    lcm = math.lcm(block_q, block_k)
    t_pad = -(-t_real // lcm) * lcm
    qp = _pad_to(_pad_to(q, 2, 128), 1, t_pad)
    kp = _pad_to(_pad_to(k, 2, 128), 1, t_pad)
    vp = _pad_to(_pad_to(v, 2, 128), 1, t_pad)
    d_pad = qp.shape[2]
    nq = t_pad // block_q
    nk = t_pad // block_k

    kernel = functools.partial(
        _attn_fwd_kernel,
        scale=scale,
        causal=causal,
        t_real=t_real,
        block_q=block_q,
        block_k=block_k,
    )
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d_pad), jnp.float32),   # output accumulator
        ],
        interpret=interpret,
    )(qp, kp, vp)
    return o[:, :t_real, :d_real], lse, (qp, kp, vp, t_pad, d_pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _, _ = _fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse, (qp, kp, vp, t_pad, d_pad) = _fwd_impl(
        q, k, v, causal, block_q, block_k, interpret
    )
    return o, (qp, kp, vp, lse, o, t_pad, d_pad)


def _flash_bwd(causal, block_q, block_k, interpret, res, do):
    qp, kp, vp, lse, o, t_pad, d_pad = res
    bh, t_real, d_real = o.shape
    scale = 1.0 / (d_real ** 0.5)
    dop = _pad_to(_pad_to(do, 2, 128), 1, t_pad)  # same policy as _fwd_impl
    # delta = rowsum(dO ∘ O) — one bandwidth pass, fused by XLA; carried as
    # [bh, 1, t_pad] (same singleton-sublane layout as lse) for legal tiling
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = _pad_to(delta, 1, t_pad)[:, None, :]

    nk = t_pad // block_k
    nq = t_pad // block_q
    common = dict(
        scale=scale,
        causal=causal,
        t_real=t_real,
        block_q=block_q,
        block_k=block_k,
    )
    dk, dv = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel, **common),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d_pad), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), qp.dtype),
            jax.ShapeDtypeStruct((bh, t_pad, d_pad), qp.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d_pad), jnp.float32),
            pltpu.VMEM((block_k, d_pad), jnp.float32),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d_pad), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d_pad), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((bh, t_pad, d_pad), qp.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d_pad), jnp.float32)],
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    return (
        dq[:, :t_real, :d_real],
        dk[:, :t_real, :d_real],
        dv[:, :t_real, :d_real],
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def _tpu_block_sizes(t16: int, block_q: int, block_k: int) -> "tuple[int, int]":
    """Snap block sizes to Mosaic lane-tiling-safe values for real-TPU runs.

    The lse/delta block is ``(1, 1, block_q)`` — block_q sits in the LANE
    dimension, so a block smaller than the padded time axis must be a
    multiple of 128 lanes. Short sequences (t16 < 128) use the full width
    (block == padded array dim, which Mosaic masks internally); otherwise
    blocks round to 128 multiples. Interpret mode is unconstrained."""
    if t16 < 128:
        return t16, t16
    bq = max(128, (block_q // 128) * 128)
    bk = max(128, (block_k // 128) * 128)
    return bq, bk


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Blockwise streaming-softmax attention, [B, H, T, D] -> [B, H, T, D].

    Differentiable (custom VJP with flash recomputation). Runs as a Mosaic
    kernel on TPU, interpreter elsewhere."""
    if interpret is None:
        interpret = _pk.interpret_default()
    b, h, t, d = q.shape
    t16 = -(-t // 16) * 16  # sublane-aligned cap for short sequences
    block_q = min(block_q, t16)
    block_k = min(block_k, t16)
    if not interpret:
        block_q, block_k = _tpu_block_sizes(t16, block_q, block_k)
    qf = q.reshape(b * h, t, d)
    kf = k.reshape(b * h, t, d)
    vf = v.reshape(b * h, t, d)
    o = _flash(qf, kf, vf, causal, block_q, block_k, interpret)
    return o.reshape(b, h, t, d)
