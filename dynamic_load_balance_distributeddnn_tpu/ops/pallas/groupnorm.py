"""Fused GroupNorm — one VMEM-resident pass per batch element.

GroupNorm is the zoo-wide normalization (the reference's deliberate
BatchNorm replacement, Net/Resnet.py:11-13: unequal per-worker batch sizes
would skew batch statistics). It is bandwidth-bound: stats + normalize +
affine are three passes over the activation when left to generic codegen.
This kernel keeps one batch element's [S, C] activation in VMEM and does
stat reduction, normalization and the affine in a single pass.

Mosaic-friendly trick: the per-group reduction is expressed as a matmul with
a one-hot [C, G] group-membership matrix (built from iota in-kernel), so the
lane dimension stays C throughout — no in-kernel reshapes that split the
lane axis (which TPU tiling cannot do cheaply).

Backward is the standard analytic GroupNorm VJP in plain jnp from saved
(x, mean, rstd) — XLA fuses it well; the forward is where fusion was missing.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamic_load_balance_distributeddnn_tpu.ops import pallas as _pk


def _gn_fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, rstd_ref,
                   *, groups: int, eps: float, relu: bool):
    x = x_ref[0].astype(jnp.float32)            # [S, C]
    s_dim, c = x.shape
    cg = c // groups
    n = s_dim * cg

    chan = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 0)
    grp = jax.lax.broadcasted_iota(jnp.int32, (c, groups), 1)
    member = (chan // cg == grp).astype(jnp.float32)  # [C, G] one-hot

    col_sum = jnp.sum(x, axis=0, keepdims=True)        # [1, C]
    col_sq = jnp.sum(x * x, axis=0, keepdims=True)     # [1, C]
    g_sum = jnp.dot(col_sum, member, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)
    g_sq = jnp.dot(col_sq, member, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)
    mean = g_sum / n                                   # [1, G]
    # clamp like flax's _compute_stats: f32 cancellation in E[x^2]-mean^2 can
    # go slightly negative for large-mean/small-spread activations
    var = jnp.maximum(g_sq / n - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)

    mean_c = jnp.dot(mean, member.T, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)  # [1, C]
    rstd_c = jnp.dot(rstd, member.T, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)
    y = (x - mean_c) * rstd_c * scale_ref[...] + bias_ref[...]
    if relu:
        # fused epilogue: saves the separate elementwise pass (and its HBM
        # round trip) that a GN-then-relu pair costs outside the kernel
        y = jnp.maximum(y, 0.0)
    y_ref[0] = y.astype(y_ref.dtype)
    mean_ref[0] = mean
    rstd_ref[0] = rstd


def _fwd_impl(x3, scale, bias, groups: int, eps: float, interpret: bool,
              relu: bool):
    b, s_dim, c = x3.shape
    kernel = functools.partial(_gn_fwd_kernel, groups=groups, eps=eps,
                               relu=relu)
    call = pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, s_dim, c), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, s_dim, c), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, groups), lambda i: (i, 0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s_dim, c), x3.dtype),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
            jax.ShapeDtypeStruct((b, 1, groups), jnp.float32),
        ],
        interpret=interpret,
    )
    y, mean, rstd = call(x3, scale.reshape(1, c), bias.reshape(1, c))
    return y, mean[:, 0], rstd[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_gn(x3, scale, bias, groups: int, eps: float, interpret: bool,
              relu: bool):
    y, _, _ = _fwd_impl(x3, scale, bias, groups, eps, interpret, relu)
    return y


def _fused_gn_fwd(x3, scale, bias, groups, eps, interpret, relu):
    y, mean, rstd = _fwd_impl(x3, scale, bias, groups, eps, interpret, relu)
    return y, (x3, scale, bias, mean, rstd)


def _fused_gn_bwd(groups, eps, interpret, relu, res, dy):
    x3, scale, bias, mean, rstd = res
    b, s_dim, c = x3.shape
    cg = c // groups
    n = s_dim * cg
    xf = x3.astype(jnp.float32).reshape(b, s_dim, groups, cg)
    dyf = dy.astype(jnp.float32)
    xhat = (xf - mean[:, None, :, None]) * rstd[:, None, :, None]
    xhat = xhat.reshape(b, s_dim, c)
    if relu:
        # relu VJP folded in: recompute the pre-relu output's sign from the
        # saved stats (no extra residual tensor) and zero the dead lanes
        pre = xhat * scale[None, None, :] + bias[None, None, :]
        dyf = jnp.where(pre > 0, dyf, 0.0)
    dxhat = (dyf * scale[None, None, :]).reshape(b, s_dim, groups, cg)
    xhat_g = xhat.reshape(b, s_dim, groups, cg)
    sum_dxhat = jnp.sum(dxhat, axis=(1, 3), keepdims=True)
    sum_dxhat_xhat = jnp.sum(dxhat * xhat_g, axis=(1, 3), keepdims=True)
    dx = (rstd[:, None, :, None] / n) * (
        n * dxhat - sum_dxhat - xhat_g * sum_dxhat_xhat
    )
    dscale = jnp.sum(dyf * xhat, axis=(0, 1))
    dbias = jnp.sum(dyf, axis=(0, 1))
    return dx.reshape(b, s_dim, c).astype(x3.dtype), dscale, dbias


_fused_gn.defvjp(_fused_gn_fwd, _fused_gn_bwd)


def fused_group_norm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    groups: int,
    eps: float = 1e-6,
    interpret: Optional[bool] = None,
    relu: bool = False,
) -> jnp.ndarray:
    """GroupNorm over the trailing channel axis of [B, ..., C], optionally
    with a fused relu epilogue (``relu=True``) — the GN→relu pair that every
    CNN block in the zoo uses (e.g. Net/Densenet.py:16-19) in one pass.

    Stats are computed in f32 regardless of input dtype (bf16-safe); the
    output matches the input dtype.
    """
    if interpret is None:
        interpret = _pk.interpret_default()
    shape = x.shape
    c = shape[-1]
    assert c % groups == 0, f"channels {c} not divisible by groups {groups}"
    b = shape[0]
    s_dim = 1
    for d in shape[1:-1]:
        s_dim *= d
    x3 = x.reshape(b, s_dim, c)
    y = _fused_gn(x3, scale.astype(jnp.float32), bias.astype(jnp.float32),
                  groups, eps, interpret, relu)
    return y.reshape(shape)
