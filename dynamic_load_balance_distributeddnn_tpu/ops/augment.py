"""On-device image augmentation.

The reference augments on the host through torchvision transforms
(RandomCrop(32, padding=4), RandomHorizontalFlip, Normalize —
dataloader.py:72-77). A per-image Python loop is exactly what a TPU host
should not be doing, so here the raw uint8 batch is shipped to the device and
the crop/flip/normalize run inside the jitted train step, vectorized with
vmap — they fuse into the first conv's input pipeline under XLA.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def normalize_images(x_u8: jnp.ndarray, mean, std) -> jnp.ndarray:
    """uint8 NHWC -> float32 normalized with dataset stats
    (dataloader.py:63/76/91)."""
    x = x_u8.astype(jnp.float32) / 255.0
    mean = jnp.asarray(mean, dtype=jnp.float32)
    std = jnp.asarray(std, dtype=jnp.float32)
    return (x - mean) / std


def augment_images(
    x_u8: jnp.ndarray,
    rng: jax.Array,
    mean,
    std,
    pad: int = 4,
    flip: bool = True,
) -> jnp.ndarray:
    """Random crop (with ``pad`` px reflection-free zero padding) + horizontal
    flip + normalize, one independent draw per example."""
    b, h, w, _ = x_u8.shape
    k_crop, k_flip = jax.random.split(rng)
    x = normalize_images(x_u8, mean, std)
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    offs = jax.random.randint(k_crop, (b, 2), 0, 2 * pad + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, img.shape[-1]))

    x = jax.vmap(crop_one)(xp, offs)
    if flip:
        do = jax.random.bernoulli(k_flip, 0.5, (b,))
        x = jnp.where(do[:, None, None, None], x[:, :, ::-1, :], x)
    return x
