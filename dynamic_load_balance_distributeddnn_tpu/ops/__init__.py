from dynamic_load_balance_distributeddnn_tpu.ops.losses import (
    example_weights,
    per_example_cross_entropy,
    per_example_nll,
)
from dynamic_load_balance_distributeddnn_tpu.ops.augment import augment_images, normalize_images

__all__ = [
    "example_weights",
    "per_example_cross_entropy",
    "per_example_nll",
    "augment_images",
    "normalize_images",
]
