"""On-device synthetic load for straggler injection (fault_mode='compute').

The reference simulates stragglers with host ``time.sleep`` slices inside the
step loop (dbs.py:103). In a single-controller SPMD process a host sleep would
stall *every* worker, so the compute-mode injector instead burns real MXU
cycles on the target device: a matmul chain whose trip count is a traced
scalar, so one compiled executable serves every slowdown level
(``lax.fori_loop`` keeps it a single XLA while loop — no data-dependent Python
control flow). The chain's output is returned so XLA cannot dead-code it.

``calibrate_iter_cost`` measures seconds/iteration once per backend, letting
callers convert "this worker should lose S seconds" into an iteration count —
the same contract as the reference's per-epoch wait seconds.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

_SIZE = 256  # matmul side; big enough to hit the MXU, small enough for VMEM


def synthetic_load(n_iters: jnp.ndarray, seed_val: jnp.ndarray) -> jnp.ndarray:
    """Run ``n_iters`` dependent matmuls; returns a scalar that must be kept
    live by the caller (e.g. summed into an aux output)."""
    x = jnp.full((_SIZE, _SIZE), 1e-4, dtype=jnp.float32) + seed_val * 1e-8

    def body(_, acc):
        return jnp.tanh(acc @ acc) * 0.5 + 0.5

    out = jax.lax.fori_loop(0, n_iters, body, x)
    return jnp.sum(out) * 1e-12


@functools.lru_cache(maxsize=4)
def calibrate_iter_cost(device_kind: str = "", iters: int = 200) -> float:
    """Seconds per synthetic-load iteration on the default backend."""
    fn = jax.jit(synthetic_load)
    fn(jnp.int32(8), jnp.float32(0)).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    fn(jnp.int32(iters), jnp.float32(0)).block_until_ready()
    dt_hi = time.perf_counter() - t0
    t0 = time.perf_counter()
    fn(jnp.int32(1), jnp.float32(0)).block_until_ready()
    dt_lo = time.perf_counter() - t0
    return max((dt_hi - dt_lo) / (iters - 1), 1e-9)
