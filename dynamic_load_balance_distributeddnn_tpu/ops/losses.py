"""Per-example losses and the weighted-gradient contract.

The reference combines worker gradients as sum_r (p_r / sum p) * g_r, where
g_r is worker r's mean-over-batch gradient and p_r its data share
(dbs.py:291-301). Here the same math is expressed once, per example: every
example e carries a weight w_e with sum_e w_e == 1 over the global batch, and
the combined gradient is the gradient of sum_e w_e * loss_e. Each worker
differentiates its local partial sum; a plain SUM across workers then
reproduces the reference's weighted combine exactly:

- DBS mode:  w_e = mask_e / N_total          (=> worker weight = count_r/N = p_r)
- `-de` mode: w_e = mask_e / (ws * count_r)  (=> worker weight = 1/ws,
                                              dbs.py:293's degraded branch)

Padding examples get w_e = 0, so the static padded shapes never perturb the
math.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def per_example_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Softmax cross-entropy per example (reference criterion for CNNs,
    dbs.py:374). logits: [..., C]; labels: [...] int."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None].astype(jnp.int32), axis=-1)
    return logz - gold[..., 0]


def per_example_nll(log_probs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Negative log-likelihood on log-probabilities (reference criterion for
    the Transformer LM, dbs.py:372)."""
    gold = jnp.take_along_axis(log_probs, labels[..., None].astype(jnp.int32), axis=-1)
    return -gold[..., 0]


def example_weights(
    mask: np.ndarray,
    total_true: int,
    worker_count: int,
    world_size: int,
    uniform_worker_weight: bool = False,
) -> np.ndarray:
    """Host-side weight vector for one worker's (padded) batch.

    ``uniform_worker_weight`` selects the `-de` degraded combine
    (parser.py:77-79, dbs.py:293).
    """
    m = mask.astype(np.float32)
    if uniform_worker_weight:
        denom = max(worker_count, 1) * world_size
    else:
        denom = max(total_true, 1)
    return m / float(denom)
