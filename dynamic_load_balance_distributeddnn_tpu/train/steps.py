"""Compiled training steps.

Two execution paths implement the reference's compute→combine→update loop
(dbs.py:228-238):

**Elastic path** — the DBS path. Each logical worker's forward/backward is its
own XLA executable, compiled for that worker's *bucketed* batch shape and
dispatched onto its device; workers sharing a device serialize there
(contention, like the reference's `-gpu 0,0,0,1`), workers on different
devices run concurrently (JAX async dispatch). Per-worker gradients are
weighted per-example (ops/losses.py) so a plain SUM reproduces the
reference's data-share-weighted combine (dbs.py:293-295); the sum + SGD
update runs as ONE fused collective over the mesh — deliberately unlike the
reference's per-parameter allreduce loop (dbs.py:294-300), which would be
poison on ICI (SURVEY §5.8).

**Fused path** — the uniform fast path (dbs off, or a converged uniform plan,
one worker per chip): a single jitted SPMD step via shard_map — local grad,
optional per-worker clip (reference clips before combining, dbs.py:274),
psum, replicated update. No Python dispatch per worker, full XLA fusion.

Both paths produce bitwise-identical update math for the same plan; they
differ only in scheduling.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamic_load_balance_distributeddnn_tpu.models import ModelSpec
from dynamic_load_balance_distributeddnn_tpu.ops.augment import augment_images, normalize_images
from dynamic_load_balance_distributeddnn_tpu.ops.faultload import synthetic_load
from dynamic_load_balance_distributeddnn_tpu.ops.losses import (
    per_example_cross_entropy,
    per_example_nll,
)
from dynamic_load_balance_distributeddnn_tpu.parallel import wire as wirefmt
from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import shard_map
from dynamic_load_balance_distributeddnn_tpu.train.state import TrainState


def _per_example_loss(
    spec: ModelSpec, outputs: jnp.ndarray, labels: jnp.ndarray, use_pallas: bool = False
) -> jnp.ndarray:
    if spec.output_kind == "log_probs":
        return per_example_nll(outputs, labels)
    if use_pallas:
        from dynamic_load_balance_distributeddnn_tpu.ops.pallas import fused_softmax_xent

        return fused_softmax_xent(outputs, labels)
    return per_example_cross_entropy(outputs, labels)


class StepLibrary:
    """Builds and caches every executable one model needs.

    jax.jit's own cache handles the per-shape (bucketed batch) and per-device
    specialization of the elastic path; this class just holds the closed-over
    configuration.
    """

    def __init__(
        self,
        spec: ModelSpec,
        mesh: Mesh,
        tx: optax.GradientTransformation,
        mean: Optional[np.ndarray] = None,
        std: Optional[np.ndarray] = None,
        augment: bool = False,
        grad_clip: float = 0.0,
        compute_dtype: Optional[Any] = None,
        use_pallas: bool = False,
        shard_update: bool = False,
        grad_accum: int = 1,
        compress_grads: str = "",
        remat: bool = False,
        grad_comm: str = "flat",
        grad_comm_wire: str = "int8",
        grad_comm_wires: Optional[Tuple[str, ...]] = None,
        zero1_padded: int = 0,
    ):
        self.spec = spec
        self.mesh = mesh
        self.tx = tx
        # Tree gradient collective (ISSUE 12, N-level since ISSUE 17): on a
        # >=2-level topology mesh (parallel/topology.py TopologyTree), the
        # combine reduce-scatters up the tree — fp32 over the innermost
        # (fastest) axis, then one hop per outer level on that hop's wire
        # codec (parallel/wire.py tree_allreduce) with per-hop
        # error-feedback residuals carried in the TrainState — and
        # all-gathers back down. "flat" keeps the one-psum combine (the
        # only choice on a 1-D mesh).
        self.grad_comm = grad_comm
        self.grad_comm_wire = grad_comm_wire
        self.axes = tuple(mesh.axis_names)
        self.hier = grad_comm == "hier" and len(self.axes) >= 2
        if grad_comm == "hier" and len(self.axes) < 2:
            raise ValueError(
                "grad_comm='hier' needs a tree mesh with >= 2 levels "
                "(parallel/mesh.py tree_mesh); the engine resolves the "
                "factorization and falls back to flat when none exists"
            )
        # Per-hop wire codecs, outermost hop first, one per mesh level; the
        # innermost hop is structurally fp32 (it is the reduce-scatter the
        # residual layout assumes error-free). Default: the legacy single
        # grad_comm_wire on the outermost (slowest) hop, fp32 below — the
        # exact PR-12 two-level behaviour on a two-level mesh.
        if grad_comm_wires:
            wires = tuple(grad_comm_wires)
        else:
            wires = (grad_comm_wire,) + ("fp32",) * max(len(self.axes) - 1, 0)
        if self.hier:
            if len(wires) != len(self.axes):
                raise ValueError(
                    f"grad_comm_wires needs one codec per mesh level: got "
                    f"{len(wires)} for axes {self.axes}"
                )
            if wires[-1] != "fp32":
                raise ValueError(
                    "the innermost tree hop must be fp32 (parallel/wire.py "
                    "tree_allreduce carries no residual for it)"
                )
            for w in wires:
                if w not in wirefmt.WIRE_FORMATS:
                    raise ValueError(f"unknown wire codec {w!r}")
        self.grad_comm_wires = wires
        self.mean = mean
        self.std = std
        self.augment = augment
        self.grad_clip = grad_clip
        self.use_pallas = use_pallas
        # bfloat16 mixed precision: params/activations cast for the forward/
        # backward, f32 master weights + f32 loss/grad accumulation
        self.compute_dtype = compute_dtype
        # Cross-replica weight-update sharding (ZeRO-1 analogue, arXiv
        # 2004.13336), generic over optax transforms since PR 13: gradients
        # reduce-scatter into 1/n flat chunks (optionally on the quantized
        # wire, or through the hierarchical ICI/DCN spine), tx.update runs
        # on the chunk against the flat-init sharded opt state
        # (train/state.py shard_optimizer_state), and the update delta
        # all-gathers back. ``zero1_padded`` is the flat padded parameter
        # count the engine computed at state conversion — the opt-state
        # spec and the update math key off it.
        self.shard_update = shard_update
        self.zero1_padded = int(zero1_padded)
        if shard_update and self.zero1_padded <= 0:
            raise ValueError(
                "shard_update needs zero1_padded (the flat padded parameter "
                "count from train/state.py zero1_padded_size)"
            )
        # State donation is DISABLED under the sharded update — a
        # correctness sanction, not a tuning choice: donating a carry that
        # holds the inject_hyperparams opt state miscompiles on XLA:CPU
        # (jax 0.4.37) — the wrapper's pass-through/astype'd hyperparam
        # outputs let the backend alias carry buffers it also donated, and
        # the SECOND invocation of the executable reads freed memory (nan
        # params, then heap corruption at teardown; reproduced
        # deterministically on fused_epoch, graph-shape dependent —
        # optimization_barrier fences moved the miscompile around instead
        # of killing it, so the sanction is categorical: no donated state
        # buffers, no freed-buffer aliasing). Cost: one transient extra
        # copy of params + the 1/n opt chunks per dispatch — the
        # steady-state optimizer memory the feature exists to shrink is
        # unaffected.
        self._state_donate: tuple = () if shard_update else (0,)
        # Micro-batching inside the fused step (lax.scan over batch slices,
        # grads summed before the collective) — exact under per-example
        # weighting; activation memory scales with batch/grad_accum.
        self.grad_accum = max(int(grad_accum), 1)
        # "int8": gradient collective quantized to 8-bit levels with a shared
        # pmax scale and STOCHASTIC rounding (unbiased — no error-feedback
        # state needed), summed in int16 on the wire. Halves collective bytes
        # vs f32 at 127-level precision; opt-in, fused path only.
        self.compress_grads = compress_grads
        # jax.checkpoint on the training forward: activations recomputed in
        # the backward instead of stored — exact same math, HBM for
        # activations traded for ~1/3 more FLOPs (the standard TPU memory
        # lever; lets batch/model scale past activation-memory limits).
        self.remat = remat
        # Optional AOT compile service (runtime/compiler.py), attached by the
        # engine: superstep_cache_size() folds its compiled superstep
        # variants into the compile-once accounting, since service-dispatched
        # supersteps never populate the lazy jit caches.
        self.aot_service = None
        self._build()

    @classmethod
    def zero1_shell(
        cls,
        mesh: Mesh,
        tx: optax.GradientTransformation,
        zero1_padded: int,
        *,
        hier: bool = False,
        wire: str = "fp32",
        wires: Optional[Tuple[str, ...]] = None,
        compress: str = "",
    ) -> "StepLibrary":
        """A minimal library exposing ONLY the ZeRO-1 update spine —
        ``_zero1_update`` + ``_state_spec`` with no model plumbing — for
        the zero1 A/B bench and the parity tests. Owned HERE so the set of
        attributes the spine reads lives next to the spine: drift breaks
        at this factory, not at bench time."""
        lib = cls.__new__(cls)
        lib.mesh = mesh
        lib.axes = tuple(mesh.axis_names)
        lib.hier = hier
        lib.tx = tx
        lib.shard_update = True
        lib.zero1_padded = int(zero1_padded)
        lib.compress_grads = compress
        lib.grad_comm_wire = wire
        lib.grad_comm_wires = (
            tuple(wires)
            if wires
            else (wire,) + ("fp32",) * max(len(lib.axes) - 1, 0)
        )
        lib._state_donate = ()
        return lib

    def _apply_train(self, params, x, rng):
        apply = lambda p, xx: self.spec.module.apply(  # noqa: E731
            self._cast_compute(p), xx, train=True, rngs={"dropout": rng}
        )
        if self.remat:
            # prevent_cse=False: safe (and recommended) because the remat'd
            # forward only ever runs under jit, including the grad-accum scan
            # body — avoids optimization barriers in the hot loop.
            return jax.checkpoint(apply, prevent_cse=False)(params, x)
        return apply(params, x)

    def _cast_compute(self, tree):
        if self.compute_dtype is None:
            return tree
        dt = self.compute_dtype
        return jax.tree_util.tree_map(
            lambda t: t.astype(dt) if hasattr(t, "dtype") and t.dtype == jnp.float32 else t,
            tree,
        )

    # ------------------------------------------------------------ input prep

    def _prep_images(self, x_u8: jnp.ndarray, rng: jax.Array, train: bool) -> jnp.ndarray:
        if self.spec.input_kind == "tokens":
            return x_u8
        if self.mean is None:
            return x_u8.astype(jnp.float32)
        if train and self.augment:
            return augment_images(x_u8, rng, self.mean, self.std)
        return normalize_images(x_u8, self.mean, self.std)

    # ----------------------------------------------------------- elastic path

    def _build(self):
        spec = self.spec

        def local_grads(params, x, y, w, rng, slow_iters, train_prep_rng):
            """Shared forward/backward for one worker's (padded) batch."""
            x = self._cast_compute(self._prep_images(x, train_prep_rng, train=True))

            def loss_fn(p):
                out = self._apply_train(p, x, rng)
                losses = _per_example_loss(spec, out.astype(jnp.float32), y, self.use_pallas)
                mask = (w > 0).astype(jnp.float32)
                wloss = jnp.sum(losses * w)
                return wloss, (jnp.sum(losses * mask), jnp.sum(mask))

            (wloss, (loss_sum, count)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)

            if self.grad_clip > 0:
                # The reference clips each worker's LOCAL mean gradient before
                # the weighted combine (dbs.py:274). Our local grad is
                # w_r * g_r, so unscale -> clip -> rescale.
                w_r = jnp.maximum(jnp.sum(w), 1e-12)
                unscaled = jax.tree_util.tree_map(lambda g: g / w_r, grads)
                gnorm = optax.global_norm(unscaled)
                scale = jnp.minimum(1.0, self.grad_clip / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

            # Straggler injection (fault_mode='compute'): real, unelidable MXU
            # work whose trip count is a traced scalar.
            probe = synthetic_load(slow_iters, wloss)
            return grads, wloss, loss_sum, count, probe

        @jax.jit
        def worker_step_first(params, x, y, w, rng, slow_iters):
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda t: t[None], g)
            return acc, (wloss, loss_sum, count, probe)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def worker_step_acc(params, acc, x, y, w, rng, slow_iters):
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda a, t: a + t[None], acc, g)
            return acc, (wloss, loss_sum, count, probe)

        self.worker_step_first = worker_step_first
        self.worker_step_acc = worker_step_acc
        # shared forward/backward closure, reused by the windowed and
        # superstep executables built lazily below
        self._local_grads = local_grads

        # Windowed twins: the whole staged window rides in once per window and
        # each call slices its step ON DEVICE (lax.dynamic_index_in_dim on a
        # traced step index), so a worker-step dispatch is ONE executable call
        # instead of one call plus 4 host-issued slice dispatches. The jit
        # cache specializes per (window length, bucketed batch) — the
        # superstep cache key of ISSUE 2 — and per device via the committed
        # inputs. Math after the slice is byte-for-byte local_grads.
        def _win_slice(s, *arrays):
            return tuple(
                jax.lax.dynamic_index_in_dim(a, s, 0, keepdims=False)
                for a in arrays
            )

        @jax.jit
        def worker_step_first_win(params, xw, yw, ww, kw, s, slow_iters):
            x, y, w, rng = _win_slice(s, xw, yw, ww, kw)
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda t: t[None], g)
            return acc, (wloss, loss_sum, count, probe)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def worker_step_acc_win(params, acc, xw, yw, ww, kw, s, slow_iters):
            x, y, w, rng = _win_slice(s, xw, yw, ww, kw)
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda a, t: a + t[None], acc, g)
            return acc, (wloss, loss_sum, count, probe)

        @jax.jit
        def worker_step_first_win_idx(
            params, train_x, train_y, iw, ww, kw, s, slow_iters
        ):
            idx, w, rng = _win_slice(s, iw, ww, kw)
            x = jnp.take(train_x, idx, axis=0, mode="clip")
            y = jnp.take(train_y, idx, axis=0, mode="clip")
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda t: t[None], g)
            return acc, (wloss, loss_sum, count, probe)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def worker_step_acc_win_idx(
            params, acc, train_x, train_y, iw, ww, kw, s, slow_iters
        ):
            idx, w, rng = _win_slice(s, iw, ww, kw)
            x = jnp.take(train_x, idx, axis=0, mode="clip")
            y = jnp.take(train_y, idx, axis=0, mode="clip")
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda a, t: a + t[None], acc, g)
            return acc, (wloss, loss_sum, count, probe)

        self.worker_step_first_win = worker_step_first_win
        self.worker_step_acc_win = worker_step_acc_win
        self.worker_step_first_win_idx = worker_step_first_win_idx
        self.worker_step_acc_win_idx = worker_step_acc_win_idx

        # Index-fed twins for the device-resident data cache: the train
        # arrays live in HBM; each step gathers its rows on device, so the
        # host sends [b_pad] int32 indices instead of the batch itself.
        # Padding slots index row 0 and carry weight 0 — identical math to
        # the materialized path (same rows, same weights).
        @jax.jit
        def worker_step_first_idx(params, train_x, train_y, idx, w, rng, slow_iters):
            x = jnp.take(train_x, idx, axis=0, mode="clip")
            y = jnp.take(train_y, idx, axis=0, mode="clip")
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda t: t[None], g)
            return acc, (wloss, loss_sum, count, probe)

        @functools.partial(jax.jit, donate_argnums=(1,))
        def worker_step_acc_idx(params, acc, train_x, train_y, idx, w, rng, slow_iters):
            x = jnp.take(train_x, idx, axis=0, mode="clip")
            y = jnp.take(train_y, idx, axis=0, mode="clip")
            g, wloss, loss_sum, count, probe = local_grads(
                params, x, y, w, rng, slow_iters, rng
            )
            acc = jax.tree_util.tree_map(lambda a, t: a + t[None], acc, g)
            return acc, (wloss, loss_sum, count, probe)

        self.worker_step_first_idx = worker_step_first_idx
        self.worker_step_acc_idx = worker_step_acc_idx

        # -------------------------------------------------- combine + update

        replicated = NamedSharding(self.mesh, P())
        tx = self.tx

        @functools.partial(
            jax.jit,
            donate_argnums=(0, 1),
            out_shardings=replicated,
        )
        def combine_update(state: TrainState, stacked_grads):
            grads = jax.tree_util.tree_map(lambda g: jnp.sum(g, axis=0), stacked_grads)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(params=params, opt_state=opt_state, step=state.step + 1)

        self.combine_update = combine_update

        # Non-donating twin used for timing probes: same collective + update
        # math, but inputs stay valid and the result is discarded, so probing
        # never double-applies an optimizer step.
        @functools.partial(jax.jit, out_shardings=replicated)
        def combine_probe(state: TrainState, stacked_grads):
            grads = jax.tree_util.tree_map(lambda g: jnp.sum(g, axis=0), stacked_grads)
            updates, opt_state = tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(params=params, opt_state=opt_state, step=state.step + 1)

        self.combine_probe = combine_probe

    # -------------------------------------------------- elastic superstep
    # (engine._train_epoch_elastic, ISSUE 2). One dispatch per WINDOW for a
    # whole device group: a lax.scan over the window's steps whose body
    # replays the per-step path's exact op sequence — each worker's
    # local_grads at its true bucketed shape, the [1,...]-stacked left-fold
    # accumulation, sum over the stacked axis, tx.update, apply — so the
    # result is bitwise-identical to per-step dispatch. Only valid when the
    # group spans EVERY worker (single-device topologies): with workers on
    # several devices, step k's gradients need step k-1's cross-device
    # combine, which no single-device scan can contain.

    def _superstep_body(self, state: TrainState, xs, ys, ws_, ks, slows):
        """One scanned step for a whole worker group: tuples hold one entry
        per worker, each at its own (static) bucketed shape."""
        acc = None
        aux = []
        for i in range(len(ws_)):
            g, wloss, loss_sum, count, probe = self._local_grads(
                state.params, xs[i], ys[i], ws_[i], ks[i], slows[i], ks[i]
            )
            if acc is None:
                acc = jax.tree_util.tree_map(lambda t: t[None], g)
            else:
                acc = jax.tree_util.tree_map(lambda a, t: a + t[None], acc, g)
            aux.append(jnp.stack([wloss, loss_sum, count, probe]))
        grads = jax.tree_util.tree_map(lambda t: jnp.sum(t, axis=0), acc)
        if self.shard_update:
            # ZeRO-1 inside the scan (the shard_update x scan-mode gap,
            # carried since PR 13): scan mode only exists on a 1-device
            # mesh, where the windowed zero-1 combine twin's collectives
            # are identities — with_comm=False with local_index=0 replays
            # the exact same chunk math (chunk == padded, off == 0) with
            # no collective-axis context needed, and the rng recipe
            # matches _sharded_combine_body's at axis index 0.
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0x5D1E), 0), state.step
            )
            state = self._zero1_update(
                state, grads, rng, with_comm=False, local_index=0
            )
        else:
            updates, opt_state = self.tx.update(
                grads, state.opt_state, state.params
            )
            params = optax.apply_updates(state.params, updates)
            state = state.replace(
                params=params, opt_state=opt_state, step=state.step + 1
            )
        return state, jnp.stack(aux)

    @functools.cached_property
    def group_superstep(self):
        """Materialized-feed superstep: carry = the full TrainState (the
        per-step combine cadence lives INSIDE the scan); scanned inputs are
        per-worker (x, y, w) windows plus the per-step rng keys — the same
        wkeys table the per-step path consumes, so the rng stream is
        identical. Returns (state, aux[win, n_workers, 4])."""

        def superstep(state, xs, ys, ws_, ks, slows):
            def body(st, inp):
                return self._superstep_body(st, *inp, slows)

            # unroll=True: a rolled scan lowers to a while-loop whose body
            # XLA emits with different reduction blocking than the
            # standalone executables — measurably (~1e-8) off the per-step
            # path. Fully unrolled, the window compiles to the same op
            # sequence and the bitwise-parity contract holds; the engine
            # bounds the unroll length via config.superstep_window.
            return jax.lax.scan(body, state, (xs, ys, ws_, ks), unroll=True)

        # donation rides the shard_update sanction (see _state_donate)
        return jax.jit(superstep, donate_argnums=self._state_donate)

    @functools.cached_property
    def group_superstep_idx(self):
        """Device-cache-fed superstep: the HBM-resident train arrays ride in
        whole (no re-transfer) and each scanned step gathers each worker's
        rows by index on device — the host ships [win, b_pad] int32 per
        worker instead of the batches."""

        def superstep(state, train_x, train_y, idxs, ws_, ks, slows):
            def body(st, inp):
                iw, ws_s, ks_s = inp
                xs = tuple(
                    jnp.take(train_x, i, axis=0, mode="clip") for i in iw
                )
                ys = tuple(
                    jnp.take(train_y, i, axis=0, mode="clip") for i in iw
                )
                return self._superstep_body(st, xs, ys, ws_s, ks_s, slows)

            # unroll=True: see group_superstep — bitwise parity requires the
            # unrolled lowering
            return jax.lax.scan(body, state, (idxs, ws_, ks), unroll=True)

        # donation rides the shard_update sanction (see _state_donate)
        return jax.jit(superstep, donate_argnums=self._state_donate)

    def superstep_cache_size(self) -> int:
        """Compiled (shape-tuple, window-length) superstep variants — the
        quantity the compile-once contract (tests/test_superstep.py) bounds.
        Counts both lazy-jit cache entries and AOT-service executables (the
        service dispatch path never touches the jit caches)."""
        n = 0
        for name in ("group_superstep", "group_superstep_idx"):
            fn = self.__dict__.get(name)
            if fn is not None:
                n += fn._cache_size()
        if self.aot_service is not None:
            n += self.aot_service.count_keys(("group_superstep",))
        return n

    # --------------------------------------- sharded-state combine twins
    # (elastic dispatch, ISSUEs 12/13): drop-in replacements for
    # combine_update / combine_probe when the combine itself must run
    # inside a shard_map body — the two-level hier spine, and/or the
    # ZeRO-1 sharded update (whose opt-state chunks and reduce-scatter are
    # per-device by construction). Each device sums its own [1, ...] slice
    # of the stacked partials, then the body routes: sharded update when
    # shard_update is on (the zero-1 math internally rides the hier spine
    # or the quantized flat wire as configured), else the hier
    # reduce-scatter / compressed-DCN-hop / all-gather plus the replicated
    # update — with the error-feedback residual carried through the
    # TrainState either way.

    def _sharded_combine_body(self, state: TrainState, stacked):
        local = jax.tree_util.tree_map(lambda g: jnp.sum(g, axis=0), stacked)
        rng = jax.random.fold_in(
            jax.random.fold_in(
                jax.random.PRNGKey(0x5D1E), self._data_axis_index()
            ),
            state.step,
        )
        if self.shard_update:
            return self._zero1_update(state, local, rng, with_comm=True)
        grads, new_residual = self._hier_combine(
            local, rng, state.comm_residual
        )
        updates, opt_state = self.tx.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return state.replace(
            params=params, opt_state=opt_state, step=state.step + 1,
            comm_residual=new_residual,
        )

    def _sharded_combine_twin(self, donate: bool):
        sharded = shard_map(
            self._sharded_combine_body,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(self._batch_entry)),
            out_specs=self._state_spec(),
            check_vma=False,
        )
        if donate:
            # the stacked partials (argnum 1) always donate; the state only
            # donates on the replicated-update (hier) twins — see the
            # _state_donate sanction in __init__
            return jax.jit(
                sharded, donate_argnums=self._state_donate + (1,)
            )
        return jax.jit(sharded)

    @functools.cached_property
    def combine_update_hier(self):
        return self._sharded_combine_twin(donate=True)

    @functools.cached_property
    def combine_probe_hier(self):
        """Non-donating twin for timing probes (inputs stay valid, result —
        including the would-be residual update — is discarded)."""
        return self._sharded_combine_twin(donate=False)

    @functools.cached_property
    def combine_update_zero1(self):
        """Flat-mesh ZeRO-1 combine twin (shard_update without hier): the
        same shard_map spine as the hier twins, with the body routed into
        the sharded update."""
        return self._sharded_combine_twin(donate=True)

    @functools.cached_property
    def combine_probe_zero1(self):
        return self._sharded_combine_twin(donate=False)

    # ------------------------------------------------------- AOT lowerables
    # The executable families the async compile service can pre-compile,
    # keyed by the names the engine uses in its service keys. Since ISSUE 5
    # the MESH-sharded programs are included too: the fused whole-epoch
    # scans (``fused_epoch``/``fused_epoch_idx``) and the combine twins
    # lower from ShapeDtypeStructs carrying explicit NamedShardings, so
    # warm-start AOT-submits them instead of paying their compile lazily
    # inside the excluded epoch 0 (the PR-3 single-host-probe gate, lifted).
    # Only the fused sync/FLOPs PROBES stay compile_now-with-concrete-args
    # (their input shardings derive from window indexing and are easiest to
    # match from the live arrays).

    def aot_lowerables(self) -> Dict[str, Callable]:
        out = {}
        if self.hier:
            # hier combine twins exist only on a tree mesh (>= 2 levels —
            # building them on a flat mesh would trace collectives over
            # axes the mesh does not define); with shard_update on they
            # ARE the sharded-update twins (the body routes)
            out["combine_update_hier"] = self.combine_update_hier
            out["combine_probe_hier"] = self.combine_probe_hier
        elif self.shard_update:
            out["combine_update_zero1"] = self.combine_update_zero1
            out["combine_probe_zero1"] = self.combine_probe_zero1
        out.update(self._aot_lowerables_base())
        return out

    def _aot_lowerables_base(self) -> Dict[str, Callable]:
        return {
            "worker_first": self.worker_step_first,
            "worker_acc": self.worker_step_acc,
            "worker_first_idx": self.worker_step_first_idx,
            "worker_acc_idx": self.worker_step_acc_idx,
            "worker_first_win": self.worker_step_first_win,
            "worker_acc_win": self.worker_step_acc_win,
            "worker_first_win_idx": self.worker_step_first_win_idx,
            "worker_acc_win_idx": self.worker_step_acc_win_idx,
            "group_superstep": self.group_superstep,
            "group_superstep_idx": self.group_superstep_idx,
            "fused_epoch": self.fused_epoch,
            "fused_epoch_idx": self.fused_epoch_idx,
            "combine_update": self.combine_update,
            "combine_probe": self.combine_probe,
        }

    # ------------------------------------------------------------ fused path
    # (evaluation is always the sharded fused_eval_step — there is no
    # single-device eval path)

    # -------------------------------------------------- mesh-axis plumbing
    # The mesh is 1-D ("data") on flat runs and an N-level topology tree
    # (outermost axis first) when the tree combine resolved. Every
    # collective/spec in the fused bodies routes through these helpers so
    # one code path serves every factorization — on a flat mesh each
    # helper degenerates to exactly the pre-hier spelling (same axis
    # string, same lowering, bitwise-same programs).

    @property
    def _axis_arg(self):
        """Collective axis argument — the lone axis name, or the axis tuple
        (jax.lax collectives reduce over every named axis). ONE source of
        truth with the engine's placement specs: parallel/mesh.py
        ``mesh_batch_axes`` — collectives and batch sharding diverging on
        which axes "the whole mesh" means would reduce gradients over a
        different axis set than the data is sharded on."""
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
            mesh_batch_axes,
        )

        return mesh_batch_axes(self.mesh)

    @property
    def _batch_entry(self):
        """PartitionSpec entry splitting a batch dim over the whole mesh —
        the same value as :attr:`_axis_arg` (P treats a tuple entry as one
        dim split over all named axes); kept as its own name so spec sites
        read as sharding, collective sites as reduction."""
        return self._axis_arg

    def _data_axis_index(self):
        """Flat device position inside a shard_map body: the mixed-radix
        fold of the per-axis indices, outermost axis most significant —
        identical numbering under EVERY factorization (tree_mesh reshapes
        row-major), so per-device rng folds are invariant to the mesh
        shape."""
        if len(self.axes) == 1:
            return jax.lax.axis_index(self.axes[0])
        idx = jax.lax.axis_index(self.axes[0])
        for a in self.axes[1:]:
            idx = idx * int(self.mesh.shape[a]) + jax.lax.axis_index(a)
        return idx

    # ------------------------------------------- tree gradient combine
    # (ISSUE 12, N-level since ISSUE 17, after DynamiQ's compressed
    # multi-hop all-reduce): reduce-scatter UP the topology tree — fp32
    # over the innermost (fastest) axis, then one hop per outer level on
    # that hop's wire codec, shrinking the vector by the level size each
    # hop — and all-gather back DOWN. Per-hop error-feedback residuals
    # (TrainState.comm_residual) make the biased wires convergent
    # (parallel/wire.py).

    def _hier_combine(self, grads, rng, residual):
        """N-level tree gradient reduction inside a shard_map body.

        ``grads``: this device's local gradient tree. ``residual``: this
        device's per-hop error-feedback rows — a tuple with one [1, W_i]
        slice of ``TrainState.comm_residual`` per hop 0..k-1, outermost
        first. Returns ``(reduced grads tree, new residual tuple)``. The
        tree is raveled ONCE so the whole combine is 2k+1 collectives
        regardless of leaf count (the flat combine pays one psum per
        leaf); the spine itself lives in parallel/wire.py so the
        grad_comm bench times the identical code."""
        names = self.axes
        sizes = tuple(int(self.mesh.shape[a]) for a in names)
        out, new_residual = wirefmt.tree_allreduce(
            grads,
            rng,
            names,
            sizes,
            self.grad_comm_wires,
            residuals=(
                tuple(r[0] for r in residual) if residual is not None else None
            ),
        )
        return out, tuple(r[None] for r in new_residual)

    @functools.cached_property
    def _opt_state_spec(self):
        """Per-leaf shard_map spec pytree of the GENERIC flat-init sharded
        optimizer state (train/state.py shard_optimizer_state): leaves
        whose leading dim is the padded flat parameter count are the 1/n
        chunks (split over the zero-1 chunk axes — device-major on a
        two-level mesh), everything else (inject_hyperparams' lr, adam's
        count) is replicated. Derived from ``tx.init``'s abstract shapes so
        arbitrary optax transforms spec themselves."""
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
            zero1_chunk_axes,
        )

        padded = self.zero1_padded
        ax = zero1_chunk_axes(self.mesh)
        abs_state = jax.eval_shape(
            self.tx.init, jax.ShapeDtypeStruct((padded,), jnp.float32)
        )
        return jax.tree_util.tree_map(
            lambda l: P(ax) if (l.ndim >= 1 and l.shape[0] == padded) else P(),
            abs_state,
        )

    def _state_spec(self):
        """shard_map spec for the TrainState: fully replicated, except the
        flat 1/n optimizer chunks when weight-update sharding is on
        (prefix-spec pytree: ``params=P()`` covers the whole params
        subtree) and the per-device error-feedback residual on
        hierarchical runs."""
        from dynamic_load_balance_distributeddnn_tpu.train.state import (
            TrainState as TS,
        )

        if self.shard_update:
            return TS(
                params=P(),
                opt_state=self._opt_state_spec,
                step=P(),
                comm_residual=P(self._batch_entry) if self.hier else P(),
            )
        if self.hier:
            return TS(
                params=P(),
                opt_state=P(),
                step=P(),
                comm_residual=P(self._batch_entry),
            )
        return P()

    def _fused_shard_body(self, state, x, y, w, slow_scalar, seed, with_comm=True):
        """Per-device body of the fused SPMD step: local grad, optional
        per-worker clip (reference clips before combining, dbs.py:274), psum,
        replicated SGD update.

        ``with_comm=False`` builds the comm-free twin used by the sync-time
        probe (engine._probe_fused_sync): identical math except the psums are
        skipped, so (t_full − t_nocomm) isolates the collective cost — the
        fused-path analogue of the reference's per-step allreduce wait meter
        (dbs.py:297-299)."""
        spec = self.spec
        tx = self.tx
        idx = self._data_axis_index()
        rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(0), seed), idx),
            state.step,
        )

        def slice_grads(x_s, y_s, w_s, rng_s):
            """Weighted loss + grads for one (micro-)batch slice. Per-example
            weighting makes accumulation exact: sums of weighted slice grads
            equal the whole-batch weighted grad."""
            x_p = self._cast_compute(self._prep_images(x_s, rng_s, train=True))

            def loss_fn(p):
                out = self._apply_train(p, x_p, rng_s)
                losses = _per_example_loss(
                    spec, out.astype(jnp.float32), y_s, self.use_pallas
                )
                mask = (w_s > 0).astype(jnp.float32)
                return jnp.sum(losses * w_s), (jnp.sum(losses * mask), jnp.sum(mask))

            return jax.value_and_grad(loss_fn, has_aux=True)(state.params)

        acc = self.grad_accum
        if acc > 1:
            b = x.shape[0]
            assert b % acc == 0, (
                f"per-device batch {b} must divide by grad_accum {acc}"
            )

            def micro(carry, inp):
                g_acc, wl, ls, cnt, i = carry
                x_s, y_s, w_s = inp
                (wl_s, (ls_s, cnt_s)), g = slice_grads(
                    x_s, y_s, w_s, jax.random.fold_in(rng, i)
                )
                g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                return (g_acc, wl + wl_s, ls + ls_s, cnt + cnt_s, i + 1), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            stacked = (
                x.reshape((acc, b // acc) + x.shape[1:]),
                y.reshape((acc, b // acc) + y.shape[1:]),
                w.reshape((acc, b // acc) + w.shape[1:]),
            )
            (grads, wloss, loss_sum, count, _), _ = jax.lax.scan(
                micro,
                (zeros, jnp.float32(0), jnp.float32(0), jnp.float32(0), jnp.int32(0)),
                stacked,
            )
        else:
            (wloss, (loss_sum, count)), grads = slice_grads(x, y, w, rng)
        if self.grad_clip > 0:
            w_r = jnp.maximum(jnp.sum(w), 1e-12)
            unscaled = jax.tree_util.tree_map(lambda g: g / w_r, grads)
            gnorm = optax.global_norm(unscaled)
            scale = jnp.minimum(1.0, self.grad_clip / jnp.maximum(gnorm, 1e-12))
            grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

        probe = synthetic_load(slow_scalar, wloss)
        metrics = jnp.stack([wloss, loss_sum, count, probe])
        if self.shard_update:
            state = self._zero1_update(
                state, grads, jax.random.fold_in(rng, 0x7FFF), with_comm
            )
            if with_comm:
                metrics = jax.lax.psum(metrics, self._axis_arg)
            return state, metrics
        new_residual = state.comm_residual
        if with_comm:
            if self.hier:
                grads, new_residual = self._hier_combine(
                    grads, jax.random.fold_in(rng, 0x7FFF), state.comm_residual
                )
            elif self.compress_grads == "int8":
                grads = self._compressed_psum(grads, rng)
            else:
                grads = jax.lax.psum(grads, self._axis_arg)
            metrics = jax.lax.psum(metrics, self._axis_arg)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        state = state.replace(
            params=params, opt_state=opt_state, step=state.step + 1,
            comm_residual=new_residual,
        )
        return state, metrics

    def _compressed_psum(self, grads, rng):
        """Quantized FLAT gradient collective (compressed-allreduce family):
        per leaf, one stochastic-rounded int8 all-reduce hop over the whole
        mesh (parallel/wire.py — E[dequant] == grad, so no error-feedback
        buffer is required), summed in int16 on the wire — half the bytes of
        an f32 collective. The per-leaf scale pmax is a scalar, negligible
        next to the tensor traffic. The hierarchical combine generalizes
        this into the cross-host hop of _hier_combine."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        n = len(self.mesh.devices.flat)
        out = []
        for i, g in enumerate(leaves):
            key = jax.random.fold_in(rng, i + 0x7FFF)
            total, _sent = wirefmt.compressed_reduce(
                g, key, self._axis_arg, n, "int8"
            )
            out.append(total.astype(g.dtype))
        return jax.tree_util.tree_unflatten(treedef, out)

    def _zero1_update(
        self, state, local_grads, rng, with_comm: bool, local_index=None
    ):
        """Generic sharded optimizer update (ZeRO-1 analogue, arXiv
        2004.13336) over an ARBITRARY optax transform: ravel the gradient
        tree ONCE, reduce-scatter into this device's 1/n chunk, run
        ``tx.update`` on the chunk against the chunked opt state and the
        matching flat param chunk (param-dependent transforms — adamw's
        weight decay — see exactly their slice), all-gather the update
        delta, apply. Exact for elementwise transforms — identical per
        element to the replicated per-leaf update (the update shard is
        uniform even when data shards are not, which is why this composes
        with DBS).

        Wire composition (PR-12, N-level since ISSUE 17): on a tree mesh
        the reduce-scatter walks the tree — full-precision over the
        innermost (fastest) axis, then one EF'd hop per outer level on
        that hop's ``grad_comm_wires`` codec, the outermost hop a
        compressed all-reduce of the top chunk; each device then keeps
        its mixed-radix flat block (innermost axis most significant —
        parallel/mesh.py zero1_chunk_axes), so the two-level layout
        ``d*H + h`` is unchanged. On the flat mesh,
        ``compress_grads='int8'`` rides the quantized reduce-scatter
        (parallel/wire.py compressed_reduce_scatter). ``with_comm=False``
        builds the comm-free probe twin: same FLOPs shape, collectives
        replaced by local slices/pads (output is discarded) — and, with
        ``local_index`` given, the AXIS-FREE twin the scan-mode superstep
        runs under plain jit (no shard_map axis context): the caller
        supplies the flat chunk index instead of ``_data_axis_index()``.
        On the 1-device mesh that path exists on, chunk == padded and the
        slice/pad pair is the identity the size-1 collectives would be."""
        import jax.flatten_util

        opt = state.opt_state
        n = len(self.mesh.devices.flat)
        flat_g, unravel = jax.flatten_util.ravel_pytree(local_grads)
        t_real = flat_g.size
        # the ctor-validated padding is THE convention (train/state.py
        # zero1_padded_size) — recomputing it here could silently diverge
        # from the state conversion's chunk layout
        padded = self.zero1_padded
        assert padded % n == 0 and padded >= t_real, (padded, n, t_real)
        flat_g = jnp.pad(flat_g, (0, padded - t_real))
        chunk = padded // n
        new_residual = state.comm_residual
        key = jax.random.fold_in(rng, 0x2E01)
        if self.hier:
            names = self.axes
            sizes = tuple(int(self.mesh.shape[a]) for a in names)
            k = len(names) - 1
            idxs = [jax.lax.axis_index(a) for a in names]
            # same padding convention as attach_comm_residual(pad_multiple=n)
            widths = wirefmt.tree_hop_widths(t_real, sizes, pad_multiple=n)
            assert widths[-1] == padded, (widths, padded)
            # this device's flat block: mixed-radix offset with the
            # innermost axis most significant (zero1_chunk_axes order) —
            # exactly where the scatter cascade below lands its chunk
            off = idxs[0] * chunk
            for i in range(1, k + 1):
                off = off + idxs[i] * widths[i - 1]
            if with_comm:
                # innermost reduce-scatter at full precision (ICI): the
                # device's index along the fastest axis picks its
                # widths[k-1] slice of the in-group sum
                v = jax.lax.psum_scatter(
                    flat_g, names[k], scatter_dimension=0, tiled=True
                )
                res = state.comm_residual
                new_rows = list(res) if res is not None else [None] * k
                # middle hops k-1..1: EF'd compressed reduce-scatter on
                # each hop's wire, vector shrinking by sizes[i] per hop
                for i in range(k - 1, 0, -1):
                    vi = v + (res[i][0] if res is not None else 0.0)
                    v, sent = wirefmt.compressed_reduce_scatter_ef(
                        vi,
                        jax.random.fold_in(key, i),
                        names[i],
                        sizes[i],
                        self.grad_comm_wires[i],
                    )
                    new_rows[i] = (vi - sent)[None]
                # top hop: compressed all-reduce of the widths[0] chunk
                v0 = v + (res[0][0] if res is not None else 0.0)
                total, sent = wirefmt.compressed_reduce(
                    v0,
                    jax.random.fold_in(key, 0),
                    names[0],
                    sizes[0],
                    self.grad_comm_wires[0],
                )
                new_rows[0] = (v0 - sent)[None]
                new_residual = tuple(new_rows)
                # re-split across the top level: index a_0 owns the a_0-th
                # 1/s_0 sub-slice of the fully reduced top chunk
                g_chunk = jax.lax.dynamic_slice(
                    total, (idxs[0] * chunk,), (chunk,)
                )
            else:
                g_chunk = jax.lax.dynamic_slice(flat_g, (off,), (chunk,))
        else:
            # A size-1 data axis makes the uncompressed collectives
            # identities — route the slice twin instead, so single-device
            # topologies compile the SAME flat-update program on every
            # dispatch path (per-step combine twin, fused shard body,
            # scan-mode superstep). The scan x zero1 bitwise-parity
            # contract rides on the lowering being shared, not merely
            # value-equal: XLA contracts the update chain differently
            # around a collective than around a slice (ulp-scale drift no
            # optimization_barrier placement removes). The quantized wire
            # stays collective — stochastic rounding is no identity even
            # over one device.
            if n == 1 and self.compress_grads != "int8":
                with_comm = False
                if local_index is None:
                    local_index = 0
            off = (
                self._data_axis_index() if local_index is None else local_index
            ) * chunk
            if with_comm:
                if self.compress_grads == "int8":
                    g_chunk = wirefmt.compressed_reduce_scatter(
                        flat_g, key, self._axis_arg, n, "int8"
                    )
                else:
                    g_chunk = jax.lax.psum_scatter(
                        flat_g, self._axis_arg, scatter_dimension=0, tiled=True
                    )
            else:
                g_chunk = jax.lax.dynamic_slice(flat_g, (off,), (chunk,))
        flat_p, _ = jax.flatten_util.ravel_pytree(state.params)
        flat_p = jnp.pad(flat_p.astype(jnp.float32), (0, padded - t_real))
        p_chunk = jax.lax.dynamic_slice(flat_p, (off,), (chunk,))
        updates_chunk, opt_state = self.tx.update(g_chunk, opt, p_chunk)
        if with_comm:
            if self.hier:
                # gather back in layout order, outermost axis first (each
                # gather rebuilds the next-wider hop vector, inverting the
                # scatter cascade LIFO), innermost last (rebuilds the flat
                # vector)
                delta = updates_chunk
                for a in self.axes:
                    delta = jax.lax.all_gather(delta, a, tiled=True)
            else:
                delta = jax.lax.all_gather(
                    updates_chunk, self._axis_arg, tiled=True
                )
        else:
            delta = jax.lax.dynamic_update_slice(
                jnp.zeros((padded,), updates_chunk.dtype), updates_chunk, (off,)
            )
        params = jax.tree_util.tree_map(
            lambda p, u: p + u.reshape(p.shape).astype(p.dtype),
            state.params,
            unravel(delta[:t_real]),
        )
        return state.replace(
            params=params, opt_state=opt_state, step=state.step + 1,
            comm_residual=new_residual,
        )

    @functools.cached_property
    def fused_step(self):
        """One-jit SPMD step for uniform plans with one worker per device.
        Inputs: state (replicated), batch [D*b, ...] (sharded on 'data'),
        per-example weights, per-device slow_iters [D], scalar seed."""

        def per_shard(state, x, y, w, slow_iters, seed):
            return self._fused_shard_body(state, x, y, w, slow_iters[0], seed)

        bx = self._batch_entry
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(bx), P(bx), P(bx), P(bx), P()),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=self._state_donate)

    @functools.cached_property
    def fused_epoch(self):
        """A whole epoch in ONE dispatch: lax.scan over the step axis inside
        the SPMD program. Inputs are the full epoch's batches
        [steps, D*b, ...] (sharded on the batch axis); state is carried by the
        scan. The dbs-off / converged-uniform fast path — no per-step Python,
        full XLA pipelining."""

        def per_shard(state, xs, ys, ws_, slow_iters, seed):
            def body(state, inp):
                x, y, w = inp
                return self._fused_shard_body(state, x, y, w, slow_iters[0], seed)

            state, metrics = jax.lax.scan(body, state, (xs, ys, ws_))
            return state, jnp.sum(metrics, axis=0)

        bx = self._batch_entry
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(
                self._state_spec(),
                P(None, bx),
                P(None, bx),
                P(None, bx),
                P(bx),
                P(),
            ),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=self._state_donate)

    @functools.cached_property
    def fused_epoch_idx(self):
        """``fused_epoch`` fed by the device-resident data cache: the train
        arrays are passed replicated (already on device — no re-transfer) and
        each scanned step gathers its rows by index on device. The per-epoch
        host->device traffic is [steps, D*b] int32 + f32 weights instead of
        the batches themselves — the whole-dataset epoch transfer disappears."""

        def per_shard(state, train_x, train_y, idxs, ws_, slow_iters, seed):
            def body(state, inp):
                idx_s, w = inp
                x = jnp.take(train_x, idx_s, axis=0, mode="clip")
                y = jnp.take(train_y, idx_s, axis=0, mode="clip")
                return self._fused_shard_body(state, x, y, w, slow_iters[0], seed)

            state, metrics = jax.lax.scan(body, state, (idxs, ws_))
            return state, jnp.sum(metrics, axis=0)

        bx = self._batch_entry
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(
                self._state_spec(),
                P(),
                P(),
                P(None, bx),
                P(None, bx),
                P(bx),
                P(),
            ),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return jax.jit(sharded, donate_argnums=self._state_donate)

    def _fused_probe(self, with_comm: bool):
        """Non-donating single-step twin of ``fused_step`` for timing probes.
        ``with_comm=False`` drops the psums (see _fused_shard_body); outputs
        are discarded by the caller, so the unreplicated no-comm outputs are
        harmless (check_vma is off)."""

        def per_shard(state, x, y, w, slow_iters, seed):
            return self._fused_shard_body(
                state, x, y, w, slow_iters[0], seed, with_comm=with_comm
            )

        bx = self._batch_entry
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(self._state_spec(), P(bx), P(bx), P(bx), P(bx), P()),
            out_specs=(self._state_spec(), P()),
            check_vma=False,
        )
        return jax.jit(sharded)

    @functools.cached_property
    def fused_step_probe(self):
        return self._fused_probe(with_comm=True)

    @functools.cached_property
    def fused_step_nocomm(self):
        return self._fused_probe(with_comm=False)

    @functools.cached_property
    def comm_probe(self):
        """Standalone gradient collective: psum of a grads-shaped tree over
        the mesh. Fallback sync-time meter when the full-vs-nocomm delta is
        below timer noise — the closest analogue of the reference's blocking
        allreduce wait (dbs.py:296-298)."""

        axes = self._axis_arg

        def per_shard(tree):
            return jax.lax.psum(tree, axes)

        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(P(),),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(sharded)

    @functools.cached_property
    def fused_eval_step(self):
        """Sharded evaluation over the mesh — the whole test batch split across
        devices. (The reference redundantly evaluates the FULL test set on
        every rank, dbs.py:147; sharding it is the same math, ws× faster.)"""
        spec = self.spec
        apply_fn = spec.module.apply
        prep = self._prep_images
        axes = self._axis_arg

        def per_shard(params, x, y, mask):
            xf = prep(x, jax.random.PRNGKey(0), train=False)
            out = apply_fn(params, xf, train=False)
            losses = _per_example_loss(spec, out, y)
            m = mask.astype(jnp.float32)
            pred = jnp.argmax(out, axis=-1)
            stats = jnp.stack(
                [jnp.sum(losses * m), jnp.sum((pred == y).astype(jnp.float32) * m), jnp.sum(m)]
            )
            return jax.lax.psum(stats, axes)

        bx = self._batch_entry
        sharded = shard_map(
            per_shard,
            mesh=self.mesh,
            in_specs=(P(), P(bx), P(bx), P(bx)),
            out_specs=P(),
            check_vma=False,
        )
        return jax.jit(sharded)


def stack_partials(partials_by_device, mesh: Mesh):
    """Zero-copy assembly of per-device gradient partials (each with a leading
    [1, ...] axis, living on its device) into global arrays sharded over the
    mesh — the input of combine_update. This is the moment the reference would
    enter its gloo allreduce (dbs.py:296); here it is just array surgery, the
    actual reduction happens inside the combine_update collective.

    Multi-host: each process passes only its local devices' partials (the
    mesh's addressable slice); JAX matches shards to mesh positions by device,
    and the cross-host reduction happens inside the combine collective over
    DCN."""
    n_local = len(partials_by_device)
    n_global = len(mesh.devices.flat)
    assert n_local == len([d for d in mesh.devices.flat if d.process_index == jax.process_index()])
    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        mesh_batch_axes,
    )

    sharding = NamedSharding(mesh, P(mesh_batch_axes(mesh)))

    leaves_by_dev = [jax.tree_util.tree_leaves(p) for p in partials_by_device]
    treedef = jax.tree_util.tree_structure(partials_by_device[0])
    stacked_leaves = []
    for li in range(len(leaves_by_dev[0])):
        shards = [leaves_by_dev[d][li] for d in range(n_local)]
        shape = (n_global,) + tuple(shards[0].shape[1:])
        stacked_leaves.append(
            jax.make_array_from_single_device_arrays(shape, sharding, shards)
        )
    return jax.tree_util.tree_unflatten(treedef, stacked_leaves)


def shard_views(tree, devices):
    """Per-device single-device views of a replicated global tree: one tree
    per requested device whose leaves are that device's local shards (no
    copies)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    views = [[] for _ in devices]
    index = {dev: i for i, dev in enumerate(devices)}
    for leaf in leaves:
        hit = 0
        for s in leaf.addressable_shards:
            i = index.get(s.device)
            if i is not None:
                views[i].append(s.data)
                hit += 1
        assert hit == len(devices), "replicated tree missing shards for mesh devices"
    return [jax.tree_util.tree_unflatten(treedef, v) for v in views]
