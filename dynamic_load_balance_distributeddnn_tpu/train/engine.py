"""The training engine: the DBS feedback loop.

The reference's per-worker epoch loop (dbs.py:313-446) becomes one controller
driving all logical workers:

    for epoch:
        adjust LR (one-cycle)                        dbs.py:386-387
        shares <- solver(node_times, shares)         dbs.py:388-391
        plan   <- partition dataset + batch sizes    dbs.py:394-395
        train one epoch (elastic or fused path)      dbs.py:408-413
        validate                                     dbs.py:417-421
        node_times <- per-worker compute times       dbs.py:423-426
        record the 9 metric series                   dbs.py:428-438

Per-worker compute time on an async SPMD runtime cannot be a naive
``time.time()`` around a dispatched call (SURVEY §5.1), so the engine times a
*probe*: one standalone execution of each worker's step (blocking, after
warm-up), scaled by the worker's step count. Probes inherently include
compute-mode injected load; virtual-mode injection is added to the vector
afterwards. Communication (combine+update) is probed separately and never
enters the solver's time vector — the reference's compute/comm split contract
(dbs.py:250, 297-299).
"""

from __future__ import annotations

import concurrent.futures
import os
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dynamic_load_balance_distributeddnn_tpu.analysis.guards import CompileTracker
from dynamic_load_balance_distributeddnn_tpu.balance import (
    HostOverheadMeter,
    TimeKeeper,
    exchange_times,
    initial_partition,
    integer_batch_split,
    rebalance,
)
from dynamic_load_balance_distributeddnn_tpu.balance.controller import (
    OnlineRebalanceController,
    step_time,
)
from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    ShareTrajectoryPredictor,
    equilibrium_shares,
    quantize_batches,
)
from dynamic_load_balance_distributeddnn_tpu.config import Config
from dynamic_load_balance_distributeddnn_tpu.data import (
    DatasetBundle,
    build_epoch_plan,
    build_remainder_plan,
    load_dataset,
)
from dynamic_load_balance_distributeddnn_tpu.faults import (
    EpochFaults,
    FaultContext,
    FaultInjector,
    LuckyFaultInjector,
    NullInjector,
    ScheduledStragglerInjector,
    StaticStragglerInjector,
)
from dynamic_load_balance_distributeddnn_tpu.models import build_model
from dynamic_load_balance_distributeddnn_tpu.obs import (
    MetricsRecorder,
    MetricsRegistry,
    init_logger,
)
from dynamic_load_balance_distributeddnn_tpu.obs.trace import EPOCH_CAT, get_tracer
from dynamic_load_balance_distributeddnn_tpu.ops.faultload import calibrate_iter_cost
from dynamic_load_balance_distributeddnn_tpu.ops.losses import example_weights
from dynamic_load_balance_distributeddnn_tpu.parallel import WorkerTopology, data_mesh
from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import replicated_sharding
from dynamic_load_balance_distributeddnn_tpu.runtime.compiler import AOTCompileService
from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
    WorkerHealth,
    WorkerLost,
    retry_transient,
)
from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import heartbeat
from dynamic_load_balance_distributeddnn_tpu.train.schedule import one_cycle_lr
from dynamic_load_balance_distributeddnn_tpu.train.state import create_state, make_optimizer
from dynamic_load_balance_distributeddnn_tpu.train.pipeline import (
    WindowTransferPipeline,
)
from dynamic_load_balance_distributeddnn_tpu.train.steps import (
    StepLibrary,
    shard_views,
    stack_partials,
)

# Dispatch-overhead probe op, constructed ONCE per process: building it inside
# _probe_workers (the pre-fix form, kept as the canonical G001 fixture in
# tests/fixtures/graftlint/g001_violation.py) made every probe epoch pay a
# fresh wrapper + XLA compile for a no-op.
_tiny_sync_probe = jax.jit(lambda a: a + 1.0)


class Trainer:
    """Vision-model trainer (the Transformer-LM path lives in
    train/lm_engine.py and shares this controller's balance machinery)."""

    # Subclasses opt out of bucket snapping (the LM path's "batch" is a small
    # column count where bucket quantization would distort the balance).
    SNAP_BATCHES = True

    def __init__(
        self,
        cfg: Config,
        bundle: Optional[DatasetBundle] = None,
        injector: Optional[FaultInjector] = None,
        logger=None,
        log_to_file: bool = True,
        timing_model=None,
        job_id: Optional[str] = None,
    ):
        """``timing_model``: optional callable(plan) -> per-worker seconds,
        replacing wall-clock probes with a deterministic model — used by tests
        to verify the controller dynamics hermetically (wall-clock on tiny CPU
        batches is dispatch-overhead-dominated and not ∝ batch size).

        ``job_id``: tenant tag when this trainer is one stream of a
        :class:`~..runtime.scheduler.MultiStreamEngine` pool. Folded into
        ``_comm_sig`` so every AOT-registry key carries the tenant — two
        jobs with identical model/topology must never resolve each other's
        executables through any shared compile cache or artifact."""
        self.cfg = cfg
        self.timing_model = timing_model
        self.job_id = job_id
        self.logger = logger or init_logger(cfg, to_file=log_to_file)

        # graftscope tracer, configured FIRST (see the fuller note at the
        # MetricsRegistry construction below): instrumentation that runs
        # during init itself — the hier combine's link-bandwidth probe and
        # its comm_* phase spans — must land in THIS run's trace, not the
        # previous configuration's buffer (or the void). A TENANT trainer
        # (job_id set — one stream of a MultiStreamEngine) must NOT
        # reconfigure the process-wide tracer: configure() rebuilds the
        # event buffer and the thread-local job-tag slots, so a second
        # tenant's admission would drop every earlier tenant's spans and
        # untag their worker threads. In many-stream mode the engine's
        # caller owns the tracer config; per-tenant trace flags are
        # ignored.
        if job_id is None:
            self._trace = get_tracer().configure(
                cfg.trace,
                ring_size=cfg.trace_ring,
                jax_annotations=cfg.trace_annotations,
            )
        else:
            self._trace = get_tracer()

        # Multi-host: each process owns a contiguous slice of the global
        # workers, mapped onto its LOCAL devices; the combine mesh spans every
        # process's used devices (XLA collectives ride ICI within a host, DCN
        # across — the reference's gloo ring analogue, SURVEY §5.8). All
        # processes replicate the plan/solver deterministically, so the only
        # cross-host traffic is gradients (in-step psum) and the per-epoch
        # time vector (process_allgather in balance/timing.py).
        self.n_proc = jax.process_count()
        self.proc_id = jax.process_index()
        if cfg.world_size % self.n_proc != 0:
            raise ValueError(
                f"world_size {cfg.world_size} must divide evenly across "
                f"{self.n_proc} processes"
            )
        self.ws_local = cfg.world_size // self.n_proc
        self.rank_lo = self.proc_id * self.ws_local

        # flight recorder (ISSUE 15): stream the tracer's events into a
        # crash-durable per-process spool so a SIGKILL'd or wedged process
        # leaves its timeline behind (at most the last flush interval is
        # lost). Attached HERE — immediately after the process identity is
        # known and before any instrumented init work (hier bandwidth
        # probe, AOT warm) — so even a process that dies during bring-up
        # spools its evidence. File name carries the logical ident AND the
        # pid: a respawned joiner shares the ident with its dead
        # predecessor but must never interleave frames into its file.
        self._spool_writer = None
        if cfg.trace != "off" and cfg.trace_spool:
            from dynamic_load_balance_distributeddnn_tpu.obs.spool import (
                SpoolWriter,
            )

            ident0 = int(os.environ.get("DBS_MH_IDENT", self.proc_id))
            spool_path = os.path.join(
                cfg.trace_spool, f"proc{ident0}.{os.getpid()}.spool"
            )
            self._spool_writer = SpoolWriter(
                spool_path,
                ident=ident0,
                flush_interval_s=cfg.trace_spool_flush_s,
                fsync=cfg.trace_spool_fsync,
            )
            self._trace.attach_spool(self._spool_writer)
            self.logger.info(
                f"flight recorder: trace spooling to {spool_path} "
                f"(flush every {cfg.trace_spool_flush_s}s"
                + (", fsync" if cfg.trace_spool_fsync else "")
                + ")"
            )
            # drain on GC even when run() never completes — without
            # capturing self (weakref.finalize must not pin the trainer)
            import weakref

            weakref.finalize(self, self._spool_writer.close)

        local_devices = sorted(jax.local_devices(), key=lambda d: d.id)
        ids_global = cfg.worker_device_ids(len(local_devices))
        ids_local = ids_global[self.rank_lo : self.rank_lo + self.ws_local]
        used = sorted(set(ids_local))
        if self.n_proc > 1:
            # Every process must use the same local device ordinals, or the
            # global meshes (built per-process below) would disagree and the
            # collectives would hang. Validate instead of assuming.
            for p in range(self.n_proc):
                slice_p = ids_global[p * self.ws_local : (p + 1) * self.ws_local]
                if sorted(set(slice_p)) != used:
                    raise ValueError(
                        "multi-host topology must be symmetric: every process "
                        f"must map its workers onto the same local device "
                        f"ordinals (process 0 uses {used}, process {p} would "
                        f"use {sorted(set(slice_p))}); adjust the device map"
                    )
        self.topology = WorkerTopology.build(
            self.ws_local,
            [local_devices[i] for i in used],
            [used.index(i) for i in ids_local],
        )
        if self.n_proc == 1:
            mesh_devices = list(self.topology.devices)
        else:
            # Symmetric hosts: every process contributes the same local device
            # ordinals, ordered by process index then device id.
            by_proc: Dict[int, list] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            mesh_devices = []
            for p in sorted(by_proc):
                proc_devs = sorted(by_proc[p], key=lambda d: d.id)
                mesh_devices.extend(proc_devs[i] for i in used)
        # Tree gradient combine (ISSUE 12, N-level since ISSUE 17): resolve
        # --grad_comm hier into an N-level topology mesh when the device
        # list factors into a TopologyTree — declared (--hier_levels),
        # derived from the real process topology / synthetic --hier_hosts
        # split, or probe-learned. self.grad_comm is the RUNTIME choice —
        # "flat" whenever no factorization exists or the bandwidth probe
        # says the fabric gains nothing — and everything downstream
        # (StepLibrary axes, combine dispatch, AOT keys, bytes-on-wire
        # accounting) keys off it, never off cfg.grad_comm.
        self.grad_comm = "flat"
        self._hier_hosts = 0
        self._topo_tree = None
        self._grad_comm_wires: tuple = ()
        self._link_bw: Optional[Dict] = None
        # bandwidth-probe verdict memo: a reshard's tree re-derivation must
        # not re-enable a structure the probe measured as a loss here
        self._probe_gated_flat = False
        if cfg.grad_comm == "hier":
            tree, learn = self._resolve_topology_tree(mesh_devices)
            if tree is None:
                self.logger.warning(
                    "grad_comm=hier: no topology-tree factorization of "
                    f"{len(mesh_devices)} devices "
                    f"(hier_levels={cfg.hier_levels!r}, "
                    f"hier_hosts={cfg.hier_hosts}, processes={self.n_proc})"
                    " — falling back to the flat combine"
                )
            else:
                self.grad_comm = "hier"
                self._topo_tree = tree
                self._hier_hosts = tree.sizes[0]
        if self.grad_comm == "hier":
            from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
                probe_link_bandwidth,
                tree_mesh,
            )

            self.mesh = tree_mesh(
                mesh_devices, self._topo_tree.names, self._topo_tree.sizes
            )
            # The bandwidth probe always runs on a SINGLE-PROCESS tree
            # mesh — its per-phase spans and per-level bytes/s are the
            # run's comm observability, the input of the learned-tree
            # merge and the per-hop codec choice — but it only GATES
            # (falls back to flat) when the operator opted in: forced hier
            # on a deliberately synthetic split (tests, the bench) must
            # stay hier. Multi-host runs skip it entirely: the probe
            # device_puts host-local arrays onto the global mesh
            # (non-addressable from any one process), and a per-process
            # wall-clock verdict could DIVERGE across hosts — half the
            # fleet on a tree mesh, half flat, deadlocked at the first
            # collective. Real pods trust --grad_comm until the probe
            # learns a replicated decision channel (ROADMAP).
            if self.n_proc == 1:
                self._link_bw = probe_link_bandwidth(
                    self.mesh, gate_ratio=cfg.dcn_probe_gate
                )
                heartbeat()
                if learn:
                    self._learn_tree_from_probe(mesh_devices)
            elif cfg.dcn_bandwidth_probe or learn:
                self.logger.warning(
                    "the bandwidth probe is single-process-only today — "
                    "keeping grad_comm=hier as configured"
                )
            if (
                cfg.dcn_bandwidth_probe
                and self.grad_comm == "hier"
                and self._link_bw is not None
                and not self._link_bw["hier_wins"]
            ):
                self.logger.warning(
                    "grad_comm=hier: bandwidth probe measured the tree "
                    "structure at "
                    f"{self._link_bw['hier_wall_s']:.4f}s vs "
                    f"{self._link_bw['flat_wall_s']:.4f}s for one flat "
                    f"psum (ratio {self._link_bw['wall_ratio']:.3f}, gate "
                    f"{cfg.dcn_probe_gate}) — falling back to the flat "
                    "combine"
                )
                self.grad_comm = "flat"
                self._hier_hosts = 0
                self._topo_tree = None
                self._probe_gated_flat = True
                self.mesh = data_mesh(mesh_devices)
        if self.grad_comm != "hier" and getattr(self, "mesh", None) is None:
            self.mesh = data_mesh(mesh_devices)
        self.n_dev = len(mesh_devices)
        self._grad_comm_wires = self._resolve_wires()
        # AOT-key / plan-layout signature of the combine structure: a new
        # axis factorization or wire format is a new compiled-program
        # universe, so it participates in every registry key the combine
        # and fused executables are filed under. Since PR 13 the UPDATE
        # SPEC (sharded vs replicated optimizer) is part of the same
        # signature — a zero-1 program and a replicated one lower from
        # different state specs and must never resolve to each other.
        self._comm_sig = self._compute_comm_sig()

        self._setup_data(bundle)
        self._setup_model()

        # Async AOT compile service (runtime/compiler.py): warm-start and
        # speculative compiles run as jit(...).lower(abstract).compile() jobs
        # on a thread pool — no dummy execution, no device_put traffic — and
        # the elastic hot loop dispatches the compiled executables directly
        # (the lazy jit wrappers stay as fallback). aot_warm=False keeps the
        # legacy execute-to-compile warm loop as the A/B reference.
        self._aot: Optional[AOTCompileService] = None
        self._build_aot_service()
        self._aot_view_specs: Dict[int, object] = {}
        self._aot_dummy_template: list = []
        # world generation: bumped on every elastic re-shard and mixed into
        # every AOT registry key — device indices and mesh programs are only
        # meaningful within one fleet generation, and a stale executable
        # resolving across a re-shard dispatches onto devices that left the
        # fleet (sharding-mismatch crash at best, wrong-device work at worst)
        self._aot_gen = 0
        self._aot_failed_logged: set = set()
        self._aot_warm_t0: Optional[float] = None
        self._aot_compiled_last = 0.0

        if injector is not None:
            self.injector = injector
        elif cfg.straggler and cfg.fault_schedule != "none":
            # time-varying profile (ISSUE 11): the factors follow a sin/ramp
            # schedule within epochs — the scenario window-cadence
            # rebalancing exists for (epoch_faults still exposes the
            # epoch-MEAN view, so epoch-cadence runs stay well-defined)
            self.injector = ScheduledStragglerInjector(
                cfg.straggler_factors(),
                mode=cfg.fault_mode,
                schedule=cfg.fault_schedule,
                period=cfg.fault_period,
                seed=cfg.seed,
            )
        elif cfg.straggler:
            self.injector = StaticStragglerInjector(
                cfg.straggler_factors(), mode=cfg.fault_mode
            )
        elif cfg.fault_tolerance:
            self.injector = LuckyFaultInjector(
                cfg.world_size,
                cfg.fault_tolerance_chance,
                mode=cfg.fault_mode,
                seed=cfg.seed,
                logger=self.logger,
            )
        else:
            self.injector = NullInjector(cfg.world_size)
        self._needs_iter_cost = cfg.fault_mode == "compute" and not isinstance(
            self.injector, NullInjector
        )

        # Elastic world size (ISSUE 6): the ACTIVE fleet. ``world_size`` is
        # the engine's RUNTIME world size — equal to cfg.world_size until a
        # confirmed worker loss shrinks it (readmission grows it back);
        # every runtime surface (solver vectors, plan build, capacity caps,
        # probe loops, rng splits) derives from it. ``active_ranks`` maps
        # compact runtime ranks -> ORIGINAL config ranks: injectors and
        # health verdicts speak original ranks, plans/topology/shares are
        # compact over the survivors.
        self.world_size = cfg.world_size
        self.active_ranks = list(range(cfg.world_size))
        self.health = WorkerHealth(
            cfg.world_size,
            detect_misses=cfg.elastic_detect_misses,
            latency_factor=cfg.elastic_latency_factor,
            logger=self.logger,
        )
        self._recoveries = 0
        self._elastic_events: list = []
        self._epoch_snap: Optional[dict] = None
        self._detect_t0: Optional[float] = None
        # epoch-time each worker's loss was CONFIRMED at: recovery re-runs
        # the epoch, so liveness rounds re-visit schedule times BEFORE the
        # loss — a "not down" verdict there is the past, not a recovery
        self._lost_t: Dict[int, float] = {}
        self._hb_beacon = None
        self._hb_beacon_path: Optional[str] = None
        # Multi-host elasticity (ISSUE 14): the rendezvous state machine
        # (armed with the peer beacon when DBS_PEER_HB_DIR is set) and the
        # fleet's ORIGINAL process identities. ``proc_id``/``n_proc`` are
        # the LIVE world's compact values and change across a re-rendezvous;
        # ``_orig_proc_id``/``_proc_roster``/``_n_proc0`` speak the original
        # ident space the heartbeat files, worker-rank ownership and the
        # rendezvous protocol are keyed by. A respawned joiner carries its
        # original ident in DBS_MH_IDENT (its live process index is whatever
        # rank the grow rendezvous assigned).
        self._rdzv = None
        self._n_proc0 = self.n_proc
        self._orig_proc_id = int(os.environ.get("DBS_MH_IDENT", self.proc_id))
        self._proc_roster = list(range(self.n_proc))
        self._peer_scan_cache = None
        if cfg.elastic == "on" and self.n_proc > 1:
            self._arm_peer_heartbeats()

        # XLA-recompile sentinel (analysis/guards.py): drained every epoch.
        # A compile on a plan layout seen before means a shape fell off the
        # bucket ladder or a jit wrapper was rebuilt inside a timed epoch —
        # invisible in the wall on a fast chip, poison for the DBS signal.
        # (First-visit compiles of a fresh layout are expected lazy work when
        # warm_start is off.)
        self._compile_tracker = CompileTracker()
        self._seen_plan_layouts: set = set()

        self.recorder = MetricsRecorder()
        self.recorder.stamp_data_source(
            self.bundle if self.bundle is not None else getattr(self, "corpus", None)
        )
        # Wall-definition provenance (ADVICE r4): since round 4, epoch walls
        # (and examples_per_s/MFU derived from them) EXCLUDE standalone probe
        # steps on every path; pre-round-4 artifacts include them. Stamped so
        # cross-round comparisons can detect the definition boundary instead
        # of silently mixing the two.
        self.recorder.meta["wall_excludes_probes"] = True
        # combine-structure provenance: which collective this run's walls
        # were measured under (and what the bandwidth probe saw, if it ran)
        self.recorder.meta["grad_comm"] = self.grad_comm
        if self.grad_comm == "hier":
            self.recorder.meta["grad_comm_wire"] = cfg.grad_comm_wire
            self.recorder.meta["grad_comm_hosts"] = self._hier_hosts
            self.recorder.meta["grad_comm_levels"] = [
                [n, int(s)] for n, s in self._topo_tree.levels
            ]
            self.recorder.meta["grad_comm_wires"] = list(self._grad_comm_wires)
        if self._link_bw is not None:
            self.recorder.meta["link_bandwidth"] = {
                k: v for k, v in self._link_bw.items()
            }
        # induced-straggler provenance: lets offline tooling compute the
        # ideal equilibrium partition (share_i ∝ 1/f_i) and report the
        # balancer-quality convergence metric (BASELINE.md §protocol)
        if cfg.straggler:
            self.recorder.meta["straggler_factors"] = [
                float(f) for f in cfg.straggler_factors()
            ]
            self.recorder.meta["fault_mode"] = cfg.fault_mode
        self.shares = initial_partition(cfg.world_size)
        self.node_times = np.ones(cfg.world_size, dtype=np.float64)
        self.per_example_cost = np.full(cfg.world_size, np.nan)
        # In-step cost of one synthetic-load iteration: seeded from the
        # standalone calibration, then closed-loop-corrected from realized
        # probe deltas (per-process — hosts may genuinely differ).
        self._iter_cost_s: Optional[float] = None
        self._iter_cost_calibrated = False
        self.timekeeper = TimeKeeper(cfg.world_size)
        self.total_wallclock = 0.0
        self.total_probe_s = 0.0  # probe/instrumentation wall, kept OUT of
        #                           epoch walls (see run_epoch) but reported
        # Fused-path sync-time meter: seconds of collective cost per step,
        # measured once per run (shapes are constant on the fused path).
        self._fused_sync_per_step: Optional[float] = None
        # FLOP accounting (obs/flops.py): per-padded-example step FLOPs from
        # XLA's cost model, measured once per run; per-epoch totals derive
        # from each epoch's plan. None when the backend exposes no cost model.
        self._flops_per_padded_example: Optional[float] = None
        self._epoch_flops: Optional[float] = None
        self._warmed = False
        self._probes_ran = False  # replicated across processes by construction
        # Adaptive probe scheduler (config.probe_mode): once the per-example
        # cost model is anchored by real probes, epochs skip the probe steps
        # entirely and the solver is fed MODELED times; these fields track the
        # re-probe schedule and the wall-deviation trigger.
        self._probe_this_epoch = True
        self._next_probe_epoch = 0
        self._probe_sig: Optional[tuple] = None
        self._probe_episode: Optional[tuple] = None
        self._probe_wall_ref: Optional[float] = None
        self._slow_streak = 0
        self._sync_per_step = 0.0  # last probed elastic sync cost, reused on skips
        # Device-resident data cache (config.device_cache): train arrays live
        # in HBM and epochs are fed by index (on-device gather), so the
        # per-epoch reshard uploads [steps, batch] int32 instead of the
        # dataset. Lazily materialized per path (replicated for the fused
        # scan; one copy per used device for the elastic executables).
        self._use_device_cache = self._decide_device_cache()
        self._cache_repl = None
        self._cache_dev: Dict[int, tuple] = {}
        # Elastic-superstep bookkeeping: host-overhead meter (dispatch vs put
        # walls, reset per epoch) and the (shape-tuple, window) keys the scan
        # mode has dispatched — the compile-once sentinel the CompileTracker
        # warning is cross-checked against (run_epoch).
        self._host_meter = HostOverheadMeter()
        self._superstep_keys: set = set()
        # Solver-trajectory predictor (balance/solver.py): one-step-ahead
        # share-vector prediction feeding scan-mode shape-TUPLE speculation
        # (config.speculate_scan) — tuples have no finite ±bucket adjacency,
        # but the NEXT tuple is a deterministic function of the next share
        # vector, which the solver's smooth trajectory makes predictable.
        self._share_predictor = ShareTrajectoryPredictor()
        # Online window-cadence rebalance controller (ISSUE 11,
        # balance/controller.py): lazily built per fleet generation by
        # _window_controller() when cfg.rebalance == "window"; its EMA rate
        # track and regret ledger persist across epochs, and speculation is
        # re-aimed at ITS candidate plans (the switched-to executables are
        # always AOT-warm — a switch never pays a foreground compile).
        self._rebalance_ctl: Optional[OnlineRebalanceController] = None
        self._rebalance_events: list = []
        self._switches_last = 0
        self._window_rebalance_logged = False
        self._fault_ctx: Optional[FaultContext] = None
        self._clean_compute_s: Optional[np.ndarray] = None
        self._clean_examples: Optional[np.ndarray] = None
        # graftscope (obs/trace.py + obs/registry.py): the process-wide span
        # tracer — configured here from the run config, shared by every
        # instrumented module (pipeline, AOT service, solver, watchdog) —
        # and the unified registry over this engine's observability
        # surfaces. trace="off" keeps every span call a single attribute
        # check (no buffer, no jax — sentinel-silent under the compile
        # guards); the trace saves at end of run (run()).
        # The engine OWNS the process-wide tracer config: configured
        # unconditionally (at the TOP of __init__, before the mesh/probe
        # block), so a trace="off" run can never inherit an earlier traced
        # run's enabled state (and its wall overhead + surprise trace file)
        # from the same process — bench arms, test suites and notebook
        # drivers all build engines back to back.
        self.obs = MetricsRegistry(recorder=self.recorder, tracer=self._trace)
        self.obs.attach(
            host_meter=self._host_meter,
            compile_tracker=self._compile_tracker,
            health=self.health,
        )
        if self._aot is not None:
            self.obs.attach(aot_service=self._aot)
        if cfg.packed == "on":
            # fail fast at init: the epoch dispatch prefers the fused paths,
            # so a forced-but-infeasible packed config would otherwise be
            # silently overridden (or only rejected mid-run)
            self._can_use_packed(None)
        if self._use_device_cache:
            mb = (self.bundle.train_x.nbytes + self.bundle.train_y.nbytes) / 1e6
            self.logger.info(
                f"device cache: train arrays HBM-resident ({mb:.1f} MB), "
                "epochs fed by index"
            )

    def _build_aot_service(self) -> None:
        """(Re)construct the AOT compile service. Re-run after a multi-host
        re-rendezvous: the old pool's registry and any mid-flight lowerings
        reference the RETIRED backend, so the recovery path closes the old
        service and builds a fresh one against the new world."""
        cfg = self.cfg
        self._aot = None
        if not cfg.aot_warm:
            return
        self._aot = AOTCompileService(
            workers=cfg.aot_pool,
            logger=self.logger,
            tick=heartbeat,
            backend=cfg.aot_backend,
            process_workers=cfg.aot_workers,
            # workers write their own graftscope trace files next to the
            # run trace; save_trace stitches them in (pid-tagged tracks)
            trace_dir=cfg.trace_dir if cfg.trace != "off" else None,
        )
        if getattr(self, "steps", None) is not None:
            self.steps.aot_service = self._aot
        # tie the pool's lifetime to the trainer: processes that build
        # many engines (the test tier, bench retry/insurance loops) must
        # not accumulate idle non-daemon compile threads
        import weakref

        weakref.finalize(self, self._aot.close, False)

    def _decide_device_cache(self) -> bool:
        cfg = self.cfg
        if cfg.device_cache == "off":
            return False
        tx = getattr(self.bundle, "train_x", None) if self.bundle is not None else None
        ty = getattr(self.bundle, "train_y", None) if self.bundle is not None else None
        if tx is None or ty is None:
            # tokens path (LM folds its stream into windows host-side)
            if cfg.device_cache == "on":
                self.logger.warning("device_cache=on ignored: no cacheable train arrays")
            return False
        if cfg.device_cache == "on":
            return True
        return tx.nbytes + ty.nbytes <= cfg.device_cache_mb * 1_000_000

    def _device_cache_replicated(self):
        if self._cache_repl is None:
            arrays = (
                self.bundle.train_x,
                np.asarray(self.bundle.train_y, dtype=np.int32),
            )
            sh = replicated_sharding(self.mesh)
            if self.n_proc == 1:
                self._cache_repl = tuple(jax.device_put(a, sh) for a in arrays)
            else:
                # every process holds the identical bundle (same files/seed),
                # so its full array IS the addressable portion of the
                # replicated global array
                self._cache_repl = tuple(
                    jax.make_array_from_process_local_data(sh, a) for a in arrays
                )
        return self._cache_repl

    def _device_cache_for(self, d: int):
        if d not in self._cache_dev:
            dev = self.topology.devices[d]
            if self._cache_repl is not None:
                # the replicated copy already has a buffer on this device —
                # reference it instead of uploading a second copy (keeps HBM
                # residency at one dataset per device in fused-DBS mode,
                # where both the scan and the probes need the cache)
                self._cache_dev[d] = tuple(
                    next(
                        s.data
                        for s in arr.addressable_shards
                        if s.device == dev
                    )
                    for arr in self._cache_repl
                )
            else:
                self._cache_dev[d] = (
                    jax.device_put(self.bundle.train_x, dev),
                    jax.device_put(
                        np.asarray(self.bundle.train_y, dtype=np.int32), dev
                    ),
                )
        return self._cache_dev[d]

    # -------------------------------------------------------------- set-up
    # Subclass hooks: the LM trainer (train/lm_engine.py) overrides these.

    def _setup_data(self, bundle: Optional[DatasetBundle]) -> None:
        cfg = self.cfg
        if bundle is None:
            n_cap = cfg.n_train or (2048 if cfg.debug else None)
            n_test = 2048 if cfg.debug else None
            bundle = load_dataset(cfg.dataset, cfg.data_dir, n_train=n_cap, n_test=n_test)
        self.bundle = bundle
        self.n_train = len(bundle.train_x)
        if bundle.synthetic:
            self.logger.info(
                f"dataset {cfg.dataset}: files not found, using the synthetic stand-in"
            )

    def _setup_model(self) -> None:
        cfg = self.cfg
        from dynamic_load_balance_distributeddnn_tpu.ops.pallas import set_use_pallas

        set_use_pallas(cfg.use_pallas)  # routes GroupNorm at module trace time
        self.spec = build_model(cfg.model, num_classes=self.bundle.num_classes)
        self.tx = make_optimizer(cfg.learning_rate, cfg.momentum)
        h, w, c = self.bundle.train_x.shape[1:]
        example = jnp.zeros((1, h, w, c), jnp.float32)
        self.state = create_state(
            self.spec.module,
            example,
            self.tx,
            seed=cfg.seed,
            sharding=replicated_sharding(self.mesh),
        )
        self._zero1_padded = 0
        if cfg.shard_update:
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                shard_optimizer_state,
                zero1_padded_size,
            )

            self._zero1_padded = zero1_padded_size(self.state.params, self.n_dev)
            self.state = shard_optimizer_state(self.state, self.mesh, self.tx)
        if self.grad_comm == "hier":
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                attach_comm_residual,
            )

            # zero error-feedback residual, [n_dev, chunk] one row per
            # device over the two-level mesh; checkpoints restore into it.
            # With shard_update the residual chunk follows the ZERO-1
            # padding (a multiple of the TOTAL device count, so the
            # post-hop chunk re-splits evenly across hosts).
            self.state = attach_comm_residual(
                self.state, self.mesh,
                pad_multiple=self.n_dev if cfg.shard_update else 0,
            )
        self._build_steps()

    def _build_steps(self) -> None:
        """(Re)build the StepLibrary against the CURRENT mesh. Split out of
        ``_setup_model`` because the elastic recovery path rebuilds it after
        a fleet change: every compiled executable closes over the mesh, so
        a survivor mesh means a fresh library (old executables are garbage
        the moment their devices leave the fleet)."""
        cfg = self.cfg
        augment = cfg.dataset in ("cifar10", "cifar100")
        self.steps = StepLibrary(
            self.spec,
            self.mesh,
            self.tx,
            mean=self.bundle.mean,
            std=self.bundle.std,
            augment=augment,
            grad_clip=cfg.grad_clip,
            compute_dtype=jnp.bfloat16 if cfg.precision == "bfloat16" else None,
            use_pallas=cfg.use_pallas,
            shard_update=cfg.shard_update,
            grad_accum=cfg.grad_accum,
            compress_grads=cfg.compress_grads,
            remat=cfg.remat,
            grad_comm=self.grad_comm,
            grad_comm_wire=cfg.grad_comm_wire,
            grad_comm_wires=self._grad_comm_wires or None,
            zero1_padded=getattr(self, "_zero1_padded", 0),
        )
        if getattr(self, "_aot", None) is not None:
            self.steps.aot_service = self._aot

    def _build_plan(self, epoch: int, batch_sizes: np.ndarray):
        return build_epoch_plan(
            self.n_train,
            self.shares,
            batch_sizes,
            self.cfg.batch_size,
            epoch,
            seed=self.cfg.seed,
            bucket=self.cfg.bucket,
        )

    # ------------------------------------------------------------------ run

    def _dummy_batch(self, b: int):
        """Zero-filled (x, y, w) for one padded batch of ``b`` — the warm-up
        compile driver. Vision layout; the LM trainer overrides."""
        h, w_, c = self.bundle.train_x.shape[1:]
        return (
            np.zeros((b, h, w_, c), dtype=self.bundle.train_x.dtype),
            np.zeros((b,), dtype=np.int32),
            np.full((b,), 1.0 / max(b * self.world_size, 1), dtype=np.float32),
        )

    # ------------------------------------------------- AOT compile service
    # (runtime/compiler.py). The compile universe — per-step ladder rungs,
    # windowed twins, superstep scan keys — is described as abstract
    # ShapeDtypeStruct args (committed single-device shardings; param/state
    # trees ride in as live arrays so weak types and committed-ness are
    # exact) and compiled concurrently in the background. Dispatch resolves
    # the compiled executables from the service by (kind, batch, window,
    # device) key and falls back to the lazy jit wrappers on a miss.

    def _warm_ladder(self) -> "tuple[list, int]":
        """(ladder rungs, capacity width): every padded batch shape the
        balancer can produce — bucket multiples up to ``_cap_b``. Single
        source of truth for both warm paths (AOT and legacy)."""
        max_b = self._cap_b
        return list(range(self.cfg.bucket, max_b + 1, self.cfg.bucket)), max_b

    def _dummy_arg_shapes(self, b: int) -> list:
        """Per-(x, y, w) ``(shape, dtype)`` at batch ``b`` WITHOUT
        materializing batches: ``_dummy_batch``'s leading dim is the batch
        by contract (vision and LM alike), so one b=1 template — built once
        — scales to every rung. Spec building on the real TPU ladder would
        otherwise allocate and discard tens of MB of zeros per sweep."""
        if not self._aot_dummy_template:
            self._aot_dummy_template = [
                (tuple(t.shape[1:]), t.dtype) for t in self._dummy_batch(1)
            ]
        return [((b,) + s, dt) for s, dt in self._aot_dummy_template]

    def _aot_sds(self, shape, dtype, dev):
        from jax.sharding import SingleDeviceSharding

        return jax.ShapeDtypeStruct(
            tuple(int(s) for s in shape), dtype, sharding=SingleDeviceSharding(dev)
        )

    def _aot_step_key(self, kind: str, b: int, d: int, win: Optional[int]) -> tuple:
        return (kind, int(b), int(win or 0), int(d), self._aot_gen)

    @property
    def _batch_axes(self):
        """PartitionSpec entry splitting a batch dim over the whole mesh —
        the lone axis name (flat) or the (host, device) tuple (hier)."""
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
            mesh_batch_axes,
        )

        return mesh_batch_axes(self.mesh)

    def _combine_names(self) -> "tuple[str, str]":
        """(update, probe) combine executable names for the active combine
        structure — the hier twins ride the two-level mesh (routing into
        the sharded update internally when shard_update is on), the zero-1
        twins the flat mesh with a sharded update, the flat pair the single
        psum plus replicated update."""
        if self.grad_comm == "hier":
            return ("combine_update_hier", "combine_probe_hier")
        if self.cfg.shard_update:
            return ("combine_update_zero1", "combine_probe_zero1")
        return ("combine_update", "combine_probe")

    def _aot_view_spec(self, d: int):
        """Abstract spec of device d's params view: shapes/dtypes/shardings
        never change across steps, so one spec serves the whole run (and
        holds no reference to any live param buffers)."""
        if d not in self._aot_view_specs:
            views = shard_views(self.state.params, self.topology.devices)
            self._aot_view_specs[d] = jax.tree_util.tree_map(
                lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=t.sharding),
                views[d],
            )
        return self._aot_view_specs[d]

    def _comm_bytes_per_step(self) -> "tuple[float, float]":
        """(ICI bytes, DCN bytes) of ONE gradient combine — the logical
        per-device payload each link class carries, the series the
        grad_comm bench reports per arm.

        flat: the full f32 tree rides every link it spans — ICI always, DCN
        only when the mesh actually crosses hosts (real processes; a
        single-process synthetic split has no DCN and records 0).
        hier: the innermost reduce-scatter + all-gather keep 2x the tree
        on ICI at full precision; each middle hop adds its shrinking
        vector on that hop's wire (up) plus f32 back (down) to the ICI
        class; only the top-hop chunk crosses DCN in the outermost wire's
        sum dtype (parallel/wire.py wire_payload_bytes). On a two-level
        tree this reduces exactly to the PR-12 numbers."""
        from dynamic_load_balance_distributeddnn_tpu.parallel.wire import (
            tree_hop_widths,
            wire_payload_bytes,
        )

        if not hasattr(self, "_param_elems"):
            self._param_elems = int(
                sum(p.size for p in jax.tree_util.tree_leaves(self.state.params))
            )
        elems = self._param_elems
        if self.grad_comm == "hier":
            sizes = self._topo_tree.sizes
            wires = self._grad_comm_wires
            # pad_multiple=0: the LOGICAL payload accounting (identical to
            # the PR-12 numbers); the zero-1 layout pads slightly wider but
            # the padding is zeros, not signal
            widths = tree_hop_widths(elems, sizes, pad_multiple=0)
            dcn = widths[0] * wire_payload_bytes(wires[0], sizes[0])
            # innermost hop: full-tree f32 reduce-scatter + all-gather
            ici = 2.0 * elems * 4
            # middle hops 1..k-1: the hop's vector on its wire up, f32 down
            for i in range(1, len(sizes) - 1):
                ici += widths[i] * (
                    wire_payload_bytes(wires[i], sizes[i]) + 4
                )
            return float(ici), float(dcn)
        # flat: compress_grads rides its own int16 wire (half the f32 bytes)
        per_elem = 2 if self.cfg.compress_grads == "int8" else 4
        return (
            float(elems * per_elem),
            float(elems * per_elem if self.n_proc > 1 else 0),
        )

    def _modeled_comm_step_s(self) -> float:
        """Modeled wall of ONE gradient combine over the probe's measured
        per-level link rates (ISSUE 17): each hop's bytes (the same per-hop
        accounting as :meth:`_comm_bytes_per_step`) divided by that level's
        measured bytes/s, summed — hops serialize along the tree spine.
        Feeds the window controller's ``comm_step_s`` so the rebalance
        hysteresis sees the comm floor a compute rebalance cannot touch.
        0.0 whenever there is no resolved tree or no probe data (the
        compute-only model — never guess a wall from missing rates)."""
        if self.grad_comm != "hier" or self._topo_tree is None:
            return 0.0
        rates = (self._link_bw or {}).get("level_bytes_per_s")
        sizes = self._topo_tree.sizes
        wires = self._grad_comm_wires
        if not rates or len(rates) != len(sizes) or len(wires) != len(sizes):
            return 0.0
        r = [float(x) if x and float(x) > 0 else 0.0 for x in rates]
        if any(x <= 0.0 for x in r):
            return 0.0
        from dynamic_load_balance_distributeddnn_tpu.parallel.wire import (
            tree_hop_widths,
            wire_payload_bytes,
        )

        if not hasattr(self, "_param_elems"):
            self._param_elems = int(
                sum(p.size for p in jax.tree_util.tree_leaves(self.state.params))
            )
        elems = self._param_elems
        widths = tree_hop_widths(elems, sizes, pad_multiple=0)
        k = len(sizes) - 1
        total = 2.0 * elems * 4 / r[k]  # innermost f32 RS + AG
        for i in range(1, k):  # middle hops: wire up, f32 down
            total += widths[i] * (
                wire_payload_bytes(wires[i], sizes[i]) + 4
            ) / r[i]
        total += widths[0] * wire_payload_bytes(wires[0], sizes[0]) / r[0]
        return float(total)

    def _aot_resolve(self, kind: str, b: int, d: int, win: Optional[int], fallback):
        """Compiled executable for a dispatch site, or the lazy jit
        fallback. Non-blocking: an in-flight or failed job falls back."""
        if self._aot is None:
            return fallback
        return self._aot.get(self._aot_step_key(kind, b, d, win)) or fallback

    def _aot_submit_worker_steps(
        self, d: int, b: int, wins, want_acc: bool, want_plain: bool,
        speculative: bool = False,
    ) -> list:
        """Queue the worker-step executables for one (device, rung): the
        plain single-step pair (probes + step-mode dispatch) and the
        window-sliced pair per window length (window-mode dispatch). Returns
        the submitted/deduped keys. ``_dummy_batch`` output is used purely
        as a host-side shape/dtype template — nothing is transferred."""
        svc = self._aot
        if svc is None:
            return []
        use_cache = self._use_device_cache
        suffix = "_idx" if use_cache else ""
        kinds = []
        if want_plain:
            kinds.append(("worker_first" + suffix, None))
            if want_acc:
                kinds.append(("worker_acc" + suffix, None))
        for win in wins or ():
            kinds.append(("worker_first_win" + suffix, win))
            if want_acc:
                kinds.append(("worker_acc_win" + suffix, win))
        keys = [self._aot_step_key(kind, b, d, win) for kind, win in kinds]
        if all(svc.has(k) for k in keys):
            return keys  # steady state: skip all spec construction
        dev = self.topology.devices[d]
        sds = lambda shape, dt: self._aot_sds(shape, dt, dev)  # noqa: E731
        view = self._aot_view_spec(d)
        (xs_, xd), (ys_, yd), (ws_sh, wd) = self._dummy_arg_shapes(b)
        key_t = sds((2,), jnp.uint32)
        slow_t = sds((), jnp.int32)
        acc_t = jax.tree_util.tree_map(
            lambda p: self._aot_sds((1,) + tuple(p.shape), p.dtype, dev), view
        )
        cache = self._device_cache_for(d) if use_cache else ()
        targets = []
        if want_plain:
            if use_cache:
                data = cache + (sds((b,), jnp.int32), sds(ws_sh, wd))
            else:
                data = (sds(xs_, xd), sds(ys_, yd), sds(ws_sh, wd))
            targets.append(("worker_first" + suffix, (view,) + data + (key_t, slow_t), None))
            if want_acc:
                targets.append(
                    ("worker_acc" + suffix, (view, acc_t) + data + (key_t, slow_t), None)
                )
        for win in wins or ():
            kw_t = sds((win, 2), jnp.uint32)
            s_t = sds((), jnp.int32)
            if use_cache:
                data = cache + (sds((win, b), jnp.int32), sds((win,) + ws_sh, wd))
            else:
                data = (
                    sds((win,) + xs_, xd),
                    sds((win,) + ys_, yd),
                    sds((win,) + ws_sh, wd),
                )
            targets.append(
                ("worker_first_win" + suffix, (view,) + data + (kw_t, s_t, slow_t), win)
            )
            if want_acc:
                targets.append(
                    ("worker_acc_win" + suffix, (view, acc_t) + data + (kw_t, s_t, slow_t), win)
                )
        lows = self.steps.aot_lowerables()
        keys = []
        for kind, args, win in targets:
            k = self._aot_step_key(kind, b, d, win)
            if not svc.has(k):
                svc.submit(k, lows[kind], args, speculative=speculative)
            keys.append(k)
        return keys

    def _aot_submit_superstep(self, padded, win: int, speculative: bool = False) -> list:
        """Queue one scan-mode superstep (shape-tuple, window) key. The
        TrainState rides into lowering as the live tree (exact leaf
        shardings/weak types — a spec cannot express committed-ness), which
        is also why no zeros dummy state is needed anymore."""
        svc = self._aot
        if svc is None:
            return []
        topo = self.topology
        d0 = topo.used_device_indices[0]
        dev = topo.devices[d0]
        use_cache = self._use_device_cache
        name = "group_superstep_idx" if use_cache else "group_superstep"
        shape_key = topo.group_shape_key(list(padded), win)
        # register the key for the compile-once sentinel cross-check exactly
        # like the legacy warm did
        self._superstep_keys.add(shape_key)
        k = (name, shape_key, d0, self._aot_gen)
        if svc.has(k):
            return [k]
        sds = lambda shape, dt: self._aot_sds(shape, dt, dev)  # noqa: E731
        cols = []
        for b in padded:
            (xs_, xd), (ys_, yd), (ws_sh, wd) = self._dummy_arg_shapes(b)
            kw_t = sds((win, 2), jnp.uint32)
            ww_t = sds((win,) + ws_sh, wd)
            if use_cache:
                cols.append((sds((win, b), jnp.int32), ww_t, kw_t))
            else:
                cols.append(
                    (sds((win,) + xs_, xd), sds((win,) + ys_, yd), ww_t, kw_t)
                )
        tup = tuple(zip(*cols))
        slows = tuple(sds((), jnp.int32) for _ in padded)
        if use_cache:
            args = (self.state,) + self._device_cache_for(d0) + tup + (slows,)
        else:
            args = (self.state,) + tup + (slows,)
        svc.submit(k, self.steps.aot_lowerables()[name], args, speculative=speculative)
        return [k]

    def _aot_fused_key(self, n_win: int, width: int, slow_len: int) -> tuple:
        name = "fused_epoch_idx" if self._use_device_cache else "fused_epoch"
        return (
            (name, int(n_win), int(width), int(slow_len), self._aot_gen)
            + self._comm_sig
        )

    def _aot_submit_fused(self, n_win: int, width: int, slow_len: int) -> list:
        """Queue one fused whole-epoch-scan window executable
        (``fused_epoch``/``fused_epoch_idx``) as an AOT job: the MESH-sharded
        program lowers from ``ShapeDtypeStruct`` specs carrying explicit
        ``NamedSharding``s (batch axis split over the data mesh, replicated
        scalars), with the live TrainState riding in for exact leaf
        shardings/committed-ness — the multi-device lowering the service was
        previously gated away from (single-host probes only). Single-process
        only: multi-host runs keep the lazy path."""
        svc = self._aot
        if svc is None or self.n_proc > 1:
            return []
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
            batch_sharding,
        )

        k = self._aot_fused_key(n_win, width, slow_len)
        if svc.has(k):
            return [k]
        mesh = self.mesh
        use_cache = self._use_device_cache

        def sds(shape, dt, sh):
            return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dt, sharding=sh)

        bx = self._batch_axes

        def win_spec(shape, dt):
            full = (n_win, width) + tuple(shape)
            return sds(full, dt, batch_sharding(mesh, len(full), axis=bx, axis_dim=1))

        (xs_, xd), (ys_, yd), (ws_sh, wd) = [
            (s[1:], dt) for s, dt in self._dummy_arg_shapes(1)
        ]
        w_t = win_spec(ws_sh, wd)
        slow_t = sds((slow_len,), jnp.int32, batch_sharding(mesh, 1, axis=bx))
        seed_t = sds((), jnp.int32, replicated_sharding(mesh))
        if use_cache:
            cache_x, cache_y = self._device_cache_replicated()
            args = (
                self.state, cache_x, cache_y,
                win_spec((), jnp.int32), w_t, slow_t, seed_t,
            )
        else:
            args = (self.state, win_spec(xs_, xd), win_spec(ys_, yd), w_t,
                    slow_t, seed_t)
        svc.submit(k, self.steps.aot_lowerables()[k[0]], args)
        return [k]

    def _resolve_fused_epoch(self, n_win: int, width: int, slow_len: int, args):
        """Compiled fused-epoch executable for one window geometry: the
        service registry if present, a blocking inline ``compile_now`` on a
        cold key (same wall position as the lazy compile, but the executable
        registers for reuse and the compile attributes as deliberate AOT
        work, not a sentinel-visible foreground recompile), the lazy jit
        wrapper on failure or multi-host."""
        name = "fused_epoch_idx" if self._use_device_cache else "fused_epoch"
        lazy = self.steps.aot_lowerables()[name]
        if self._aot is None or self.n_proc > 1:
            return lazy
        k = self._aot_fused_key(n_win, width, slow_len)
        fn = self._aot.get(k)
        if fn is not None:
            return fn
        try:
            return self._aot.compile_now(k, lazy, args)
        except Exception as e:
            if k not in self._aot_failed_logged:
                self._aot_failed_logged.add(k)
                self.logger.warning(
                    f"AOT fused compile failed for {k}: {e!r} — using lazy jit"
                )
            return lazy

    def _aot_submit_combine(self) -> list:
        """Queue the mesh-wide combine twins (``combine_update`` +
        ``combine_probe``): their stacked-grads input is the params tree with
        a leading [n_dev] axis sharded over the data mesh
        (steps.stack_partials), a shape that never changes across the run —
        one key each. Every elastic epoch dispatches combine_update per step
        and every probe runs combine_probe, so these were the last
        steady-state executables compiling lazily on the multi-device path."""
        svc = self._aot
        if svc is None or self.n_proc > 1:
            return []
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P(self._batch_axes))
        stacked_t = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(
                (self.n_dev,) + tuple(p.shape), p.dtype, sharding=sh
            ),
            self.state.params,
        )
        keys = []
        for name in self._combine_names():
            k = (name, self._aot_gen) + self._comm_sig
            if not svc.has(k):
                svc.submit(k, getattr(self.steps, name), (self.state, stacked_t))
            keys.append(k)
        return keys

    def _aot_resolve_combine(self, name: str, fallback):
        if self._aot is None:
            return fallback
        return self._aot.get((name, self._aot_gen) + self._comm_sig) or fallback

    def _submit_warm_aot(self) -> None:
        """AOT warm-start: submit the whole compile universe and return
        immediately — the pool compiles while the engine builds epoch 0's
        plan (rebalance, partitioning, fault setup, probe scheduling); the
        remaining jobs drain at run_epoch's pre-wall barrier so no TIMED
        region ever shares cores with the compiler."""
        cfg = self.cfg
        self._aot_warm_t0 = time.perf_counter()
        ladder, max_b = self._warm_ladder()
        warm_acc = any(len(g) > 1 for g in self.topology.groups.values())
        mode = self._elastic_mode()
        wins: tuple = ()
        plan0 = self._build_plan(0, integer_batch_split(self.shares, cfg.batch_size))
        if mode in ("window", "scan"):
            wins = tuple(
                sorted({s1 - s0 for s0, s1 in self._elastic_ranges(plan0.num_steps)})
            )
        n = n_fused = self._submit_warm_fused(plan0)
        if n_fused:
            # fused-path runs never dispatch the elastic ladder or the
            # combine twins (the combine lives inside the SPMD program) —
            # only the standalone probe rungs at the plan's TRUE shapes feed
            # the balancer signal (fused-DBS mode)
            if cfg.dynamic_batch_size or self._needs_iter_cost:
                for d in self.topology.used_device_indices:
                    for r in self.topology.groups[d]:
                        b = plan0.workers[self.rank_lo + r].padded_batch
                        n += len(
                            self._aot_submit_worker_steps(
                                d, b, (), want_acc=False, want_plain=True
                            )
                        )
            self.logger.info(
                f"AOT warm: submitted {n} compile jobs ({n_fused} fused "
                "mesh programs + probe rungs) — no dummy execution; compiles "
                "overlap epoch-0 plan build, drained before its wall"
            )
            return
        for d in self.topology.used_device_indices:
            for b in ladder:
                n += len(
                    self._aot_submit_worker_steps(
                        d, b, wins if mode == "window" else (), warm_acc, want_plain=True
                    )
                )
        if mode == "scan":
            d0 = self.topology.used_device_indices[0]
            group = self.topology.groups[d0]
            padded = [plan0.workers[self.rank_lo + r].padded_batch for r in group]
            for win in wins:
                n += len(self._aot_submit_superstep(padded, win))
        else:
            n += len(self._aot_submit_combine())
        self.logger.info(
            f"AOT warm: submitted {n} compile jobs ({len(ladder)} ladder rungs "
            f"up to {max_b}, windows {list(wins)}) — no dummy execution; "
            "compiles overlap epoch-0 plan build, drained before its wall"
        )

    def _submit_warm_fused(self, plan0) -> int:
        """Warm-submit the fused whole-epoch executables when epoch 0 will
        take a fused path (mirrors _dispatch_epoch's selection on the
        epoch-0 plan): the mesh program's compile overlaps the plan build
        instead of landing inside the excluded epoch 0. Returns the number
        of submitted keys (0 = elastic run)."""
        cfg = self.cfg
        if self._aot is None or self.n_proc > 1:
            return 0
        if self._can_use_fused(plan0):
            width = sum(w.padded_batch for w in plan0.workers)
            slow_len = self.world_size
        elif self._can_use_fused_dbs(plan0):
            width = self.world_size * self._cap_b
            slow_len = self.world_size
        elif self._can_use_packed(plan0):
            width = self._cap_packed
            slow_len = 1
        else:
            return 0
        n = 0
        for s0, s1 in self._chunk_ranges(plan0.num_steps):
            n += len(self._aot_submit_fused(s1 - s0, width, slow_len))
        return n

    def _aot_stage_plan(self, plan) -> tuple:
        """Submit this plan's missing executables (a mid-run rebalance on a
        cold service compiles concurrently instead of serially-lazily) plus
        speculative adjacent ladder rungs, and return the keys the epoch's
        dispatch will barrier on."""
        if self._aot is None:
            return ()
        cfg = self.cfg
        mode = self._elastic_mode()
        topo = self.topology
        ranges = self._elastic_ranges(plan.num_steps)
        wins = tuple(sorted({s1 - s0 for s0, s1 in ranges}))
        needed: list = []
        if mode == "scan":
            d0 = topo.used_device_indices[0]
            group = topo.groups[d0]
            padded = [plan.workers[self.rank_lo + r].padded_batch for r in group]
            for win in wins:
                needed += self._aot_submit_superstep(padded, win)
            # the standalone probes still run the plain single-step rungs
            for r in group:
                b = plan.workers[self.rank_lo + r].padded_batch
                needed += self._aot_submit_worker_steps(
                    d0, b, (), want_acc=False, want_plain=True
                )
        else:
            for d in topo.used_device_indices:
                group = topo.groups[d]
                want_acc = len(group) > 1
                for r in group:
                    b = plan.workers[self.rank_lo + r].padded_batch
                    needed += self._aot_submit_worker_steps(
                        d, b, wins if mode == "window" else (), want_acc, want_plain=True
                    )
            needed += self._aot_submit_combine()
        return tuple(dict.fromkeys(needed))

    def _maybe_speculate(self, plan) -> None:
        """Background-compile the executables the NEXT rebalance is likely to
        dispatch. Ladder modes: the rungs ADJACENT to this plan's (±bucket,
        capacity-clamped) — the next rebalance moves each worker at most a
        few rungs. Scan mode (config.speculate_scan): the superstep shape
        TUPLES have no finite adjacency, so the solver's next share vector is
        PREDICTED (ShareTrajectoryPredictor) and run through the plan
        builder's own quantization — a share hit is a tuple-key hit. Called
        from run_epoch AFTER the timed region — the jobs overlap the untimed
        validation tail (and drain at the next epoch's pre-wall barrier), so
        timed walls never share cores with the compiler; a misprediction
        costs only background work."""
        cfg = self.cfg
        if self._aot is None or not cfg.aot_speculate or not cfg.dynamic_batch_size:
            return
        if self._elastic_mode() == "scan":
            if cfg.speculate_scan:
                self._speculate_scan_tuple()
            return
        wins = ()
        if self._elastic_mode() == "window":
            wins = tuple(
                sorted({s1 - s0 for s0, s1 in self._elastic_ranges(plan.num_steps)})
            )
        self._aot_speculate(plan, wins)

    def _speculate_scan_tuple(self) -> None:
        """Predict the next epoch's quantized share vector, build the plan it
        implies (host-side arithmetic only), and queue its superstep
        (shape-tuple, window) keys speculatively. A converged run predicts
        the tuple it already dispatches — the submit dedups to a lookup."""
        cfg = self.cfg
        bucket = cfg.bucket if (cfg.snap_to_bucket and self.SNAP_BATCHES) else 0
        cap = min(1.0, cfg.capacity_factor / self.world_size)
        if cap * self.world_size < 1.0:
            return  # infeasible cap (capacity_factor < 1): nothing to match
        ctl = self._rebalance_ctl
        if ctl is not None and ctl.last_candidate_batches is not None:
            # window-cadence runs: speculation is RE-AIMED at the online
            # controller's candidate plan — its EMA-rate solve is the plan a
            # mid-epoch switch (or the next epoch's boundary solve, seeded
            # from the switched shares) will actually dispatch, so a hit
            # keeps switches foreground-compile-free
            batches = np.asarray(ctl.last_candidate_batches, dtype=np.int64)
        else:
            batches = self._share_predictor.predict_batches(
                cfg.batch_size, bucket=bucket, max_share=cap
            )
        if batches is None:
            return
        # epoch index only seeds the plan's permutation; shapes are epoch-free
        pred = self._build_plan(0, batches)
        topo = self.topology
        d0 = topo.used_device_indices[0]
        group = topo.groups[d0]
        padded = [pred.workers[self.rank_lo + r].padded_batch for r in group]
        for s0, s1 in self._elastic_ranges(pred.num_steps):
            self._aot_submit_superstep(padded, s1 - s0, speculative=True)

    def _aot_speculate(self, plan, wins) -> None:
        cfg = self.cfg
        if not (cfg.snap_to_bucket and self.SNAP_BATCHES):
            return
        max_b = self._cap_b
        for d in self.topology.used_device_indices:
            group = self.topology.groups[d]
            want_acc = len(group) > 1
            for r in group:
                b = plan.workers[self.rank_lo + r].padded_batch
                for nb in (b - cfg.bucket, b + cfg.bucket):
                    if cfg.bucket <= nb <= max_b:
                        self._aot_submit_worker_steps(
                            d, nb, wins, want_acc, want_plain=True, speculative=True
                        )

    def _aot_wait_needed(self, keys, epoch: int) -> None:
        """Barrier on the keys this epoch dispatches. Failed jobs log once
        and dispatch falls back to the lazy jit wrappers (``get`` returns
        None for a failed key)."""
        if self._aot is None or not keys:
            return
        t0 = time.perf_counter()
        for k, e in self._aot.wait(keys):
            if k not in self._aot_failed_logged:
                self._aot_failed_logged.add(k)
                self.logger.warning(
                    f"AOT compile failed for {k}: {e!r} — falling back to lazy jit"
                )
        dt = time.perf_counter() - t0
        if self._aot_warm_t0 is not None:
            self.logger.info(
                f"AOT warm: epoch-{epoch} dispatch barrier {dt:.2f}s "
                f"({time.perf_counter() - self._aot_warm_t0:.1f}s since "
                "submission; remaining jobs keep compiling in the background)"
            )
            self._aot_warm_t0 = None

    def _warm_shapes(self) -> None:
        """LEGACY execute-to-compile warm (``--aot_warm off``): pre-compile
        the elastic step for every padded batch shape the balancer can
        produce (multiples of ``bucket`` up to the capacity cap), on every
        used device, by executing dummy steps serially. Kept as the
        serial-vs-concurrent A/B reference (bench aot_warm_ab) — the AOT
        service above is the production path. Without any warm, each
        rebalance's fresh shape pays its XLA compile inside a timed epoch —
        on short benchmark runs the compiles dominate and bury the
        balancer's actual win."""
        cfg = self.cfg
        ladder, max_b = self._warm_ladder()
        key = jax.random.PRNGKey(0)
        slow = jnp.int32(0)
        t0 = time.perf_counter()
        views = shard_views(self.state.params, self.topology.devices)
        # the accumulate variant only runs where workers share a device
        warm_acc = any(len(g) > 1 for g in self.topology.groups.values())
        use_cache = self._use_device_cache
        for d in self.topology.used_device_indices:
            dev = self.topology.devices[d]
            cache = self._device_cache_for(d) if use_cache else ()
            for b in ladder:
                x, y, w = self._dummy_batch(b)
                if use_cache:
                    args = cache + (
                        jax.device_put(np.zeros((b,), np.int32), dev),
                        jax.device_put(w, dev),
                        jax.device_put(key, dev),
                        jax.device_put(slow, dev),
                    )
                    step_first = self.steps.worker_step_first_idx
                    step_acc = self.steps.worker_step_acc_idx
                else:
                    args = (
                        jax.device_put(x, dev),
                        jax.device_put(y, dev),
                        jax.device_put(w, dev),
                        jax.device_put(key, dev),
                        jax.device_put(slow, dev),
                    )
                    step_first = self.steps.worker_step_first
                    step_acc = self.steps.worker_step_acc
                # deliberate execute-to-compile: this IS the serial A/B
                # reference leg (aot_warm off)
                acc, aux = step_first(views[d], *args)  # graftlint: disable=G007
                if warm_acc:
                    acc, aux = step_acc(views[d], acc, *args)
                jax.block_until_ready(aux)
                heartbeat()  # one ladder compile done — the watchdog's unit
        n_win = self._warm_windowed_shapes(ladder, views, warm_acc)
        n_win += self._warm_superstep_shapes()
        self.logger.info(
            f"Warm start: compiled {len(ladder)} batch shapes "
            f"(up to {max_b}, + {n_win} windowed/superstep variants) in "
            f"{time.perf_counter() - t0:.1f}s"
        )

    def _warm_windowed_shapes(self, ladder, views, warm_acc: bool) -> int:
        """Warm the window-sliced executables the superstep hot loop actually
        dispatches (the per-step ladder above still serves the probes). The
        window lengths come from a representative epoch-0 plan — the
        equal-step invariant keeps num_steps (and so the body/tail window
        lengths) constant across rebalanced plans, so (rung, window) covers
        the epochs' compiled-shape universe. Scan mode is excluded: its
        executables specialize on whole shape TUPLES (combinatorial — they
        compile lazily, once per (shapes, window), sentinel-checked)."""
        if self._elastic_mode() != "window":
            return 0
        cfg = self.cfg
        plan0 = self._build_plan(0, integer_batch_split(self.shares, cfg.batch_size))
        wins = sorted({s1 - s0 for s0, s1 in self._elastic_ranges(plan0.num_steps)})
        use_cache = self._use_device_cache
        key = jax.random.PRNGKey(0)
        slow = jnp.int32(0)
        s0_i = np.int32(0)
        n = 0
        for d in self.topology.used_device_indices:
            dev = self.topology.devices[d]
            cache = self._device_cache_for(d) if use_cache else ()
            for b in ladder:
                x, y, w = self._dummy_batch(b)
                for win in wins:
                    kwin = jax.device_put(jax.random.split(key, win), dev)
                    ww = jax.device_put(np.broadcast_to(w, (win,) + w.shape).copy(), dev)
                    if use_cache:
                        args = cache + (
                            jax.device_put(np.zeros((win, b), np.int32), dev),
                            ww,
                            kwin,
                            s0_i,
                            jax.device_put(slow, dev),
                        )
                        step_first = self.steps.worker_step_first_win_idx
                        step_acc = self.steps.worker_step_acc_win_idx
                    else:
                        args = (
                            jax.device_put(np.broadcast_to(x, (win,) + x.shape).copy(), dev),
                            jax.device_put(np.broadcast_to(y, (win,) + y.shape).copy(), dev),
                            ww,
                            kwin,
                            s0_i,
                            jax.device_put(slow, dev),
                        )
                        step_first = self.steps.worker_step_first_win
                        step_acc = self.steps.worker_step_acc_win
                    # deliberate execute-to-compile (serial A/B reference leg)
                    acc, aux = step_first(views[d], *args)  # graftlint: disable=G007
                    if warm_acc:
                        acc, aux = step_acc(views[d], acc, *args)
                    jax.block_until_ready(aux)
                    n += 1
                    heartbeat()
        return n

    def _warm_superstep_shapes(self) -> int:
        """Scan-mode warm: compile the epoch-0 (uniform) plan's superstep
        (shape-tuple, window) keys against a zeros dummy state (donated and
        discarded), so the run's opening epochs pay no unrolled-scan compile
        inside a timed wall. Rebalanced plans' fresh shape TUPLES are
        combinatorial and still compile lazily, once per key — warmed keys
        register in ``_superstep_keys`` so the compile-once sentinel's
        cache-vs-keys comparison stays exact."""
        if self._elastic_mode() != "scan":
            return 0
        cfg = self.cfg
        plan0 = self._build_plan(0, integer_batch_split(self.shares, cfg.batch_size))
        wins = sorted({s1 - s0 for s0, s1 in self._elastic_ranges(plan0.num_steps)})
        topo = self.topology
        d0 = topo.used_device_indices[0]
        group = topo.groups[d0]
        dev = topo.devices[d0]
        use_cache = self._use_device_cache
        key = jax.random.PRNGKey(0)
        n = 0
        for win in wins:
            padded = [plan0.workers[self.rank_lo + r].padded_batch for r in group]
            self._superstep_keys.add(topo.group_shape_key(padded, win))
            cols = []
            for b in padded:
                x, y, w = self._dummy_batch(b)
                kwin = jax.device_put(jax.random.split(key, win), dev)
                ww = jax.device_put(
                    np.broadcast_to(w, (win,) + w.shape).copy(), dev
                )
                if use_cache:
                    cols.append((
                        jax.device_put(np.zeros((win, b), np.int32), dev),
                        ww,
                        kwin,
                    ))
                else:
                    cols.append((
                        jax.device_put(np.broadcast_to(x, (win,) + x.shape).copy(), dev),
                        jax.device_put(np.broadcast_to(y, (win,) + y.shape).copy(), dev),
                        ww,
                        kwin,
                    ))
            tup = tuple(zip(*cols))
            slows = tuple(jax.device_put(jnp.int32(0), dev) for _ in group)
            # the dummy must replicate the REAL state's shardings AND
            # committed-ness, not just shapes/dtypes: zeros_like drops the
            # NamedSharding, and committing a leaf the real state leaves
            # uncommitted (the injected-hyperparams lr scalar) changes the
            # pjit signature either way — the mismatch compiles a second,
            # never-reused superstep variant
            def zero_like(t):
                z = jnp.zeros(t.shape, t.dtype)
                if getattr(t, "_committed", True):
                    z = jax.device_put(z, t.sharding)
                return z

            dummy = jax.tree_util.tree_map(zero_like, self.state)
            if use_cache:
                idxs, ws_, ks = tup
                # deliberate execute-to-compile (serial A/B reference leg)
                _, aux = self.steps.group_superstep_idx(  # graftlint: disable=G007
                    dummy, *self._device_cache_for(d0), idxs, ws_, ks, slows
                )
            else:
                xs, ys, ws_, ks = tup
                # deliberate execute-to-compile (serial A/B reference leg)
                _, aux = self.steps.group_superstep(  # graftlint: disable=G007
                    dummy, xs, ys, ws_, ks, slows
                )
            jax.block_until_ready(aux)
            n += 1
            heartbeat()
        return n

    def run(self, epochs: Optional[int] = None) -> MetricsRecorder:
        cfg = self.cfg
        epochs = cfg.epoch_size if epochs is None else epochs
        self.logger.info(
            f"Starting: {cfg.model}/{cfg.dataset}, ws={cfg.world_size}, "
            f"B={cfg.batch_size}, devices={self.n_dev}, dbs={cfg.dynamic_batch_size}"
        )
        self._maybe_warm()
        start_epoch = 0
        if cfg.ckpt_dir:
            start_epoch = self._maybe_restore()
        if cfg.profile_dir:
            jax.profiler.start_trace(cfg.profile_dir)
        try:
            for epoch in range(start_epoch, epochs):
                if cfg.elastic == "on":
                    self._run_epoch_elastic_world(epoch)
                else:
                    self.run_epoch(epoch)
                if cfg.ckpt_dir:
                    self._save_checkpoint(epoch)
        finally:
            if cfg.profile_dir:
                jax.profiler.stop_trace()
            if cfg.ckpt_dir:
                # epoch-tail saves are async (train/checkpoint.py): drain
                # them before declaring the run complete, and drop the
                # cached manager's thread pools (long-lived processes build
                # many engines)
                from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
                    flush_checkpoints,
                )

                flush_checkpoints(cfg.ckpt_dir, close=True)
                heartbeat()  # checkpoint drain answered — not a stall
        if self.proc_id == 0:
            # rank-0-only artifact, like the reference (dbs.py:440-442)
            self.recorder.save(cfg.stat_dir, cfg.base_filename())
        self.save_trace()
        self.logger.info(
            f"Total wallclock: {self.total_wallclock:.3f}s"
            + (
                f" (+{self.total_probe_s:.3f}s probe/instrumentation)"
                if self.total_probe_s > 0
                else ""
            )
        )
        return self.recorder

    def close_spool(self):
        """Drain and close the flight-recorder spool (idempotent; returns
        the closed writer for byte accounting, or None). The ONE external
        teardown surface — bench arms and test harnesses that drive epochs
        without run() call this instead of reaching into the tracer."""
        if self._spool_writer is None:
            return None
        sp = self._trace.detach_spool()
        self._spool_writer = None
        if sp is not None:
            self.logger.info(
                f"flight recorder: spool closed ({sp.path}, "
                f"{sp.bytes_written} bytes)"
            )
        return sp

    def save_trace(self) -> Optional[str]:
        """Persist the graftscope trace (Chrome-trace JSON under
        cfg.trace_dir, config-encoded filename per process) when tracing is
        enabled; returns the path. Summarize with `graftscope summarize`,
        or open in ui.perfetto.dev next to a --profile_dir device trace."""
        if not self._trace.enabled:
            return None
        # flight recorder: a clean end of run drains and closes the spool
        # (everything buffered reaches disk) — the crash path needs no
        # cooperation, the flusher thread already wrote all but the tail
        self.close_spool()
        path = os.path.join(
            self.cfg.trace_dir,
            self.cfg.base_filename().format(self.proc_id) + ".trace.json",
        )
        # process-backend compile workers buffer their own spans and write
        # them at exit: flush (shut down) the worker pool first, then stitch
        # the files into the run trace as pid-tagged tracks
        worker_traces = []
        if self._aot is not None:
            worker_traces = self._aot.flush_workers()
        self._trace.save(path)
        if worker_traces:
            from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
                merge_trace_files,
            )

            merge_trace_files(path, worker_traces)
        self.logger.info(
            f"graftscope trace saved: {path} "
            f"({len(self._trace.events())} events"
            + (f"; stitched {len(worker_traces)} compile-worker trace files"
               if worker_traces else "")
            + "; `graftscope summarize` for the per-phase epoch-attribution "
            "table)"
        )
        return path

    def _save_checkpoint(self, epoch: int) -> None:
        from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
            save_checkpoint,
        )

        save_checkpoint(
            self.cfg.ckpt_dir,
            epoch,
            self.state,
            {
                "shares": self.shares,
                "node_times": self.node_times,
                "total_wallclock": self.total_wallclock,
                "total_probe_s": self.total_probe_s,
                # elastic resume-after-loss: the fleet this checkpoint was
                # taken at (original ranks); _maybe_restore adopts it
                "active_ranks": list(self.active_ranks),
            },
        )

    def _zero1_restore_template(self, sidecar: dict):
        """Restore template matching a checkpoint saved at a REDUCED fleet
        (elastic × shard_update): the saved 1/N optimizer chunks are padded
        to the survivor device count's multiple, so the fresh full-world
        template's flat shapes would mismatch. Rebuild the opt-state chunk
        leaves at the saved padding (replicated placement — addressable for
        the restore; the post-restore reshard re-chunks). None = the stamp
        matches the current fleet, keep the ordinary template."""
        saved_active = sidecar.get("active_ranks")
        if saved_active is None:
            return None
        # same validity gate as _maybe_restore's adopt branch, applied
        # BEFORE indexing: a stamp from a different world_size (stale dir,
        # re-configured resume) must fall back to the ordinary template,
        # not crash the restore
        if not all(
            isinstance(r, (int, float)) and 0 <= int(r) < self.cfg.world_size
            for r in saved_active
        ):
            return None
        from dynamic_load_balance_distributeddnn_tpu.train.state import (
            zero1_padded_size,
            zero1_param_count,
        )

        local_devices = sorted(jax.local_devices(), key=lambda d: d.id)
        ids_global = self.cfg.worker_device_ids(len(local_devices))
        n_dev_saved = len({ids_global[int(r)] for r in saved_active})
        saved_padded = zero1_padded_size(self.state.params, n_dev_saved)
        if saved_padded == self._zero1_padded:
            return None
        total = zero1_param_count(self.state.params)
        rep = replicated_sharding(self.mesh)

        def resize(leaf):
            if not (hasattr(leaf, "ndim") and leaf.ndim >= 1):
                return leaf
            if leaf.shape[0] < total:
                return leaf
            shape = (saved_padded,) + tuple(leaf.shape[1:])
            return jax.device_put(jnp.zeros(shape, leaf.dtype), rep)

        return self.state.replace(
            opt_state=jax.tree_util.tree_map(resize, self.state.opt_state)
        )

    def _maybe_restore(self) -> int:
        from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
            restore_checkpoint,
        )

        template_fn = None
        if self.cfg.elastic == "on" and self.cfg.shard_update:
            template_fn = self._zero1_restore_template
        # a respawned JOINER entering the grown world (DBS_MH_IDENT marks
        # it): measure our own ranks' per-example costs on their local
        # devices (no collectives) and publish them into the grow
        # rendezvous's probe exchange BEFORE the restore barrier both sides
        # synchronize on — the survivors publish theirs at the matching
        # point in _mh_rerendezvous, so after the restore every publication
        # is on disk and both sides collect the identical set (ISSUE 17)
        joiner = (
            self.cfg.elastic == "on"
            and self.n_proc > 1
            and self._rdzv is not None
            and os.environ.get("DBS_MH_IDENT") is not None
        )
        if joiner:
            own_costs = {}
            for r in self._ranks_of_proc(self._orig_proc_id):
                c = self._probe_local_cost(int(r))
                if c is not None:
                    own_costs[int(r)] = float(c)
            self._publish_probe_costs(own_costs)
        restored = restore_checkpoint(
            self.cfg.ckpt_dir, self.state, template_fn=template_fn
        )
        if restored is None:
            return 0
        epoch, state, controller = restored
        self.state = state
        # Elastic resume-after-loss: a run that checkpointed at a REDUCED
        # fleet stamps its active ranks; adopt them (re-shard to the saved
        # survivor set) so the controller vectors below line up. Without
        # elastic (or with a stale/not-applicable stamp) a length-mismatched
        # controller vector resets to uniform rather than poisoning the
        # solver with a wrong-shaped state.
        saved_active = controller.get("active_ranks")
        if self.cfg.elastic == "on" and self.n_proc > 1:
            # Multi-host: the LIVE rendezvous roster is authoritative, not
            # the checkpoint stamp — a joiner restoring a shrink-era
            # checkpoint (stamped with the survivor fleet) is entering the
            # GROWN world its join rendezvous just established
            live = sorted(
                r
                for r in range(self.cfg.world_size)
                if self._proc_of_rank(r) in set(self._proc_roster)
            )
            if live != self.active_ranks:
                self._reshard_world(live)
                self.state = retry_transient(
                    lambda: self._state_from_host(
                        self._state_to_host(self.state)
                    ),
                    logger=self.logger,
                    desc="resume state re-placement",
                    tick=heartbeat,
                )
                self._fix_comm_residual()
                for r in range(self.cfg.world_size):
                    if r not in self.active_ranks:
                        self.health.mark_down(r)
            base = (
                [int(r) for r in saved_active]
                if saved_active
                and all(
                    0 <= int(r) < self.cfg.world_size for r in saved_active
                )
                else list(self.active_ranks)
            )
            if "shares" in controller and len(controller["shares"]) == len(
                base
            ):
                self._adopt_controller_vectors(
                    base,
                    controller["shares"],
                    controller.get("node_times", controller["shares"]),
                )
            elif "shares" in controller:
                # a stamp from a different world layout: keep the fresh
                # uniform vectors rather than poisoning the solver — same
                # contract as the single-process resume path below
                self.logger.warning(
                    f"Resume: sidecar vectors ({len(controller['shares'])} "
                    f"entries) do not match the stamped fleet "
                    f"({len(base)}) — resetting to uniform"
                )
            if joiner:
                # upgrade the sidecar-derived seed to the equilibrium of the
                # exchanged probe costs (ISSUE 17). The restore above was a
                # global barrier, so every process's probe file is on disk;
                # collect is all-or-nothing, so an incomplete exchange keeps
                # the identical sidecar vectors on every process instead
                self._collect_probe_seed()
            if "total_wallclock" in controller:
                self.total_wallclock = float(controller["total_wallclock"])
            if "total_probe_s" in controller:
                self.total_probe_s = float(controller["total_probe_s"])
            self.logger.info(
                f"Resumed from checkpoint at epoch {epoch} over the live "
                f"fleet {self.active_ranks} (roster {self._proc_roster})"
            )
            return epoch + 1
        if (
            self.cfg.elastic == "on"
            and saved_active is not None
            and sorted(int(r) for r in saved_active) != self.active_ranks
            and all(0 <= int(r) < self.cfg.world_size for r in saved_active)
        ):
            self._reshard_world(sorted(int(r) for r in saved_active))
            # _reshard_world leaves state placement to its caller: the
            # restored state is still replicated over the FULL original
            # mesh, and a mixed device set poisons every state-fed
            # executable on the survivor mesh — re-place onto it
            self.state = retry_transient(
                lambda: self._state_from_host(self._state_to_host(self.state)),
                logger=self.logger,
                desc="resume state re-placement",
                tick=heartbeat,
            )
            self._fix_comm_residual()
            for r in range(self.cfg.world_size):
                if r not in self.active_ranks:
                    self.health.mark_down(r)
            self.logger.info(
                f"Resume: adopted checkpointed survivor fleet "
                f"{self.active_ranks} (world size {self.world_size})"
            )
        for key, fallback in (
            ("shares", lambda: initial_partition(self.world_size)),
            ("node_times", lambda: np.ones(self.world_size, dtype=np.float64)),
        ):
            if key in controller:
                vec = np.asarray(controller[key], dtype=np.float64)
                if len(vec) == self.world_size:
                    setattr(self, key, vec)
                else:
                    self.logger.warning(
                        f"Resume: checkpointed {key} has length {len(vec)} "
                        f"but the fleet is {self.world_size} — resetting to "
                        "uniform"
                    )
                    setattr(self, key, fallback())
        if "total_wallclock" in controller:
            self.total_wallclock = float(controller["total_wallclock"])
        if "total_probe_s" in controller:
            self.total_probe_s = float(controller["total_probe_s"])
        self.logger.info(f"Resumed from checkpoint at epoch {epoch}")
        return epoch + 1

    # ------------------------------------------------- elastic world size
    # (ISSUE 6). Degradation ladder: the solver re-routes data away from a
    # SLOW worker every epoch (the paper's story); a LOST worker — dead or
    # preempted — used to kill the run. With cfg.elastic on, worker loss is
    # detected (health checks at window boundaries, fed by the preemption
    # injector's virtual schedule or real peer heartbeats), CONFIRMED
    # (detect_misses consecutive misses), and survived: drain, re-solve the
    # partition over the survivors (the same solver code path as the
    # straggler re-route — balance/solver.py restarts its velocity track on
    # world-size change by design), re-shard the data, re-warm the new
    # world size's executables through the AOT service, and continue from
    # the epoch-start consistent snapshot. A recovered worker is readmitted
    # at the next epoch boundary with a probe-seeded share.

    def _arm_peer_heartbeats(self) -> None:
        """Multi-host detection + recovery channel: each process beacons its
        own heartbeat file under DBS_PEER_HB_DIR; health checks scan peers
        for staleness (and the watchdog's exit-reason tag), and the SAME
        directory carries the re-rendezvous protocol files
        (runtime/rendezvous.py) — a confirmed peer-process loss is survived
        by tearing down ``jax.distributed`` and re-initializing over the
        survivor roster at the epoch boundary (``_recover_multihost``).
        Workers that want that recovery must have brought the world up
        through ``rendezvous.elastic_initialize`` (a stock-initialized
        world's coordination service aborts every survivor on peer death);
        detection alone works either way."""
        hb_dir = os.environ.get("DBS_PEER_HB_DIR")
        if not hb_dir:
            return
        from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
            ProcessHeartbeat,
        )
        from dynamic_load_balance_distributeddnn_tpu.runtime.rendezvous import (
            RendezvousStateMachine,
        )

        self._rdzv = RendezvousStateMachine(
            hb_dir, self._orig_proc_id, logger=self.logger
        )
        roster = self._rdzv.current_roster()
        if len(roster) == self.n_proc:
            self._proc_roster = roster
        # the ORIGINAL fleet shape anchors worker-rank ownership
        # (_ranks_of_proc slices world_size by the GEN-0 process count). A
        # long-lived survivor inherited it from its own gen-0 n_proc, but a
        # respawned JOINER builds its engine inside the grown world — if
        # the fleet grew back to fewer processes than gen 0 had, the live
        # process count is the WRONG divisor. ack_g0 records the original
        # roster; adopt its size when present.
        import json as _json

        try:
            with open(os.path.join(hb_dir, "ack_g0.json")) as f:
                g0 = _json.load(f)
            roster0 = [int(p) for p in g0.get("roster", ())]
            if roster0 and len(roster0) != self._n_proc0:
                self.logger.info(
                    f"elastic: adopting generation-0 fleet shape "
                    f"({len(roster0)} processes) for rank ownership "
                    f"(live world has {self.n_proc})"
                )
                self._n_proc0 = len(roster0)
        except (OSError, ValueError):
            pass
        self._hb_beacon = ProcessHeartbeat(
            period_s=float(os.environ.get("DBS_PEER_HB_PERIOD_S", "1.0"))
        )
        beacon_path = self._hb_beacon.beacon(hb_dir, f"proc{self._orig_proc_id}")
        self._hb_beacon_path = beacon_path
        # a stall-watchdog abort must be readable by the PEERS too, not just
        # the parent watching this process's own heartbeat file — register
        # the beacon so the abort path tags it with the exit reason
        from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
            register_exit_tag_path,
            unregister_exit_tag_path,
        )

        register_exit_tag_path(beacon_path)
        # tie beacon/watcher threads and the tag registration to THIS
        # trainer's lifetime: long-lived processes build many engines, and
        # a later run's abort must not rewrite a finished run's beacon file
        import weakref

        beacon = self._hb_beacon  # finalize must not capture self

        def _teardown() -> None:
            beacon.stop()
            unregister_exit_tag_path(beacon_path)

        weakref.finalize(self, _teardown)
        # detection must run OFF the controller thread: when a peer dies
        # mid-collective, the controller is wedged inside that collective —
        # the watcher thread still sees the stale pulse, logs it, and drops
        # a marker file the launcher (bench retry loop, test harness) reads
        stale_s = float(os.environ.get("DBS_PEER_HB_STALE_S", "10.0"))
        peers = [
            f"proc{p}" for p in self._proc_roster if p != self._orig_proc_id
        ]
        # the callback must not capture self either: the WATCHER thread
        # holds it, and a closed-over trainer would be pinned reachable —
        # the finalize above would then never fire
        logger, proc_id = self.logger, self._orig_proc_id

        def _on_stale(ident: str, info: dict) -> None:
            reason = ProcessHeartbeat.stale_reason(info)
            logger.warning(
                f"elastic: peer {ident} unreachable ({reason}) — survivors "
                "will re-rendezvous at the next boundary (a wedged "
                "collective against the dead peer errors or aborts first)"
            )
            # flight-recorder detection edge: emitted from the WATCHER
            # thread — exactly the thread that still runs when the
            # controller is wedged in a collective against the dead peer
            get_tracer().instant(
                "peer_stale", cat="elastic",
                args={"peer": ident, "reason": reason},
            )
            try:
                import json

                path = os.path.join(
                    hb_dir, f"elastic_detected_{ident}_by_proc{proc_id}.json"
                )
                # G017 protocol-file discipline: sibling watchers read this
                # marker while we write it, so publish atomically (tmp +
                # os.replace) — a torn in-place write here is exactly the
                # half-JSON the rendezvous readers must otherwise survive
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump({"peer": ident, "reason": reason}, f)
                os.replace(tmp, path)
            except OSError:
                pass

        self._hb_beacon.watch(hb_dir, peers, stale_s, _on_stale)
        # re-armable watcher factory for fleet growth (a rejoined peer — or
        # one the original watcher already fired on — needs a fresh watch
        # thread; closures capture the beacon, never self)
        beacon_ref = self._hb_beacon
        self._peer_watch = lambda idents: beacon_ref.watch(
            hb_dir, idents, stale_s, _on_stale
        )
        self.logger.info(
            f"elastic: process heartbeat beacon + peer watcher armed under "
            f"{hb_dir}"
        )

    def _ranks_of_proc(self, p: int) -> range:
        """ORIGINAL worker ranks owned by ORIGINAL process ``p`` — the
        gen-0 contiguous slice, invariant across re-rendezvous (compact
        runtime ranks re-derive from these via ``active_ranks``)."""
        wsp = self.cfg.world_size // max(self._n_proc0, 1)
        return range(p * wsp, (p + 1) * wsp)

    def _proc_of_rank(self, r: int) -> int:
        return int(r) // (self.cfg.world_size // max(self._n_proc0, 1))

    def _scan_peer_heartbeats(self, force: bool = False) -> set:
        """Original ranks owned by peers whose heartbeat files went stale
        (multi-host only) — plus ranks of peers another SURVIVOR already
        claimed lost for this generation (rendezvous loss files), so
        detection stays coherent across survivors whose beacon scans lag.
        Single-process runs return an empty set. Throttled to the heartbeat
        period (``force`` bypasses — the collective-failure attribution
        path needs a fresh verdict NOW): this runs at every window boundary
        inside the timed epoch, and a fresh listdir + per-file read there
        cannot learn anything a sub-period rescan didn't — while on a slow
        shared filesystem it would bill real I/O stalls to the epoch
        wall."""
        hb_dir = os.environ.get("DBS_PEER_HB_DIR")
        if not hb_dir or self.n_proc == 1:
            return set()
        period_s = float(os.environ.get("DBS_PEER_HB_PERIOD_S", "1.0"))
        now = time.perf_counter()
        cached = self._peer_scan_cache
        if not force and cached is not None and now - cached[0] < period_s:
            return cached[1]
        from dynamic_load_balance_distributeddnn_tpu.runtime.health import (
            ProcessHeartbeat,
        )

        stale_s = float(os.environ.get("DBS_PEER_HB_STALE_S", "10.0"))
        down: set = set()
        scan = ProcessHeartbeat.scan(hb_dir)
        claimed = (
            self._rdzv.claimed_losses() if self._rdzv is not None else set()
        )
        for p in self._proc_roster:
            if p == self._orig_proc_id:
                continue
            if p in claimed:
                # another survivor's published verdict: adopt it instead of
                # dispatching one more collective against the dead process
                down.update(self._ranks_of_proc(p))
                continue
            info = scan.get(f"proc{p}")
            if info is None:
                continue
            if ProcessHeartbeat.is_stale(info, stale_s):
                self.logger.warning(
                    f"elastic: peer process {p} unreachable "
                    f"({ProcessHeartbeat.stale_reason(info)})"
                )
                down.update(self._ranks_of_proc(p))
        self._peer_scan_cache = (now, down)
        return down

    def _check_health(self, epoch: int, frac: float = 0.0) -> None:
        """One liveness round over the active fleet, at epoch-time
        ``epoch + frac`` (window boundaries during the elastic epoch, 0.0
        at epoch start). A worker scheduled down by the preemption
        injector — or owned by a stale peer process — accrues a miss;
        ``detect_misses`` consecutive misses raise :class:`WorkerLost` and
        the run loop enters the recovery path."""
        if self.cfg.elastic != "on":
            return
        t = float(epoch) + min(max(frac, 0.0), 0.999)
        down: set = set()
        down_workers = getattr(self.injector, "down_workers", None)
        if down_workers is not None:
            down = set(down_workers(t))
        down |= self._scan_peer_heartbeats()
        confirmed = []
        for r in self.active_ranks:
            if r in down:
                if self._detect_t0 is None:
                    self._detect_t0 = time.perf_counter()  # first miss seen
                if self.health.report_miss(r):
                    confirmed.append(r)
                    self._lost_t[r] = t
            else:
                self.health.report_alive(r)
        # a DROPPED worker (no longer active) that stops reading as down —
        # its process heartbeat resumed, its injector outage ended — is
        # signalling again: LOST -> RECOVERING, picked up by _maybe_readmit
        # at the next epoch boundary. Without this, only injector-scheduled
        # rejoins could ever readmit (active-rank loops never see the rank).
        # Gated on t >= the confirmed loss time: the recovery path RE-RUNS
        # the epoch, so these rounds re-visit schedule times from before the
        # loss, where "not down" is history, not a recovery.
        for r in self.health.lost():
            if (
                r not in down
                and r not in self.active_ranks
                and t >= self._lost_t.get(r, -1.0)
            ):
                self.health.report_alive(r)
        if not any(r in down for r in self.active_ranks) and not confirmed:
            self._detect_t0 = None
        if confirmed:
            raise WorkerLost(confirmed)

    def _run_epoch_elastic_world(self, epoch: int) -> Dict[str, float]:
        """One epoch under elasticity: readmit recovered workers at the
        boundary, snapshot the consistent state, and on a confirmed loss
        recover and RE-RUN the epoch over the survivors (the snapshot makes
        the re-run exact — no example is half-applied)."""
        self._maybe_readmit(epoch)
        while True:
            self._snapshot_epoch_state()
            try:
                return self.run_epoch(epoch)
            except WorkerLost as e:
                if self._recoveries >= self.cfg.elastic_max_recoveries:
                    self.logger.error(
                        f"elastic: recovery budget exhausted "
                        f"({self._recoveries}) — giving up"
                    )
                    raise
                self._recover(e.ranks, epoch)
            except Exception as e:  # noqa: BLE001 — attributed or re-raised
                # multi-host: a peer dying MID-collective surfaces as the
                # collective's error (closed socket) long before any window-
                # boundary health check runs — attribute it to the peer
                # verdict before treating it as fatal
                lost = self._attribute_collective_failure(e, epoch)
                if lost is None:
                    raise
                if self._recoveries >= self.cfg.elastic_max_recoveries:
                    self.logger.error(
                        f"elastic: recovery budget exhausted "
                        f"({self._recoveries}) — giving up"
                    )
                    raise
                self.logger.warning(
                    f"elastic: dispatch failure attributed to lost "
                    f"worker(s) {lost} — recovering"
                )
                self._recover(lost, epoch)

    def _snapshot_epoch_state(self) -> None:
        """Host-copy of the TrainState + controller vectors at the epoch
        boundary — the 'last consistent state' recovery resumes from. A
        HOST copy is mandatory: the hot-path executables donate the state
        buffers, so a device-side reference would be invalidated by the
        very epoch the snapshot exists to undo. One copy per epoch is the
        price of elasticity (only paid with elastic on)."""
        self._epoch_snap = {
            "state": self._state_to_host(self.state),
            "shares": self.shares.copy(),
            "node_times": self.node_times.copy(),
            "per_example_cost": self.per_example_cost.copy(),
            "active": list(self.active_ranks),
            "total_wallclock": self.total_wallclock,
            "total_probe_s": self.total_probe_s,
        }

    def _state_to_host(self, state) -> tuple:
        """(leaves, treedef) with each leaf as (owned numpy copy,
        committed?, weak_type?). Committed-ness and weak types are part of
        the pjit signature (see _warm_superstep_shapes) — dropping them
        would fork fresh compiled variants of every state-fed executable
        after a recovery."""
        leaves, treedef = jax.tree_util.tree_flatten(state)
        host = [
            (
                np.array(x, copy=True),
                bool(getattr(x, "_committed", True)),
                bool(getattr(x, "weak_type", False)),
            )
            for x in leaves
        ]
        return host, treedef

    def _state_from_host(self, snap: tuple):
        """Rebuild the TrainState from a host snapshot onto the CURRENT
        mesh. Replicated leaves re-place directly; with shard_update on,
        the flat 1/N optimizer chunks re-chunk for the (possibly changed)
        survivor mesh STRAIGHT from the host arrays — unpad to the true
        parameter count, re-pad to the new device-count multiple
        (:attr:`_zero1_padded`, set by _reshard_world), place 1/N-sharded
        (the host-side all_gather→re-split of the reshard boundary; the
        snapshot already materialized the full vector). Placing them
        replicated first would transiently hold the FULL optimizer state
        on every device — the exact memory shard_update exists to avoid.
        The generation-keyed AOT registry (``_aot_gen`` in every key)
        guarantees no stale zero-1 executable can resolve against the
        re-chunked layout."""
        host, treedef = snap
        sh = replicated_sharding(self.mesh)
        chunk_idx: set = set()
        chunked_sh = None
        total = new_padded = 0
        if self.cfg.shard_update:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
                zero1_chunk_axes,
            )

            # identify the flat-init chunk vectors by TREE POSITION (the
            # opt_state subtree) + the leading-dim convention of
            # state.py shard_optimizer_state — scalars/hyperparams are 0-d
            idx_tree = jax.tree_util.tree_unflatten(
                treedef, list(range(len(host)))
            )
            total = int(
                sum(host[i][0].size
                    for i in jax.tree_util.tree_leaves(idx_tree.params))
            )
            new_padded = self._zero1_padded
            chunk_idx = {
                i
                for i in jax.tree_util.tree_leaves(idx_tree.opt_state)
                if host[i][0].ndim >= 1 and host[i][0].shape[0] >= total
            }
            chunked_sh = NamedSharding(
                self.mesh, P(zero1_chunk_axes(self.mesh))
            )
        leaves = []
        for i, (val, committed, weak) in enumerate(host):
            if i in chunk_idx:
                v = val[:total]
                v = np.pad(
                    v, [(0, new_padded - total)] + [(0, 0)] * (v.ndim - 1)
                )
                leaves.append(jax.device_put(jnp.array(v, copy=True), chunked_sh))
                continue
            if weak and val.ndim == 0:
                leaf = jnp.asarray(val.item())
            else:
                # FORCED copy into a jax-owned buffer: the CPU backend can
                # zero-copy a numpy array (jnp.asarray/device_put alias its
                # memory), and the hot-path executables DONATE these leaves
                # — donation of an aliased buffer frees memory the snapshot
                # still owns (observed: nan values + double-free after the
                # first post-restore epoch)
                leaf = jnp.array(val, copy=True)
            if committed:
                if self.n_proc > 1:
                    # collective-free placement: device_put to a
                    # non-fully-addressable sharding runs assert_equal's
                    # hidden gloo broadcast, and the multi-host recovery /
                    # grow paths run ASYMMETRIC code across processes — an
                    # unmatched broadcast there pairs with the wrong
                    # collective on the peer. Every process holds the
                    # identical host snapshot, so assembling from local
                    # per-device copies is exact.
                    leaf = jax.make_array_from_single_device_arrays(
                        leaf.shape,
                        sh,
                        [
                            jax.device_put(leaf, d)
                            for d in sh.addressable_devices
                        ],
                    )
                else:
                    leaf = jax.device_put(leaf, sh)
            leaves.append(leaf)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _resolve_topology_tree(self, mesh_devices):
        """Resolve the combine's TopologyTree over ``mesh_devices``:
        declared (--hier_levels), else the two-level host/device split
        (real process topology or the synthetic --hier_hosts count).
        Returns ``(tree or None, learn)`` where ``learn`` says the
        operator asked for the probe-driven level merge ("learned"
        prefix)."""
        from dynamic_load_balance_distributeddnn_tpu.parallel.topology import (
            TopologyTree,
        )

        cfg = self.cfg
        spec = cfg.hier_levels.strip()
        learn = False
        if spec == "learned" or spec.startswith("learned,"):
            learn = True
            spec = spec[len("learned"):].lstrip(",")
        tree = None
        if spec:
            tree = TopologyTree.declared(spec, len(mesh_devices))
            if tree is None:
                self.logger.warning(
                    f"hier_levels={spec!r} does not factor "
                    f"{len(mesh_devices)} devices — trying the two-level "
                    "host/device split"
                )
        if tree is None:
            tree = TopologyTree.from_process_topology(
                mesh_devices, requested=cfg.hier_hosts
            )
        return tree, learn

    def _learn_tree_from_probe(self, mesh_devices) -> None:
        """Probe-driven level merge (--hier_levels learned...): collapse
        adjacent tree levels whose measured link rates are the same class,
        rebuild the mesh on the merged tree, and RE-PROBE it so
        ``_link_bw``'s per-level rates align with the final structure (the
        per-hop codec choice and the gate verdict read them). A merge down
        to one level means the fabric is symmetric — fall back flat."""
        # mesh rebuild below: drain any concurrent topology readers first
        # (G019 quiesce discipline; a no-op at __init__ time, when this
        # runs before the first pipeline exists)
        self._quiesce_pipeline()
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
            data_mesh,
            probe_link_bandwidth,
            tree_mesh,
        )
        from dynamic_load_balance_distributeddnn_tpu.parallel.topology import (
            TopologyTree,
        )

        rates = (self._link_bw or {}).get("level_bytes_per_s")
        if not rates or len(rates) != len(self._topo_tree.levels):
            return
        merged = TopologyTree.learned(self._topo_tree, rates)
        if merged is None:
            self.logger.warning(
                "hier_levels=learned: every level measured as the same "
                "link class (symmetric fabric) — falling back to the flat "
                "combine"
            )
            self.grad_comm = "flat"
            self._hier_hosts = 0
            self._topo_tree = None
            self._probe_gated_flat = True
            self.mesh = data_mesh(mesh_devices)
            return
        if merged.levels != self._topo_tree.levels:
            self.logger.info(
                f"hier_levels=learned: merged {self._topo_tree.levels} "
                f"-> {merged.levels} from measured link rates"
            )
            self._topo_tree = merged
            self._hier_hosts = merged.sizes[0]
            self.mesh = tree_mesh(mesh_devices, merged.names, merged.sizes)
            self._link_bw = probe_link_bandwidth(
                self.mesh, gate_ratio=self.cfg.dcn_probe_gate
            )
            heartbeat()

    def _resolve_wires(self) -> tuple:
        """Per-hop wire codecs for the CURRENT tree, outermost hop first —
        one entry per mesh level, innermost fp32. Sources, in order:
        explicit --grad_comm_wires list (must match the level count),
        "auto" (parallel/wire.py choose_wires over the probe's measured
        per-level rates), else the legacy default (--grad_comm_wire on the
        outermost hop, fp32 below)."""
        if self.grad_comm != "hier":
            return ()
        cfg = self.cfg
        sizes = self._topo_tree.sizes
        k = len(sizes)
        spec = cfg.grad_comm_wires.strip()
        if spec == "auto":
            rates = (self._link_bw or {}).get("level_bytes_per_s")
            if rates and len(rates) == k:
                from dynamic_load_balance_distributeddnn_tpu.parallel.wire import (
                    choose_wires,
                )

                wires = choose_wires(sizes, rates)
                self.logger.info(
                    f"grad_comm_wires=auto: {dict(zip(self._topo_tree.names, wires))} "
                    "from measured link rates"
                )
                return wires
            self.logger.warning(
                "grad_comm_wires=auto needs the bandwidth probe's "
                "per-level rates (single-process probe) — using the "
                "legacy default"
            )
            spec = ""
        if spec:
            wires = tuple(w.strip() for w in spec.split(","))
            if len(wires) == k:
                return wires
            self.logger.warning(
                f"grad_comm_wires={spec!r} has {len(wires)} entries but "
                f"the resolved tree has {k} levels — using the legacy "
                "default"
            )
        return (cfg.grad_comm_wire,) + ("fp32",) * (k - 1)

    def _compute_comm_sig(self) -> tuple:
        """AOT-key / plan-layout signature of the combine structure (see the
        __init__ comment) — recomputed on every fleet change: an elastic
        re-shard can re-derive the tree or fall back to flat, and two
        structures lower different programs that must never resolve to each
        other. The hier signature is the full tree with each hop's wire:
        one (name, size, wire) triple per level, outermost first."""
        return (
            (
                ("hier",)
                + tuple(
                    (name, size, wire)
                    for (name, size), wire in zip(
                        self._topo_tree.levels, self._grad_comm_wires
                    )
                )
                if self.grad_comm == "hier"
                else ("flat",)
            )
            + (("zero1",) if self.cfg.shard_update else ())
            # many-stream tenancy: the job id namespaces every comm-sig-keyed
            # executable per tenant (the _aot_gen component stays per-trainer)
            + ((("job", self.job_id),) if self.job_id is not None else ())
        )

    def _quiesce_pipeline(self) -> None:
        """Drain the concurrent readers of the topology fields before a
        mesh/world rebuild (G019 quiesce discipline). The window transfer
        pipeline's gather/stage threads read ``mesh``/``topology``/
        ``active_ranks``; "closed by program order" was the sanction for
        the unlocked writes below, and this turns that program-order
        argument into an enforced drain: if an abandoned epoch left its
        pipeline live (exception paths, mid-epoch preemption), close it —
        ``close`` joins the pool and is idempotent against the context
        manager's own exit."""
        pipe = getattr(self, "_live_pipeline", None)
        if pipe is not None:
            self._live_pipeline = None
            pipe.close()

    def _reshard_world(self, active: List[int]) -> None:
        """Point the engine at a new active fleet: compact controller
        vectors, survivor topology/mesh, a fresh StepLibrary against it,
        and every mesh/topology-keyed cache invalidated. The caller re-
        places the TrainState afterwards (`_state_from_host`). Multi-host:
        called AFTER a re-rendezvous re-initialized ``jax.distributed``
        over the survivor roster — ``jax.devices()`` is already the new
        global fleet and ``proc_id``/``n_proc``/``_proc_roster`` its
        compact shape; each surviving process keeps its own worker slice
        (loss is process-granular across hosts)."""
        self._quiesce_pipeline()
        cfg = self.cfg
        self.active_ranks = sorted(int(r) for r in active)
        # topology fields below are read by the pipeline's gather/stage
        # threads (G012 would flag the unlocked cross-thread writes); the
        # _quiesce_pipeline() drain above guarantees no staging thread is
        # alive across these statements (G019) — previously this relied on
        # the run loop having drained the epoch, unasserted
        self.world_size = len(self.active_ranks)  # graftlint: disable=G012
        if self.world_size < 1:
            raise RuntimeError("elastic: no surviving workers")
        local_devices = sorted(jax.local_devices(), key=lambda d: d.id)
        ids_global = cfg.worker_device_ids(len(local_devices))
        if self.n_proc > 1:
            # my workers: the slice of ORIGINAL ranks this process owned at
            # gen 0 (whole peers die; survivors keep their full slice).
            # Compact runtime ranks are positions in sorted(active), and my
            # originals are contiguous there — roster order (sorted original
            # ids) matches original-rank order by construction.
            mine = [
                r for r in self.active_ranks
                if self._proc_of_rank(r) == self._orig_proc_id
            ]
            if not mine:
                raise RuntimeError(
                    "elastic: this process owns no surviving workers"
                )
            self.ws_local = len(mine)  # graftlint: disable=G012
            self.rank_lo = self.active_ranks.index(mine[0])  # graftlint: disable=G012
            ids_local = [ids_global[r] for r in mine]
            used = sorted(set(ids_local))
            self.topology = WorkerTopology.build(
                self.ws_local,
                [local_devices[i] for i in used],
                [used.index(i) for i in ids_local],
            )
            # global combine mesh: every surviving process contributes the
            # same local device ordinals (symmetry validated at __init__),
            # ordered by the NEW process index — which is roster order
            by_proc: Dict[int, list] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            mesh_devices = []
            for p in sorted(by_proc):
                proc_devs = sorted(by_proc[p], key=lambda d: d.id)
                mesh_devices.extend(proc_devs[i] for i in used)
        else:
            self.ws_local = self.world_size  # graftlint: disable=G012
            self.rank_lo = 0  # graftlint: disable=G012
            ids_active = [ids_global[r] for r in self.active_ranks]
            used = sorted(set(ids_active))
            self.topology = WorkerTopology.build(
                self.world_size,
                [local_devices[i] for i in used],
                [used.index(i) for i in ids_active],
            )
            mesh_devices = list(self.topology.devices)
        # hier×elastic (ISSUE 14 satellite, tree-aware since ISSUE 17):
        # re-derive the topology tree over the survivors so elastic runs
        # KEEP whatever hierarchy remains — TopologyTree.restrict walks
        # the previous tree keeping every level that still divides the
        # fleet (the old all-or-nothing equal-host-blocks-or-flat
        # fallback is the degenerate case); on real multi-host fleets the
        # host level re-derives from the SURVIVING process topology
        # instead (the host axis must align with real process blocks).
        # Otherwise fall back to the flat combine — logged once, and the
        # re-keyed _comm_sig makes the structure change a new
        # compiled-program universe (no hier executable can resolve
        # against a flat world).
        prev_comm = self.grad_comm
        prev_tree = self._topo_tree
        self.grad_comm = "flat"
        self._hier_hosts = 0
        self._topo_tree = None
        if cfg.grad_comm == "hier" and not self._probe_gated_flat:
            from dynamic_load_balance_distributeddnn_tpu.parallel.topology import (
                TopologyTree,
            )

            if self.n_proc > 1 and not cfg.hier_levels:
                tree = TopologyTree.from_process_topology(
                    mesh_devices, requested=0
                )
            elif prev_tree is not None:
                tree = prev_tree.restrict(len(mesh_devices))
            else:
                tree, _ = self._resolve_topology_tree(mesh_devices)
            if tree is not None:
                self.grad_comm = "hier"
                self._topo_tree = tree
                self._hier_hosts = tree.sizes[0]
            else:
                self.logger.warning(
                    f"grad_comm=hier: the {len(mesh_devices)}-device survivor "
                    "fleet keeps no topology-tree structure (fewer than two "
                    "divisible levels) — falling back to the flat combine"
                    + (" (was hier)" if prev_comm == "hier" else "")
                )
        if self.grad_comm == "hier":
            from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
                tree_mesh,
            )

            self.mesh = tree_mesh(
                mesh_devices, self._topo_tree.names, self._topo_tree.sizes
            )
        else:
            self.mesh = data_mesh(mesh_devices)
        self.n_dev = len(mesh_devices)
        self._grad_comm_wires = self._resolve_wires()
        self._comm_sig = self._compute_comm_sig()
        if cfg.shard_update:
            # the 1/N optimizer chunk layout is sized by the DEVICE count:
            # a survivor fleet re-pads the flat state to its own multiple
            # (the _state_from_host re-chunk consumes this)
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                zero1_padded_size,
            )

            self._zero1_padded = zero1_padded_size(
                self.state.params, self.n_dev
            )
        self._build_steps()
        # mesh/topology-keyed caches: all stale the moment the fleet changed
        self._aot_gen += 1
        self._aot_view_specs = {}
        self._peer_scan_cache = None
        self._cache_repl = None
        self._cache_dev = {}
        self._eval_chunk_cache = None
        self._fused_sync_per_step = None
        self._flops_per_padded_example = None
        self._seen_plan_layouts = set()
        self._superstep_keys = set()
        self._sync_per_step = 0.0
        self.timekeeper = TimeKeeper(self.world_size)
        # world-size change: the share trajectory restarts (the predictor
        # would restart its velocity track on shape change anyway; a fresh
        # instance makes it explicit)
        self._share_predictor = ShareTrajectoryPredictor()
        # the online controller's per-worker rate track and device-group
        # step-time model are fleet-shaped: rebuilt lazily against the
        # survivor topology (ledger restarts — a new fleet, a new account;
        # executed-switch events stay in self._rebalance_events). The
        # recorder's per-epoch switch-delta baseline restarts with it, or
        # the first post-reshard epoch would record a negative delta.
        self._rebalance_ctl = None
        self.obs.controller = None  # registry slot follows the rebuild
        self._switches_last = 0
        # warm-started runs re-warm the NEW world size's compile universe:
        # _maybe_warm (next epoch entry) submits the gen's ladder to the
        # AOT service and the pre-wall drain keeps the compiles out of
        # every timed epoch — zero steady-state foreground compiles
        # survive the re-solve
        self._warmed = False

    def _recover(self, lost: List[int], epoch: int) -> None:
        """Confirmed worker loss: drain, flush checkpoints, re-solve the
        partition over the survivors, re-shard, re-place the snapshot
        state, and hand control back to the run loop (which re-runs the
        epoch). Collective/compile edges are wrapped in bounded
        exponential-backoff retries — a re-shard can race the dying
        runtime's teardown."""
        if self.n_proc > 1:
            if self._rdzv is None:
                raise RuntimeError(
                    f"elastic: worker(s) {lost} lost but no rendezvous "
                    "channel is armed (set DBS_PEER_HB_DIR and bring the "
                    "world up through rendezvous.elastic_initialize) — "
                    "aborting for resume-from-checkpoint (see README "
                    "'Fault tolerance')"
                )
            if all(self._proc_of_rank(r) == self._orig_proc_id for r in lost):
                # a loss confined to THIS process's own workers: peers see
                # a live beacon and no claim, so they would never enter the
                # rendezvous — proposing one just wedges the fleet for the
                # full phase timeout. Abort with the honest verdict
                # instead (resume-from-checkpoint restarts the fleet).
                raise RuntimeError(
                    f"elastic: worker(s) {sorted(lost)} on THIS process "
                    "confirmed lost in a multi-process world — a "
                    "single-process worker shrink cannot change the "
                    "global mesh and no peer would join a rendezvous for "
                    "it; aborting for resume-from-checkpoint"
                )
            return self._recover_multihost(lost, epoch)
        cfg = self.cfg
        t0 = self._detect_t0 or time.perf_counter()
        snap = self._epoch_snap
        with self._trace.span("recover", cat="recover"):
            self._trace.instant(
                "worker_lost", cat="elastic",
                args={"ranks": sorted(int(r) for r in lost), "epoch": int(epoch)},
            )
            self.logger.warning(
                f"elastic: worker(s) {sorted(lost)} confirmed lost at epoch "
                f"{epoch} — re-solving over survivors"
            )
            if cfg.ckpt_dir:
                # durable BEFORE the re-shard mutates the fleet: a crash
                # mid-recovery must leave a consistent checkpoint behind
                from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
                    flush_checkpoints,
                )

                flush_checkpoints(cfg.ckpt_dir)
                heartbeat()
            for r in lost:
                self.health.mark_down(r)
            prev_active = snap["active"] if snap else list(self.active_ranks)
            survivors = [r for r in prev_active if r not in set(lost)]
            keep = [i for i, r in enumerate(prev_active) if r not in set(lost)]
            retry_transient(
                lambda: self._reshard_world(survivors),
                logger=self.logger,
                desc="survivor re-shard",
                tick=heartbeat,
            )
            if snap is not None:
                # restore the epoch-start controller state, restricted to
                # survivors: shares renormalize (the re-solve seed), cost
                # anchors carry over (they are per-worker, not per-fleet)
                shares = snap["shares"][keep]
                self.shares = shares / max(shares.sum(), 1e-12)
                self.node_times = snap["node_times"][keep]
                self.per_example_cost = snap["per_example_cost"][keep]
                self.total_wallclock = snap["total_wallclock"]
                self.total_probe_s = snap["total_probe_s"]
                self.state = retry_transient(
                    lambda: self._state_from_host(snap["state"]),
                    logger=self.logger,
                    desc="state re-placement",
                    tick=heartbeat,
                )
            else:  # driven epoch-by-epoch without run(): best effort
                sel = [i for i, r in enumerate(prev_active) if r in survivors]
                shares = self.shares[sel]
                self.shares = shares / max(shares.sum(), 1e-12)
                self.node_times = self.node_times[sel]
                self.per_example_cost = self.per_example_cost[sel]
                self.state = self._state_from_host(self._state_to_host(self.state))
            self._fix_comm_residual()
            jax.block_until_ready(self.state.params)
            heartbeat()  # survivor mesh answered — recovery pipeline is live
            self._recoveries += 1
            self._detect_t0 = None
            dt = time.perf_counter() - t0
            ev = {
                "epoch": int(epoch),
                "lost": sorted(int(r) for r in lost),
                "world_size": int(self.world_size),
                "detect_to_resume_s": round(dt, 4),
            }
            self._elastic_events.append(ev)
            self.recorder.meta["elastic_events"] = self._elastic_events
            self._trace.instant("recovered", cat="elastic", args=dict(ev))
            self.logger.info(
                f"elastic: recovered over {self.world_size} survivors "
                f"{self.active_ranks} in {dt:.3f}s (detection to resumed "
                "training); epoch re-runs from the consistent snapshot"
            )

    # ------------------------------------------ multi-host re-rendezvous
    # (ISSUE 14). jax cannot shrink a live multi-host mesh, so surviving a
    # peer-PROCESS loss means rebuilding the world: survivors reach roster
    # consensus through the heartbeat-file directory (propose -> agree),
    # tear down ``jax.distributed`` (retiring the old runtime — see
    # runtime/rendezvous.py for why the retired objects deliberately leak),
    # re-initialize over the survivor set on a fresh coordinator port
    # (barrier -> establish), re-shard topology/mesh/StepLibrary onto the
    # survivor fleet, restore from the flushed checkpoint re-placed onto the
    # survivor mesh, and re-run the interrupted epoch — bitwise-identical to
    # a fresh reduced-world run from the same checkpoint. A failed or
    # timed-out rendezvous degrades to the pre-ISSUE-14 abort-and-resume
    # ladder, logged with the phase that died.

    def _fix_comm_residual(self) -> None:
        """Re-base the error-feedback residual on the CURRENT combine
        structure after a fleet change: the old world's ``[n_dev, chunk]``
        rows are meaningless on a different device count (and their stale
        shape would fork every state-fed executable signature), so a hier
        survivor mesh re-attaches zeros — error feedback re-accumulates
        within an epoch — and a re-factor that fell back to flat drops the
        leaf entirely."""
        st = self.state
        if getattr(st, "comm_residual", None) is None and self.grad_comm != "hier":
            return
        st = st.replace(comm_residual=None)
        if self.grad_comm == "hier":
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                attach_comm_residual,
            )

            st = attach_comm_residual(
                st, self.mesh,
                pad_multiple=self.n_dev if self.cfg.shard_update else 0,
            )
        self.state = st

    def _adopt_controller_vectors(
        self, base_active, shares, node_times, cost=None
    ) -> None:
        """Seed the compact controller vectors for the CURRENT active fleet
        from a PREVIOUS fleet's vectors (checkpoint sidecar or epoch
        snapshot): survivors keep their entries, newcomers fill with the
        survivor mean, shares renormalize. Pure function of
        (source vectors, rosters), so every surviving process — and a
        freshly joined one reading the same sidecar — derives the identical
        seed (the replicated-controller contract across a fleet change)."""
        base = [int(r) for r in base_active]
        sel = {r: i for i, r in enumerate(base)}

        def fill(vec, fallback):
            src = np.asarray(vec, dtype=np.float64)
            out = np.full(self.world_size, np.nan)
            for i, r in enumerate(self.active_ranks):
                if r in sel and sel[r] < len(src):
                    out[i] = src[sel[r]]
            mean = np.nanmean(out) if np.isfinite(out).any() else fallback
            out[~np.isfinite(out)] = mean
            return out

        sh = fill(shares, 1.0 / max(self.world_size, 1))
        self.shares = sh / max(sh.sum(), 1e-12)
        self.node_times = np.maximum(fill(node_times, 1.0), 1e-9)
        if cost is not None:
            self.per_example_cost = fill(cost, np.nan)
        else:
            self.per_example_cost = np.full(self.world_size, np.nan)

    def _mh_rdzv_failed(self, e: Exception, epoch: int) -> None:
        """A rendezvous phase died (hard timeout, eviction, connect
        failure): degrade to the pre-ISSUE-14 abort-and-resume ladder —
        loudly. The beacon file is tagged with the failed phase so peers
        (and the launching harness) diagnose the abort instead of reading a
        silent freeze, the event lands in the recorder meta, and the raise
        unwinds the run for the outer retry/resume loop."""
        phase = getattr(e, "phase", "unknown")
        msg = (
            f"elastic: multi-host re-rendezvous FAILED in phase "
            f"'{phase}' ({e}) — degrading to abort-and-resume-from-"
            "checkpoint"
        )
        self.logger.error(msg)
        self._trace.instant(
            "rdzv_failed", cat="rdzv",
            args={"phase": str(phase), "epoch": int(epoch)},
        )
        if self._hb_beacon_path:
            from dynamic_load_balance_distributeddnn_tpu.runtime.watchdog import (
                tag_exit_reason,
            )

            tag_exit_reason(
                self._hb_beacon_path, f"rendezvous failed: {phase}"
            )
        self._elastic_events.append(
            {"epoch": int(epoch), "rdzv_failed_phase": str(phase)}
        )
        self.recorder.meta["elastic_events"] = self._elastic_events
        raise RuntimeError(msg) from e

    def _recover_multihost(self, lost: List[int], epoch: int) -> None:
        """Confirmed PEER-PROCESS loss on the multi-host tier: publish the
        loss verdict (peers with lagging beacon scans adopt it instead of
        dispatching another collective at the dead process), then run the
        epoch-boundary re-rendezvous over the survivors."""
        cfg = self.cfg
        if cfg.shard_update:
            # recorded exclusion: re-chunking the 1/N optimizer state across
            # a multi-host re-rendezvous needs a sharded process-local
            # restore path the engine does not build yet (ROADMAP)
            raise RuntimeError(
                f"elastic: worker(s) {sorted(lost)} lost but multi-host "
                "re-rendezvous does not compose with --shard_update yet — "
                "aborting for resume-from-checkpoint"
            )
        dead_procs = sorted(
            {self._proc_of_rank(r) for r in lost}
            - {self._orig_proc_id}
        )
        self.logger.warning(
            f"elastic: worker(s) {sorted(lost)} (peer process(es) "
            f"{dead_procs}) confirmed lost at epoch {epoch} — "
            "re-rendezvousing over survivors"
        )
        self._trace.instant(
            "peer_lost", cat="elastic",
            args={
                "ranks": sorted(int(r) for r in lost),
                "procs": [int(p) for p in dead_procs],
                "epoch": int(epoch),
            },
        )
        for r in lost:
            self.health.mark_down(r)
        self._rdzv.claim_loss(dead_procs, epoch)
        survivors = [r for r in self.active_ranks if r not in set(lost)]
        self._mh_rerendezvous(epoch, survivors, lost=sorted(lost))

    def _maybe_regrow_multihost(self, epoch: int) -> None:
        """Epoch-boundary grow: (re)spawned processes that offered to join
        (``join_p*.json`` + a fresh beacon) are admitted by re-running the
        same rendezvous with them in the roster. Every process publishes its
        own ranks' carried per-example costs into the rendezvous probe
        exchange before the restore barrier, so newcomers seed at the
        equilibrium share of the exchanged costs (falling back to the
        sidecar-derived mean fill when the exchange is incomplete); their
        engine restores from the shared checkpoint and adopts the agreed
        fleet."""
        if self._rdzv is None:
            return
        alive = self._rdzv.alive_procs()
        joins = sorted(
            p
            for p in self._rdzv.pending_joins()
            if p in alive and p not in set(self._proc_roster)
        )
        if not joins:
            return
        if not self.cfg.ckpt_dir:
            # the joiner's ONLY state source is the shared checkpoint (the
            # survivors restore the same bytes so the grown world stays
            # replicated) — admitting one without a ckpt_dir would psum
            # fresh-init params against the trained ones, silently
            # diverging every process. Refuse loudly, once per epoch.
            self.logger.warning(
                f"elastic: process(es) {joins} offered to join at epoch "
                f"{epoch} but no --ckpt_dir is configured — a joiner "
                "cannot adopt the replicated state; refusing the grow"
            )
            return
        self.logger.info(
            f"elastic: process(es) {joins} offering to join at epoch "
            f"{epoch} — re-rendezvousing to grow the fleet"
        )
        active = sorted(
            set(self.active_ranks)
            | {r for p in joins for r in self._ranks_of_proc(p)}
        )
        self._mh_rerendezvous(epoch, active, joining=joins)
        for p in joins:
            self._rdzv.clear_join(p)

    def _mh_rerendezvous(
        self,
        epoch: int,
        target_active: List[int],
        lost: Sequence[int] = (),
        joining: Sequence[int] = (),
    ) -> None:
        """The shared shrink/grow spine: drain -> flush -> agree -> retire
        -> establish -> re-shard -> restore -> re-seed. Every blocking phase
        is armored (bounded timeouts in the state machine, retry_transient
        on the collective edges, heartbeat ticks throughout), and a failed
        phase degrades through :meth:`_mh_rdzv_failed` instead of hanging."""
        from dynamic_load_balance_distributeddnn_tpu.runtime import (
            rendezvous as rdzv,
        )
        from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
            flush_checkpoints,
            materialize,
            restore_checkpoint,
        )

        cfg = self.cfg
        t0 = self._detect_t0 or time.perf_counter()
        with self._trace.span("recover_mh", cat="recover"):
            # 1. durable checkpoint, manager CLOSED: the cached orbax
            # manager's async machinery holds old-world device arrays and
            # must drain and die before the runtime is retired under it
            if cfg.ckpt_dir:
                flush_checkpoints(cfg.ckpt_dir, close=True)
                heartbeat()
            # 2. host-side recovery source. Shrink resumes the interrupted
            # epoch from its START snapshot (== the flushed checkpoint);
            # grow runs at a boundary, so the LIVE state is the source.
            snap = self._epoch_snap if not joining else None
            if snap is not None:
                host_state = snap["state"]
                prev_active = list(snap["active"])
                src = {
                    "shares": snap["shares"],
                    "node_times": snap["node_times"],
                    "cost": snap["per_example_cost"],
                }
                self.total_wallclock = snap["total_wallclock"]
                self.total_probe_s = snap["total_probe_s"]
            else:
                host_state = self._state_to_host(self.state)
                prev_active = list(self.active_ranks)
                src = {
                    "shares": self.shares.copy(),
                    "node_times": self.node_times.copy(),
                    "cost": self.per_example_cost.copy(),
                }
            # 3. roster consensus (propose -> agree): bounded rounds, hard
            # per-phase timeout, watchdog ticks — a wedged peer times the
            # rendezvous out instead of hanging it
            try:
                agreement = self._rdzv.agree(
                    lambda: (
                        self._rdzv.alive_procs() - self._rdzv.claimed_losses()
                    ),
                    epoch,
                )
            except rdzv.RendezvousError as e:
                self._mh_rdzv_failed(e, epoch)
            roster = list(agreement.roster)
            # the agreed roster is authoritative: drop ranks whose process
            # died DURING the rendezvous, admit one that raced its join in
            active = [
                r for r in target_active if self._proc_of_rank(r) in set(roster)
            ]
            for p in roster:
                if all(self._proc_of_rank(r) != p for r in active):
                    active.extend(self._ranks_of_proc(p))
            active = sorted(set(active))
            # 4. quiesce every device-holding surface, then retire the old
            # runtime (client/service leak deliberately — rendezvous.py)
            if self._aot is not None:
                try:
                    self._aot.close(wait=True)
                except Exception as e:  # noqa: BLE001 — a dying pool must not block recovery
                    self.logger.warning(
                        f"elastic: AOT service close failed ({e!r}) — "
                        "continuing recovery"
                    )
                self._aot = None
            self.state = None
            self._cache_repl = None
            self._cache_dev = {}
            self._epoch_snap = None  # re-snapshotted when the epoch re-runs
            # force the dying world's wedged collectives to resolve BEFORE
            # the new world exists — unresolved, they poison the next
            # backend's launches through XLA:CPU's process-global
            # rendezvous map (see rendezvous.drain_collective_chain)
            rdzv.drain_collective_chain(logger=self.logger, tick=heartbeat)
            rdzv.retire_runtime()
            # 5. barrier on every survivor's teardown, leader brings up the
            # new coordination service, everyone connects
            try:
                # the payload is for JOINERS (join_elastic_world returns it);
                # survivors are replicated-deterministic and ignore it
                self._rdzv.establish(
                    agreement,
                    payload=(
                        {"epoch": int(agreement.epoch), "active": active}
                        if agreement.leader
                        else None
                    ),
                )
            except rdzv.RendezvousError as e:
                self._mh_rdzv_failed(e, epoch)
            # 6. adopt the new world shape; rebuild the compile service and
            # every topology/mesh surface against it. The whole rebuild tail
            # runs under a bounded retry: the dead world's wedged collective
            # resolves at an ARBITRARY later moment (gloo socket teardown is
            # async), and whatever multi-device dispatch is in flight right
            # then inherits its error — the canary (quarantine_runtime)
            # catches an inheritance that already landed, the final
            # block_until_ready catches one that landed mid-rebuild, and a
            # poisoned attempt tears the backend down and rebuilds from
            # scratch (cheap: ~0.3s on the CPU tier). With MULTIPLE
            # survivors each attempt is a voted round (ISSUE 18:
            # rdzv.rebuild_vote / rebuild_settled): every survivor
            # publishes its verdict and the round only stands when all
            # succeeded — retry counts can no longer diverge across
            # processes, so attempt N's collectives always pair N-to-N.
            self.n_proc = len(roster)
            self.proc_id = agreement.rank
            self._proc_roster = roster
            if joining:
                # grow-path probe exchange (ISSUE 17): publish OUR ranks'
                # carried costs now — BEFORE the restore barrier both sides
                # synchronize on — so every member's publication is on disk
                # by the time anyone collects (step 8 here; the joiner's
                # _maybe_restore publishes its measured costs symmetrically)
                own_costs: Dict[int, float] = {}
                for r in self._ranks_of_proc(self._orig_proc_id):
                    if r in prev_active:
                        own_costs[r] = float(
                            np.asarray(src["cost"])[prev_active.index(r)]
                        )
                self._publish_probe_costs(own_costs)
            restored_from = "epoch snapshot"
            ctl = None
            rebuild_err: Optional[Exception] = None
            # the rebuild-vote electorate: survivors only — joiners enter
            # through join_elastic_world after the survivor world settles
            survivors = [p for p in roster if p not in set(joining)]
            for attempt in range(5):
                try:
                    rdzv.quarantine_runtime(logger=self.logger, tick=heartbeat)
                except rdzv.RendezvousError as e:
                    self._mh_rdzv_failed(e, epoch)
                # a silent async failure in the preceding stage surfaces at
                # the canary instead of poisoning the next stage's launches
                # (local devices only — see rendezvous.local_canary_launch;
                # on the GROW path the joiner runs no matching canary, so a
                # global-mesh put's hidden gloo broadcast would pair with
                # the joiner's first real collective)
                _launch_canary = rdzv.local_canary_launch

                stage = "reshard"
                try:
                    self._reshard_world(active)
                    _launch_canary()
                    # 7. restore: the flushed checkpoint re-placed onto the
                    # survivor mesh (falling back to the epoch-start
                    # snapshot when no checkpoint directory is configured or
                    # the latest step is not the interrupted epoch's
                    # boundary)
                    stage = "template"
                    template = self._state_from_host(host_state)
                    materialize(template)
                    _launch_canary()
                    stage = "restore"
                    self.state = template
                    restored_from = "epoch snapshot"
                    ctl = None
                    # the GROW path restores from the flushed checkpoint
                    # too (identical bytes to the live boundary state): the
                    # JOINER's only state source is that checkpoint, and its
                    # engine restores through the same restore_checkpoint
                    # call — orbax's manager-create/restore syncs are global
                    # collectives, so the survivor must run the SAME
                    # sequence at the same program point or the joiner's
                    # syncs pair with the wrong launch (see _launch_canary)
                    if cfg.ckpt_dir:
                        got = restore_checkpoint(cfg.ckpt_dir, template)
                        if got is not None and int(got[0]) == epoch - 1:
                            self.state, ctl = got[1], got[2]
                            restored_from = f"checkpoint[{int(got[0])}]"
                        elif got is not None:
                            self.logger.warning(
                                f"elastic: latest checkpoint is epoch "
                                f"{got[0]}, not {epoch - 1} — resuming from "
                                "the epoch-start snapshot instead"
                            )
                    _launch_canary()
                    stage = "fix-residual"
                    self._fix_comm_residual()
                    stage = "materialize"
                    # materialize EVERYTHING state-shaped before declaring
                    # the world live — a poisoned buffer must surface here,
                    # inside the retry scope, not an epoch later
                    materialize(self.state)
                    rebuild_err = None
                except Exception as e:  # noqa: BLE001 — poisoned-world rebuild
                    rebuild_err = e
                    self.state = None
                    self._cache_repl = None
                    self._cache_dev = {}
                    self.logger.warning(
                        f"elastic: survivor-world rebuild attempt "
                        f"{attempt + 1} inherited the dead world's dispatch "
                        f"chain at stage '{stage}' ({str(e)[:160]}) — "
                        "rebuilding the backend"
                    )
                    heartbeat()
                    rdzv.reset_backend()
                    # the stuck global-map entries evict when the dead
                    # ops' threads unwind — observed within ~10s; back off
                    # long enough to land past that instead of burning
                    # attempts inside the window
                    time.sleep(1.0 * (attempt + 1))
                # Multi-survivor rebuild coherence: each attempt is a voted
                # round — it stands only when EVERY survivor's rebuild
                # succeeded. Otherwise all of them (the locally-successful
                # ones included) tear down and retry together, so attempt
                # N's collectives always pair N-to-N instead of a fast
                # survivor's attempt-1 ops meeting a slow peer's attempt-2.
                # Joiners don't vote: they enter via join_elastic_world
                # only after the survivor world settles.
                if len(survivors) > 1:
                    round_ok = False
                    try:
                        self._rdzv.rebuild_vote(
                            attempt, ok=rebuild_err is None
                        )
                        round_ok = self._rdzv.rebuild_settled(
                            survivors, attempt
                        )
                    except rdzv.RendezvousError as e:
                        # a peer that exhausted its attempts aborts without
                        # voting — its silence times this wait out, and the
                        # remaining survivors abort coherently with it
                        self._mh_rdzv_failed(e, epoch)
                    if not round_ok and rebuild_err is None:
                        rebuild_err = rdzv.RendezvousError(
                            "world rebuild",
                            f"attempt {attempt + 1} voted down by a peer",
                        )
                        self.state = None
                        self._cache_repl = None
                        self._cache_dev = {}
                        self.logger.warning(
                            f"elastic: rebuild attempt {attempt + 1} "
                            "succeeded locally but a peer voted it down — "
                            "rebuilding in lockstep"
                        )
                        heartbeat()
                        rdzv.reset_backend()
                        time.sleep(1.0 * (attempt + 1))
                if rebuild_err is None:
                    break
            if rebuild_err is not None:
                self._mh_rdzv_failed(
                    rdzv.RendezvousError(
                        "world rebuild", f"never settled: {rebuild_err!r}"
                    ),
                    epoch,
                )
            self._build_aot_service()
            # 8. controller seeding: sidecar vectors when the checkpoint was
            # the source (identical bytes on every process), else the
            # replicated snapshot — restricted to survivors / mean-filled
            # for joiners, shares renormalized
            if (
                ctl
                and "shares" in ctl
                and ctl.get("active_ranks") is not None
                and len(ctl["shares"]) == len(ctl["active_ranks"])
            ):
                self._adopt_controller_vectors(
                    ctl["active_ranks"], ctl["shares"],
                    ctl.get("node_times", ctl["shares"]),
                )
            else:
                self._adopt_controller_vectors(
                    prev_active, src["shares"], src["node_times"], src["cost"]
                )
            # grow path: upgrade the mean-fill seed to the equilibrium split
            # over the exchanged per-worker costs (identical on every
            # process when the exchange completes; the mean-fill above
            # stands — identically everywhere — when it does not)
            if joining:
                self._collect_probe_seed()
            for p in joining:
                for r in self._ranks_of_proc(p):
                    self.health.readmit(r)
            jax.block_until_ready(self.state.params)
            heartbeat()  # survivor world answered — the new mesh is live
            # a rejoined (or previously fired-on) peer needs a fresh watch
            if joining and getattr(self, "_peer_watch", None) is not None:
                self._peer_watch([f"proc{p}" for p in joining])
            self._recoveries += 1
            self._detect_t0 = None
            dt = time.perf_counter() - t0
            ev = {
                "epoch": int(epoch),
                "world_size": int(self.world_size),
                "rdzv_gen": int(agreement.gen),
                "roster": [int(p) for p in roster],
                "detect_to_resume_s": round(dt, 4),
                "restored_from": restored_from,
            }
            if lost:
                ev["lost"] = [int(r) for r in lost]
            if joining:
                ev["readmitted"] = [
                    int(r) for p in joining for r in self._ranks_of_proc(p)
                ]
            self._elastic_events.append(ev)
            self.recorder.meta["elastic_events"] = self._elastic_events
            self._trace.instant("mh_recovered", cat="elastic", args=dict(ev))
            self.logger.info(
                f"elastic: re-rendezvous g{agreement.gen} complete — "
                f"{self.world_size} workers over {self.n_proc} process(es) "
                f"{roster}, state from {restored_from}, {dt:.3f}s detection "
                "to resumed training"
            )

    def _attribute_collective_failure(
        self, e: Exception, epoch: int
    ) -> Optional[List[int]]:
        """A mid-epoch exception on the multi-host elastic tier is usually
        the COLLECTIVE dying with a peer (the gloo/XLA surface errors on the
        closed socket long before the beacon goes stale). Hold the epoch for
        up to the staleness window and let the beacon/claim verdict decide:
        returns the lost ranks to recover over, or None to re-raise (a real
        error, not a fleet change)."""
        if self.cfg.elastic != "on" or self.n_proc == 1 or self._rdzv is None:
            return None
        if self._detect_t0 is None:
            self._detect_t0 = time.perf_counter()
        stale_s = float(os.environ.get("DBS_PEER_HB_STALE_S", "10.0"))
        self.logger.warning(
            f"elastic: epoch {epoch} dispatch failed ({e!r}) — waiting up "
            f"to {stale_s + 3.0:.0f}s for a peer-liveness verdict before "
            "treating it as fatal"
        )
        deadline = time.monotonic() + stale_s + 3.0
        while time.monotonic() < deadline:
            down = self._scan_peer_heartbeats(force=True)
            lost = sorted(r for r in self.active_ranks if r in down)
            if lost:
                return lost
            heartbeat()  # the wait is deliberate, not a stall
            time.sleep(0.25)
        return None

    def _maybe_readmit(self, epoch: int) -> None:
        """Epoch-boundary readmission: workers whose rejoin boundary is
        ``epoch`` (injector schedule) or that resumed signalling (health
        RECOVERING) re-enter the fleet with a PROBE-SEEDED share — one
        standalone step on the readmitted worker anchors its per-example
        cost, and the share vector seeds at the solver's equilibrium
        estimate (share_i ∝ 1/c_i) so the next rebalance starts near the
        fixed point instead of re-converging from uniform."""
        cfg = self.cfg
        if cfg.elastic != "on" or cfg.elastic_readmit != "epoch":
            return
        if self._rdzv is not None and self._n_proc0 > 1:
            # multi-host growth is process-granular: a (re)spawned process
            # offers a join file and the whole fleet re-rendezvouses. Keyed
            # by the ORIGINAL fleet shape — a world shrunk to one surviving
            # process still regrows through the rendezvous channel, never
            # through local virtual-worker readmission
            self._maybe_regrow_multihost(epoch)
            return
        rejoin: set = set(self.health.recovering())
        rejoining = getattr(self.injector, "rejoining", None)
        if rejoining is not None:
            rejoin |= set(rejoining(epoch))
        if self._n_proc0 > 1:
            # a multi-host fleet that SHRANK to one process still owns only
            # its own worker slice: a dead PEER's ranks must re-enter via a
            # process rejoin (join file + grow rendezvous), never as local
            # virtual workers — the post-shrink peer scan is empty, so
            # filter by original-process ownership explicitly
            rejoin = {
                r for r in rejoin
                if self._proc_of_rank(r) in set(self._proc_roster)
            }
        # re-check liveness AT the boundary: a candidate can have gone down
        # again since it flipped RECOVERING (chance-mode injectors schedule
        # overlapping outages) — readmitting a down worker burns a full
        # recovery cycle from the bounded budget for nothing
        down_now: set = set()
        down_workers = getattr(self.injector, "down_workers", None)
        if down_workers is not None:
            down_now = set(down_workers(float(epoch)))
        down_now |= self._scan_peer_heartbeats()
        cands = sorted(
            r
            for r in rejoin
            if r not in self.active_ranks
            and r not in down_now
            and 0 <= r < cfg.world_size
        )
        if not cands:
            return
        with self._trace.span("readmit", cat="recover"):
            self._trace.instant(
                "readmitted", cat="elastic",
                args={"ranks": [int(r) for r in cands], "epoch": int(epoch)},
            )
            self.logger.info(
                f"elastic: readmitting worker(s) {cands} at epoch {epoch}"
            )
            if cfg.ckpt_dir:
                from dynamic_load_balance_distributeddnn_tpu.train.checkpoint import (
                    flush_checkpoints,
                )

                flush_checkpoints(cfg.ckpt_dir)
                heartbeat()
            host_state = self._state_to_host(self.state)
            prev_active = list(self.active_ranks)
            prev_cost = self.per_example_cost.copy()
            new_active = sorted(prev_active + cands)
            retry_transient(
                lambda: self._reshard_world(new_active),
                logger=self.logger,
                desc="readmission re-shard",
                tick=heartbeat,
            )
            self.state = retry_transient(
                lambda: self._state_from_host(host_state),
                logger=self.logger,
                desc="state re-placement",
                tick=heartbeat,
            )
            self._fix_comm_residual()
            jax.block_until_ready(self.state.params)
            heartbeat()  # readmitted mesh answered
            # carry survivors' cost anchors to their new compact slots;
            # probe-seed the newcomers
            cost = np.full(self.world_size, np.nan)
            for i, r in enumerate(self.active_ranks):
                if r in prev_active:
                    cost[i] = prev_cost[prev_active.index(r)]
            fallback = (
                float(np.nanmean(prev_cost))
                if np.isfinite(prev_cost).any()
                else np.nan
            )
            for r in cands:
                i = self.active_ranks.index(r)
                # readmit the health slot FIRST: the probe below feeds
                # observe_latency, and readmit() resets the latency track —
                # the other order would wipe the anchor (and any SUSPECT
                # verdict on a degraded comeback) the probe just measured
                self.health.readmit(r)
                probed = self._probe_readmitted(i)
                cost[i] = probed if probed is not None else fallback
            self.per_example_cost = cost
            if np.isfinite(cost).all() and (cost > 0).all():
                self.shares = equilibrium_shares(cost)
                # t_i = c_i * p_i is the epoch-time model the solver's
                # update inverts; seeding times consistently with the
                # seeded shares makes the next rebalance a fixed point of
                # the probe-seeded estimate
                self.node_times = np.maximum(cost * self.shares, 1e-9)
            else:
                self.shares = initial_partition(self.world_size)
                self.node_times = np.ones(self.world_size, dtype=np.float64)
            ev = {
                "epoch": int(epoch),
                "readmitted": [int(r) for r in cands],
                "world_size": int(self.world_size),
                "seeded_shares": [round(float(s), 4) for s in self.shares],
            }
            self._elastic_events.append(ev)
            self.recorder.meta["elastic_events"] = self._elastic_events
            self.logger.info(
                f"elastic: fleet back to {self.world_size} workers "
                f"{self.active_ranks}; probe-seeded shares "
                f"{np.round(self.shares, 4).tolist()}"
            )

    def _probe_readmitted(self, compact_rank: int) -> Optional[float]:
        """Per-example cost of a readmitted worker from one standalone
        probe step on its device (2-rep min, blocking, untimed against any
        epoch wall — this runs at the boundary). None under a deterministic
        timing model (tests) or on probe failure (caller falls back to the
        survivor mean)."""
        if self.timing_model is not None:
            return None
        try:
            d = next(
                di
                for di, group in self.topology.groups.items()
                if compact_rank in group
            )
            dev = self.topology.devices[d]
            b = max(self.cfg.bucket, 1)
            x, y, w = self._dummy_batch(b)
            views = shard_views(self.state.params, self.topology.devices)
            args = (
                jax.device_put(x, dev),
                jax.device_put(y, dev),
                jax.device_put(w, dev),
                jax.device_put(jax.random.PRNGKey(0), dev),
                jax.device_put(jnp.int32(0), dev),
            )
            fn = self.steps.worker_step_first
            _, aux = fn(views[d], *args)
            jax.block_until_ready(aux)  # warm (compile) untimed
            heartbeat()
            dt = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                _, aux = fn(views[d], *args)
                jax.block_until_ready(aux)
                dt = min(dt, time.perf_counter() - t0)
            heartbeat()
            self.health.observe_latency(self.active_ranks[compact_rank], dt)
            return max(dt, 1e-9) / b
        except Exception as e:  # noqa: BLE001 — seeding is best-effort
            self.logger.warning(
                f"elastic: readmission probe failed ({e!r}) — seeding from "
                "the survivor mean"
            )
            return None

    def _probe_local_cost(self, r: int) -> Optional[float]:
        """Per-example cost of OUR OWN original worker rank ``r`` from one
        timed probe step on its LOCAL device — the multi-host twin of
        :meth:`_probe_readmitted`, restricted to process-local puts (a
        cross-process ``shard_views`` put would run a hidden collective the
        peers are not pairing). None under a deterministic timing model, on
        a non-local rank, or on probe failure — the probe exchange then
        publishes nothing for this rank and every process falls back
        identically."""
        if self.timing_model is not None:
            return None
        try:
            if r not in self.active_ranks:
                return None
            i = self.active_ranks.index(r)
            d = next(
                di
                for di, group in self.topology.groups.items()
                if i in group
            )
            dev = self.topology.devices[d]
            if dev.process_index != jax.process_index():
                return None
            b = max(self.cfg.bucket, 1)
            x, y, w = self._dummy_batch(b)
            params = jax.tree_util.tree_map(
                lambda p: jax.device_put(jax.device_get(p), dev),
                self.state.params,
            )
            args = (
                jax.device_put(x, dev),
                jax.device_put(y, dev),
                jax.device_put(w, dev),
                jax.device_put(jax.random.PRNGKey(0), dev),
                jax.device_put(jnp.int32(0), dev),
            )
            fn = self.steps.worker_step_first
            _, aux = fn(params, *args)
            jax.block_until_ready(aux)  # warm (compile) untimed
            heartbeat()
            dt = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                _, aux = fn(params, *args)
                jax.block_until_ready(aux)
                dt = min(dt, time.perf_counter() - t0)
            heartbeat()
            return max(dt, 1e-9) / b
        except Exception as e:  # noqa: BLE001 — seeding is best-effort
            self.logger.warning(
                f"elastic: local probe for rank {r} failed ({e!r}) — "
                "publishing no cost for it"
            )
            return None

    def _publish_probe_costs(self, costs: Dict[int, float]) -> None:
        """Publish this process's finite positive per-rank costs into the
        grow-rendezvous probe exchange (rendezvous.py ``publish_probe``);
        an empty publication is deliberate — peers must not wait on a
        process that measured nothing."""
        if self._rdzv is None:
            return
        self._rdzv.publish_probe(
            {
                int(r): float(c)
                for r, c in costs.items()
                if np.isfinite(c) and float(c) > 0.0
            }
        )

    def _collect_probe_seed(self) -> bool:
        """GROW-path share seeding (ISSUE 17): read every roster member's
        probe publication and seed the equilibrium split from the union —
        a pure function of the collected files, so survivors and the
        joiner derive IDENTICAL vectors (the replicated-controller
        contract the survivor-mean guess used to satisfy trivially).
        False — keep the sidecar-derived mean-fill seeding — when the
        exchange misses a member inside the bounded window or the union
        leaves any worker's cost unknown."""
        if self._rdzv is None:
            return False
        merged = self._rdzv.collect_probes(self._proc_roster)
        if merged is None:
            self.logger.warning(
                "elastic: probe exchange incomplete — keeping the "
                "survivor-mean seed for joined workers"
            )
            return False
        cost = np.full(self.world_size, np.nan)
        for i, r in enumerate(self.active_ranks):
            c = merged.get(int(r))
            if c is not None and np.isfinite(c) and c > 0.0:
                cost[i] = c
        if not np.isfinite(cost).all():
            return False
        self.per_example_cost = cost
        self.shares = equilibrium_shares(cost)
        # t_i = c_i * p_i: seed the times consistently with the shares so
        # the next rebalance is a fixed point of the exchanged estimate
        self.node_times = np.maximum(cost * self.shares, 1e-9)
        self.logger.info(
            "elastic: probe exchange seeded equilibrium shares "
            f"{np.round(self.shares, 4).tolist()} over "
            f"{len(self._proc_roster)} process(es)"
        )
        return True

    def _maybe_warm(self) -> None:
        if self.cfg.warm_start and not self._warmed:
            self._warmed = True
            with self._trace.span("warm", cat="warm"):
                if self._aot is not None:
                    self._submit_warm_aot()  # non-blocking; compiles overlap epoch 0
                else:
                    self._warm_shapes()

    def run_epoch(self, epoch: int) -> Dict[str, float]:
        """One epoch, wrapped in the graftscope epoch span: every event
        emitted inside (phases here, transfer/dispatch/compile spans on
        worker threads) is stamped with this epoch index, which is what the
        offline attribution (`graftscope summarize`) groups by."""
        tr = self._trace
        tr.set_epoch(epoch)
        try:
            with tr.span("epoch", cat=EPOCH_CAT):
                return self._run_epoch(epoch)
        finally:
            tr.set_epoch(None)

    def _plan_epoch(self, epoch: int):
        """The epoch's host-side control work — LR schedule, solver
        rebalance, plan build, fault-episode setup, probe scheduling —
        graftscope's ``plan_solve`` phase. Returns ``(plan, faults)``."""
        cfg = self.cfg
        lr = one_cycle_lr(
            cfg.learning_rate,
            epoch,
            cfg.epoch_size,
            enabled=cfg.one_cycle_policy,
            disable_enhancements=cfg.disable_enhancements,
        )
        if lr != self.state.learning_rate():
            self.state = self.state.with_learning_rate(lr)

        if cfg.dynamic_batch_size:
            max_share = min(1.0, cfg.capacity_factor / self.world_size)
            self.shares, batch_sizes = rebalance(
                self.node_times, self.shares, cfg.batch_size, max_share=max_share
            )
            if cfg.snap_to_bucket and self.SNAP_BATCHES:
                batch_sizes = quantize_batches(
                    batch_sizes, cfg.bucket, cfg.batch_size
                )
                self.shares = batch_sizes.astype(np.float64) / batch_sizes.sum()
            # feed the trajectory predictor the REALIZED (post-quantization)
            # shares — the quantity whose next value implies the next epoch's
            # dispatched shape tuple (scan-mode speculation)
            self._share_predictor.observe(self.shares)
            self.logger.info(
                f"Epoch {epoch}: adjusted shares to {np.round(self.shares, 4).tolist()}"
            )
        else:
            batch_sizes = integer_batch_split(self.shares, cfg.batch_size)

        plan = self._build_plan(epoch, batch_sizes)
        self.logger.info(
            f"Epoch {epoch}: batch sizes {plan.batch_sizes.tolist()}, "
            f"steps {plan.num_steps}"
        )

        # Injectors are sized/indexed by the ORIGINAL config ranks (their
        # schedules outlive fleet changes); the engine's runtime arrays are
        # compact over the active fleet. Scatter runtime vectors to original
        # rank space for the injector, select the active view back out.
        ctx = FaultContext(
            batch_sizes=self._scatter_full(plan.batch_sizes.astype(np.float64)),
            iter_cost_s=(
                (self._iter_cost_s or calibrate_iter_cost())
                if self._needs_iter_cost
                else None
            ),
            per_example_cost_s=(
                self._scatter_full(self.per_example_cost)
                if np.isfinite(self.per_example_cost).all()
                else None
            ),
        )
        # kept for the window controller's per-window faults_at queries
        # (re-derived per segment after a mid-epoch switch — _window_ctx)
        self._fault_ctx = ctx
        faults = self._faults_active(
            self.injector.epoch_faults(epoch, plan.num_steps, ctx)
        )
        self._probe_this_epoch = self._should_probe(epoch, plan, faults)
        return plan, faults

    def _scatter_full(self, vec: np.ndarray) -> np.ndarray:
        """Runtime-compact vector -> original-rank-indexed vector (zeros in
        lost workers' slots). Identity while the fleet is whole."""
        if len(self.active_ranks) == self.cfg.world_size:
            return vec
        full = np.zeros(self.cfg.world_size, dtype=np.float64)
        full[self.active_ranks] = np.asarray(vec, dtype=np.float64)
        return full

    def _faults_active(self, faults: EpochFaults) -> EpochFaults:
        """Original-rank EpochFaults -> the active fleet's compact view.
        Identity while the fleet is whole."""
        if len(self.active_ranks) == self.cfg.world_size:
            return faults
        sel = np.asarray(self.active_ranks)
        return EpochFaults(
            virtual_seconds=faults.virtual_seconds[sel],
            slow_iters_per_step=faults.slow_iters_per_step[sel],
            time_multipliers=faults.time_multipliers[sel],
        )

    def _dispatch_epoch(self, plan, faults: EpochFaults, epoch: int):
        """Path selection + the epoch's whole timed training region —
        graftscope's ``train`` phase. Returns ``(train_metrics,
        ran_elastic)``."""
        cfg = self.cfg
        # shard_update composes with the elastic dispatch since PR 13 (the
        # zero-1 combine twins); grad_accum stays fused-only, and the flat
        # compressed psum does too UNLESS the sharded update carries it
        # (the quantized reduce-scatter lives inside _zero1_update)
        if (
            cfg.grad_accum > 1 or (cfg.compress_grads and not cfg.shard_update)
        ) and not (self._can_use_fused(plan) or self._can_use_fused_dbs(plan)):
            raise RuntimeError(
                "grad_accum/compress_grads require a fused path "
                "(one worker per device); this plan fell back to the elastic "
                "path"
            )
        if cfg.rebalance == "window" and (
            self._can_use_fused(plan)
            or self._can_use_fused_dbs(plan)
            or self._can_use_packed(plan)
        ):
            # config validation already forbids fused_dbs, but packed/fused
            # selection depends on the runtime topology — without this the
            # controller would silently never engage (exactly the
            # contention topology window rebalancing targets)
            if not self._window_rebalance_logged:
                self._window_rebalance_logged = True
                self.logger.warning(
                    "rebalance=window needs the elastic dispatch paths but "
                    "this topology selected a fused/packed whole-epoch scan "
                    "— running at epoch cadence (pass --packed off to force "
                    "the elastic path)"
                )
        if self._can_use_fused(plan):
            return self._train_epoch_fused(plan, faults, epoch), False
        if self._can_use_fused_dbs(plan):
            return self._train_epoch_fused(plan, faults, epoch, dbs_probe=True), False
        if self._can_use_packed(plan):
            # probes still needed for the balancer signal and/or compute-mode
            # injection calibration — mirrors the elastic path's condition
            return (
                self._train_epoch_fused(
                    plan,
                    faults,
                    epoch,
                    dbs_probe=(
                        cfg.dynamic_batch_size
                        or self._needs_iter_cost
                        or self.timing_model is not None
                    ),
                    packed=True,
                ),
                False,
            )
        return self._train_epoch_elastic(plan, faults, epoch), True

    def _run_epoch(self, epoch: int) -> Dict[str, float]:
        tr = self._trace
        self._maybe_warm()  # callers driving epochs directly still warm first
        # Phase taxonomy (graftscope): plan_solve -> aot_drain -> train ->
        # speculate -> validate -> record. The phases tile this method, so
        # the trace attributes the epoch span's wall to named segments
        # (`graftscope summarize` renders the table; the bench asserts
        # >= 95% coverage on the CPU tier).
        with tr.span("plan_solve"):
            plan, faults = self._plan_epoch(epoch)
        # epoch-boundary liveness round: catches losses that landed outside
        # the elastic window checks (fused paths, inter-epoch gaps) before
        # any of this epoch's work dispatches
        self._check_health(epoch, 0.0)

        # Drain pending AOT jobs (the warm universe's tail, the previous
        # epoch's speculation) BEFORE the timed region: concurrent backend
        # compiles contend with the epoch's own compute on CPU-bound hosts
        # and would contaminate the A/B walls — the round-6 CPU insurance
        # arm measured the dbs-on arm 2.4x WORSE purely from this
        # contention. The drain wall lives exactly where the legacy warm
        # wall lived (outside every epoch wall); in steady state nothing is
        # pending and this is a no-op. The warm still overlaps everything
        # up to here — plan build, rebalance, fault setup — and speculative
        # jobs still overlap the epoch that submits them.
        if self._aot is not None and self._aot.pending():
            with tr.span("aot_drain"):
                self._aot_wait_needed(tuple(self._aot.keys()), epoch)

        t_epoch = time.perf_counter()
        with tr.span("train"):
            train_metrics, ran_elastic = self._dispatch_epoch(plan, faults, epoch)
        # The wall excludes probe/instrumentation cost on EVERY path: the
        # fused path already kept its probes out (probe_overhead); the
        # elastic path's standalone worker probes (dbs_probe_cost) were
        # inside the wall until round 4, which made re-probe epochs
        # (probe_every) 2x outliers in the dbs-on arm while the off arm's
        # shorter run never hit one — the BENCH_r03 on-arm 0.475s IQR
        # (VERDICT r3 weak #7). The reference's signal costs zero wall
        # (it times the epoch it already runs, dbs.py:226-250); excluding
        # ours keeps the A/B apples-to-apples, and the cost stays visible
        # as its own recorder series (probe_time) + the end-of-run total.
        probe_s = train_metrics.get("probe_overhead", 0.0) + train_metrics.get(
            "dbs_probe_cost", 0.0
        )
        epoch_wall = time.perf_counter() - t_epoch - probe_s
        self.total_wallclock += epoch_wall
        self.total_probe_s += probe_s

        # speculative adjacent-rung compiles ride the UNTIMED tail: they
        # overlap validation below and drain before the next timed region
        if ran_elastic:
            with tr.span("speculate"):
                self._maybe_speculate(plan)

        with tr.span("validate"):
            val_loss, accuracy = self.validate()

        with tr.span("record"):
            self._record_epoch(
                epoch, plan, faults, train_metrics, epoch_wall, probe_s,
                val_loss, accuracy,
            )
        return {
            "epoch_wall": epoch_wall,
            "loss": train_metrics["loss"],
            "val_loss": val_loss,
            "accuracy": accuracy,
        }

    def _record_epoch(
        self, epoch: int, plan, faults: EpochFaults, train_metrics,
        epoch_wall: float, probe_s: float, val_loss: float, accuracy: float,
    ) -> None:
        """Post-epoch bookkeeping — modeled times, the probe schedule, the
        cross-host time exchange, recorder extras and the recompile
        sentinel — graftscope's ``record`` phase."""
        cfg = self.cfg
        if (
            not self._probe_this_epoch
            and self.timing_model is None
            and (cfg.dynamic_batch_size or self._needs_iter_cost)
        ):
            # probe skipped: the solver runs on MODELED per-worker times
            self._model_compute_times(plan, faults)
        self._update_probe_schedule(epoch, plan, faults, epoch_wall, train_metrics)

        # multiplier-free compute vector: the window controller's fallback
        # rate source (node_times below bakes in the epoch-mean injection
        # multipliers — composing the instantaneous schedule on top of them
        # would double-count the injected load). Stored WITH the example
        # counts of the plan it was measured under: a boundary re-solve
        # changes per-worker counts, and normalizing old seconds by new
        # counts would skew the derived rates by the share ratio.
        self._clean_compute_s = self.timekeeper.compute_s.copy()
        self._clean_examples = np.array(
            [max(w.batch_size, 1) * max(w.steps, 1) for w in plan.workers],
            dtype=np.float64,
        )
        node_times = (
            self.timekeeper.compute_s * faults.time_multipliers
            + self.timekeeper.injected_s
        )
        # Each process contributes its own workers' slice; exchange_times
        # concatenates them rank-ordered (single-process: identity).
        fresh = exchange_times(node_times[self.rank_lo : self.rank_lo + self.ws_local])
        if cfg.time_smoothing > 0.0 and epoch > 0:
            # EMA damping against probe noise (extension; 0 = reference-exact)
            a = cfg.time_smoothing
            self.node_times = a * self.node_times + (1.0 - a) * fresh
        else:
            self.node_times = fresh
        # Gate the collective on REPLICATED state (the probes-ran flag derives
        # from config alone), never on locally-measured values: a gate that
        # could differ per process would deadlock the process_allgather.
        if self.n_proc > 1 and self._probes_ran:
            self.per_example_cost = exchange_times(
                self.per_example_cost[self.rank_lo : self.rank_lo + self.ws_local]
            )
        self.logger.info(
            f"Epoch {epoch}: node times {np.round(self.node_times, 4).tolist()}, "
            f"train_loss {train_metrics['loss']:.4f}, val_loss {val_loss:.4f}, "
            f"accuracy {accuracy:.2f}, wall {epoch_wall:.3f}s"
        )

        # Throughput/MFU extras (obs/flops.py): examples/s for vision, tokens/s
        # for the LM (n_train counts tokens there); MFU against the mesh's
        # aggregate bf16 peak, from XLA-cost-model FLOPs of the real plan.
        extras = {}
        # always recorded (0.0 on probe-free epochs) so the series stays
        # index-aligned with the per-epoch series in the saved artifact
        extras["probe_time"] = probe_s
        if cfg.elastic == "on":
            # fleet observables: the series the chaos tests/bench read —
            # workers_alive steps down on loss and back up on readmission,
            # recoveries counts completed recovery cycles
            extras["workers_alive"] = float(self.world_size)
            extras["recoveries"] = float(self._recoveries)
        if self._rebalance_ctl is not None:
            # online controller observables: mid-epoch plan switches this
            # epoch (the no-thrash property the tests bound) + the full
            # ledger snapshot for offline tooling / the bench field
            ctl = self._rebalance_ctl
            extras["plan_switches"] = float(ctl.switches - self._switches_last)
            self._switches_last = ctl.switches
            self.recorder.meta["rebalance_controller"] = ctl.snapshot()
        # bytes-on-wire series (ISSUE 12): what this epoch's gradient
        # combines moved per link class under the active structure — the
        # quantity the hierarchical collective exists to shrink on DCN
        ici_b, dcn_b = self._comm_bytes_per_step()
        extras["comm_bytes_ici"] = ici_b * plan.num_steps
        extras["comm_bytes_dcn"] = dcn_b * plan.num_steps
        # elastic-path host-overhead walls (superstep A/B instrumentation;
        # absent on the fused paths, whose dispatch is one scan per window)
        for k in ("host_dispatch_s", "host_put_s", "host_overhead_per_step_s"):
            if k in train_metrics:
                extras[k] = train_metrics[k]
        # AOT compile service: compile jobs finished during this epoch
        # (background pool + inline compile_now). Deliberate overlapped work
        # — kept OUT of the xla_compiles sentinel series below, visible here.
        if self._aot is not None:
            st = self._aot.stats()
            extras["aot_compiles"] = float(st["compiled"]) - self._aot_compiled_last
            self._aot_compiled_last = float(st["compiled"])
        # Corrected-injection reporting (compute-mode A/B hygiene): alongside
        # the NOMINAL straggler profile (meta straggler_factors), stamp the
        # REALIZED injected:clean device-compute profile derived from the
        # raw-wall-differenced calibration quantities, so an artifact whose
        # realized profile drifted past the nominal ceiling is self-evident.
        if self._needs_iter_cost:
            prof = self._realized_injection_profile(plan, faults)
            if prof is not None:
                self.recorder.meta["realized_injection_profile"] = prof
        if epoch_wall > 0:
            extras["examples_per_s"] = self.n_train / epoch_wall
        ppe = self._flops_per_padded_example
        if ppe is not None and ppe > 0:
            padded_examples = train_metrics.get("padded_examples") or float(
                sum(w.padded_batch * w.steps for w in plan.workers)
            )
            self._epoch_flops = ppe * padded_examples
            extras["flops_per_epoch"] = self._epoch_flops
            if epoch_wall > 0:
                from dynamic_load_balance_distributeddnn_tpu.obs.flops import mfu

                u = mfu(self._epoch_flops / epoch_wall, self.n_dev)
                if u is not None:
                    extras["mfu_bf16_peak"] = u

        # Recompile sentinel: a plan layout the run has already executed must
        # never compile again — if it does, a shape fell off the bucket
        # ladder or a jit wrapper was rebuilt (graftlint G001/G003). A fresh
        # layout compiling is ordinary lazy work (warm_start off). Recorded
        # every epoch so the series stays aligned.
        # the layout must capture every compiled-shape dimension a plan
        # controls: padded widths AND the step counts (fused window shapes
        # carry plan.num_steps / per-worker steps in their leading dims) AND
        # the streaming window lengths (superstep/windowed executables
        # specialize on them — ISSUE 2's (shape, window) cache key)
        plan_layout = (
            self._comm_sig
            + (int(plan.num_steps),)
            + tuple((int(w.padded_batch), int(w.steps)) for w in plan.workers)
            + tuple(s1 - s0 for s0, s1 in self._elastic_ranges(plan.num_steps))
            # mid-epoch switches (rebalance=window) dispatch ADDITIONAL
            # layouts inside the same epoch: fold their (step, sizes)
            # signature in so a lazily-compiled switch tuple never reads as
            # a recompile of an already-executed layout
            + tuple(
                (int(ev["step"]),) + tuple(ev["batches"])
                for ev in self._rebalance_events
                if ev.get("epoch") == epoch
            )
        )
        layout_seen = plan_layout in self._seen_plan_layouts
        self._seen_plan_layouts.add(plan_layout)
        epoch_compiles = self._compile_tracker.take()
        extras["xla_compiles"] = float(epoch_compiles)
        if epoch_compiles and layout_seen and epoch >= 1:
            self.logger.warning(
                f"Epoch {epoch}: {epoch_compiles} XLA backend compile(s) on "
                f"an already-executed plan layout {list(plan_layout)} — a "
                "shape fell off the bucket ladder or a jit wrapper was "
                "rebuilt (graftlint G001/G003)"
            )

        heartbeat()  # epoch complete — device answered end-to-end
        self.recorder.record_epoch(
            epoch=epoch,
            train_loss=train_metrics["loss"],
            train_time=float(self.node_times[0]),
            sync_time=train_metrics["sync_time"],
            val_loss=val_loss,
            accuracy=accuracy,
            partition=self.shares.tolist(),
            node_time=self.node_times.tolist(),
            wallclock_time=self.total_wallclock,
            **extras,
        )

    # ------------------------------------------------------ probe scheduling

    def _epoch_signature(self, plan, faults: EpochFaults) -> tuple:
        """What the wall-reference comparison must hold fixed: the plan's
        batch layout and the realized injection arrays."""
        return (
            tuple(int(b) for b in plan.batch_sizes),
            tuple(int(s) for s in faults.slow_iters_per_step),
            tuple(float(m) for m in faults.time_multipliers),
            tuple(float(v) for v in faults.virtual_seconds),
        )

    def _episode_state(self, plan, faults: EpochFaults):
        """Plan-NORMALIZED injection state for the episode-change trigger.
        Compute-mode slow_iters scale with each worker's batch (the injector
        sizes them off ctx.batch_sizes), so comparing raw iters would read
        every rebalance as a new episode and degrade adaptive mode into
        per-epoch probing — the defect artifacts/SMOOTHING.json's arm B
        caught. The per-example iteration ratio is plan-invariant."""
        raw = np.asarray(faults.slow_iters_per_step, dtype=np.float64)
        ratio = raw / np.maximum(np.asarray(plan.batch_sizes, dtype=np.float64), 1.0)
        return (
            ratio,
            raw,
            np.asarray(faults.time_multipliers, dtype=np.float64),
            np.asarray(faults.virtual_seconds, dtype=np.float64),
        )

    def _episode_changed(self, plan, faults: EpochFaults) -> bool:
        if self._probe_episode is None:
            return False
        ratio, raw, mult, virt = self._episode_state(plan, faults)
        r0, w0, m0, v0 = self._probe_episode
        if not np.array_equal(mult, m0) or not np.allclose(virt, v0, rtol=0.05, atol=1e-9):
            return True
        # A real episode change moves BOTH views of the injected load; a mere
        # rebalance moves only one. Batch-scaled injectors (StaticStraggler)
        # keep the per-example ratio fixed across rebalances while raw iters
        # move; wall-seconds injectors (the random fault episodes,
        # faults.py:117) keep raw iters fixed while the ratio moves. 25%
        # relative hysteresis absorbs integer-rounding jitter; on/off
        # transitions trip both terms via the +eps guard.
        ratio_moved = np.abs(ratio - r0) > 0.25 * r0 + 1e-9
        raw_moved = np.abs(raw - w0) > 0.25 * w0 + 1e-9
        return bool(np.any(ratio_moved & raw_moved))

    def _should_probe(self, epoch: int, plan, faults: EpochFaults) -> bool:
        """Adaptive probe schedule (config.probe_mode): real per-worker probe
        steps anchor a linear per-example cost model on epochs 0-1; later
        epochs skip the probes (the balancer runs on modeled times) unless
        the anchor is stale — probe_every epochs elapsed, the injection
        episode changed, or a skipped epoch's wall deviated from the probed
        reference (_update_probe_schedule). The reference's time signal is
        free because it times the epoch it already ran (dbs.py:226-250);
        this gets the probe-based signal to amortized ~zero cost, fixing the
        balanced-plan regression where per-epoch probes were pure overhead."""
        cfg = self.cfg
        if self.timing_model is not None:
            return True  # deterministic model, zero probe cost (tests)
        if not (cfg.dynamic_batch_size or self._needs_iter_cost):
            return False
        if cfg.probe_mode == "always" or epoch < 2:
            return True
        lo, hi = self.rank_lo, self.rank_lo + self.ws_local
        want = False
        if not np.isfinite(self.per_example_cost[lo:hi]).all():
            want = True
        elif self._needs_iter_cost and self._iter_cost_s is None:
            want = True
        elif self._episode_changed(plan, faults):
            want = True  # injection episode changed — re-anchor on reality
        else:
            want = epoch >= self._next_probe_epoch
        if self.n_proc > 1:
            # _probe_workers ends in the mesh-wide combine_probe collective,
            # so the decision MUST be identical on every process; the local
            # terms above (wall trigger via _next_probe_epoch, per-host
            # calibration state) can diverge. OR the votes over the hosts —
            # one scalar in the existing per-epoch metadata exchange path.
            votes = exchange_times(np.array([1.0 if want else 0.0]))
            want = bool(np.any(np.asarray(votes) > 0.5))
        return want

    def _model_compute_times(self, plan, faults: EpochFaults) -> None:
        """Probe-skipped epochs: feed the solver modeled per-worker compute
        (frozen-anchor clean cost ∝ batch, plus calibrated injected load).
        The model is exactly what the probes would measure under the
        linearity assumption the solver itself makes; real probes re-anchor
        it on the _should_probe schedule."""
        iter_cost = self._iter_cost_s or 0.0
        for r in range(self.rank_lo, self.rank_lo + self.ws_local):
            w_plan = plan.workers[r]
            clean = float(self.per_example_cost[r]) * w_plan.batch_size
            inj = (
                iter_cost * float(faults.slow_iters_per_step[r])
                if self._needs_iter_cost
                else 0.0
            )
            self.timekeeper.add_compute(r, (clean + inj) * w_plan.steps)

    def _realized_injection_profile(self, plan, faults: EpochFaults):
        """Per-worker REALIZED injected:clean device-compute multipliers for
        compute-mode injection: (clean_r + iter_cost * slow_r) / clean_r.
        Both ingredients are RTT-immune by construction — the in-step
        iteration cost comes from PAIRED raw-wall differencing (the 0.2*dt
        correction floor cancels in the pair, _probe_workers/_calibrate_
        iter_cost) and the clean anchor from the dispatch-overhead-corrected
        standalone walls — so this is the profile the A/B actually ran at,
        not the nominal request. None until both anchors exist.

        Single-host only: the anchors are per-process and a collective gated
        on locally-measured finiteness could deadlock the allgather (the
        multi-host artifact keeps the nominal profile alone)."""
        if self.n_proc > 1:
            return None
        lo, hi = self.rank_lo, self.rank_lo + self.ws_local
        if not np.isfinite(self.per_example_cost[lo:hi]).all():
            return None
        iter_cost = self._iter_cost_s
        if iter_cost is None:
            return None
        prof = np.ones(self.world_size, dtype=np.float64)
        for r in range(lo, hi):
            clean = float(self.per_example_cost[r]) * max(
                plan.workers[r].batch_size, 1
            )
            if clean <= 0:
                return None
            inj = iter_cost * float(faults.slow_iters_per_step[r])
            prof[r] = (clean + inj) / clean
        return [round(float(p), 4) for p in prof]

    def _update_probe_schedule(
        self, epoch: int, plan, faults: EpochFaults, epoch_wall: float,
        train_metrics: Dict[str, float],
    ) -> None:
        cfg = self.cfg
        sig = self._epoch_signature(plan, faults)
        if self._probe_this_epoch:
            self._probe_sig = sig
            self._probe_episode = self._episode_state(plan, faults)
            # epoch_wall already excludes probe cost (run_epoch), so probed
            # and skipped epochs compare apples-to-apples as-is
            self._probe_wall_ref = epoch_wall
            self._next_probe_epoch = epoch + max(cfg.probe_every, 1)
            self._slow_streak = 0
        elif self._probe_wall_ref and sig != self._probe_sig:
            # the plan changed on a skipped epoch (model-driven rebalance):
            # the stored wall no longer describes this plan, so RE-BASE the
            # reference on this epoch's wall — otherwise the slowdown
            # trigger would be inert until the next probe_every anchor on
            # exactly the epochs adaptive mode newly skips. (If a genuine
            # slowdown starts the same epoch it gets baked into the ref and
            # is only caught by the anchor — bounded by probe_every.)
            self._probe_sig = sig
            self._probe_wall_ref = epoch_wall
            self._slow_streak = 0
        elif self._probe_wall_ref and sig == self._probe_sig:
            if epoch_wall > (1.0 + cfg.probe_wall_tol) * self._probe_wall_ref:
                # reality got SLOWER than the model (e.g. a real straggler
                # the injector didn't create) — but only a PERSISTENT
                # slowdown (two consecutive epochs over threshold) forces a
                # re-probe; a single epoch over is indistinguishable from
                # tunnel/host jitter, and triggering on it would degenerate
                # adaptive mode into per-epoch probing in jittery
                # environments. Faster-than-ref is benign (compile noise
                # leaving the wall); the probe_every anchor re-anchors the
                # reference either way.
                self._slow_streak += 1
                if self._slow_streak >= 2:
                    self._next_probe_epoch = epoch + 1
            else:
                self._slow_streak = 0

    # ---------------------------------------------------------- train epoch

    def _can_use_fused(self, plan) -> bool:
        """The fused whole-epoch SPMD path applies when there is no balancer
        feedback to measure (dbs off — the reference records node times only
        under dbs, dbs.py:423-426), the plan is uniform, and workers map 1:1
        onto mesh devices."""
        return (
            not self.cfg.dynamic_batch_size
            and plan.is_uniform()
            and self.topology.one_worker_per_device
            and self.n_dev == self.world_size
            and self.timing_model is None
            # compute-mode injection needs per-worker probes (elastic path),
            # so straggler A/B arms stay comparable
            and not self._needs_iter_cost
        )

    def _can_use_fused_dbs(self, plan) -> bool:
        """The fused-DBS path (SURVEY §7.3 option b): every worker padded to
        the same CAPACITY batch so ONE compiled SPMD scan serves every
        rebalanced plan; per-worker speed is still measured by the standalone
        (untimed) probe step. Needs one worker per chip."""
        return (
            self.cfg.fused_dbs
            and self.cfg.dynamic_batch_size
            and self.topology.one_worker_per_device
            and self.n_dev == self.world_size
        )

    @property
    def _cap_b(self) -> int:
        """Fused-DBS per-worker capacity width: the largest bucketed batch the
        balancer can assign (max_share of the global batch)."""
        cfg = self.cfg
        max_share = min(1.0, cfg.capacity_factor / self.world_size)
        return -(-int(np.ceil(max_share * cfg.batch_size)) // cfg.bucket) * cfg.bucket

    @property
    def _cap_packed(self) -> int:
        """Packed-epoch concat width — ONE fixed width serving every plan.

        With bucket snapping active (the default), every plan's per-worker
        widths are bucket multiples summing to floor(B/bucket)*bucket <= B
        (quantize_batches), so the tight cap ceil(B/bucket)*bucket carries
        ZERO dead rows. The old conservative cap B + ws*bucket paid up to
        ws*bucket zero-weight rows on EVERY packed step — a 20% compute tax
        at the bench shape (B=512, ws=4, bucket=32) levied on the dbs-on arm
        only (the dbs-off arm's uniform plans ride the lean fused scan),
        eating most of the balancer's ~1.25x ceiling on a timeshared chip.
        Without snapping, per-worker ceil padding can exceed B; keep the
        conservative cap there (_can_use_packed enforces the width bound)."""
        cfg = self.cfg
        B, ws, bucket = cfg.batch_size, self.world_size, cfg.bucket
        if not cfg.dynamic_batch_size:
            # dbs off: the only plan is the uniform integer split — its exact
            # packed width is a static bound. At bucket-divisible shapes this
            # equals the dbs-on tight cap, so the A/B arms (and the clean
            # leg) share one executable with identical dead-row cost: zero.
            per_batch = -(-B // ws)  # ceil: the largest worker batch
            return ws * (-(-per_batch // bucket) * bucket)
        if cfg.snap_to_bucket and self.SNAP_BATCHES and B // bucket >= ws:
            # every dbs plan (incl. the epoch-0 uniform one) passes through
            # quantize_batches under exactly these conditions — unsnapped
            # dbs plans keep the slack cap
            return -(-B // bucket) * bucket
        return B + ws * bucket

    def _can_use_packed(self, plan) -> bool:
        """Single-device packed epochs: all workers share ONE chip (the
        reference's contention topology, -gpu 0,0,0,0), so the weighted-sum
        gradient combine over the concatenated true-width batches is the
        elastic path's exact math (psum over a 1-chip mesh is identity) in
        one compiled whole-epoch scan instead of ws+1 dispatches per step.
        The balancer's per-worker time signal still comes from the
        standalone probes. Works with or without the device cache (index
        feed vs materialized windows). Needs no per-worker grad clip (the
        LM's clip is per worker, not global) and none of the fused-only
        features; vision only (the LM's column batches stay elastic or use
        fused_dbs)."""
        cfg = self.cfg
        if cfg.packed == "off":
            return False
        ok = (
            self.n_dev == 1
            and self.n_proc == 1
            and self.bundle is not None
            and getattr(self.bundle, "train_x", None) is not None
            and cfg.grad_clip == 0
            and not cfg.compress_grads
            and cfg.grad_accum <= 1
        )
        # the plan's concat of bucketed widths must fit the fixed scan width
        # (always true for snapped dbs plans, which the tight cap mirrors; an
        # unsnapped split's per-worker ceil padding can overflow it)
        fits = (
            plan is None
            or sum(w.padded_batch for w in plan.workers) <= self._cap_packed
        )
        if cfg.packed == "on" and not (ok and fits):
            if ok and not fits:
                raise ValueError(
                    f"packed=on: plan widths "
                    f"{[w.padded_batch for w in plan.workers]} sum past the "
                    f"packed scan width {self._cap_packed}"
                )
            raise ValueError(
                "packed=on needs a single-device vision topology and no "
                "grad_clip/compress_grads/grad_accum"
            )
        return ok and fits

    def _chunk_ranges(self, num_steps: int):
        """Step windows of the streaming host path: ``stream_chunk_steps``-sized
        windows (0 = one whole-epoch window). At most two distinct window
        lengths per epoch (body + tail), so the fused scan compiles at most
        twice per geometry."""
        chunk = self.cfg.stream_chunk_steps
        if chunk <= 0 or num_steps <= chunk:
            return [(0, num_steps)]
        return [(s, min(s + chunk, num_steps)) for s in range(0, num_steps, chunk)]

    def _elastic_ranges(self, num_steps: int):
        """Elastic-path step windows. Scan mode additionally caps windows at
        ``superstep_window``: the superstep compiles a fully UNROLLED window
        (bitwise parity with per-step dispatch requires the unrolled
        lowering — steps.py group_superstep), so program size must stay
        bounded. Still at most two distinct window lengths per geometry."""
        ranges = self._chunk_ranges(num_steps)
        if self._elastic_mode() != "scan":
            return ranges
        win = max(int(self.cfg.superstep_window), 1)
        out = []
        for s0, s1 in ranges:
            out.extend((s, min(s + win, s1)) for s in range(s0, s1, win))
        return out

    def _gather_fused_window(self, plan, s0: int, s1: int, pad_to=None,
                             as_indices: bool = False, pack_total=None):
        """Host-side gather of steps [s0, s1): [n, ws*b_pad, ...] numpy arrays
        in the fused path's global layout (worker r owns slice r; each process
        materializes only its own workers' slice). ``pad_to``: fused-DBS
        capacity width per worker. ``as_indices``: device-cache mode — the
        window is (idx, w) only; rows gather on device. ``pack_total``:
        packed-epoch mode — workers keep their true bucketed widths and the
        CONCAT pads (zero weight) to this fixed global width."""
        data = [
            self._worker_inputs(
                plan, self.rank_lo + r, s0, s1, pad_to=pad_to,
                as_indices=as_indices,
            )
            for r in range(self.ws_local)
        ]
        width = sum(d[0].shape[1] for d in data)
        extra = (pack_total - width) if pack_total is not None else 0
        out = []
        for i in range(len(data[0])):
            parts = [d[i] for d in data]
            if extra > 0:
                # zero pad block folded into the single concat pass (a
                # post-hoc np.pad would copy the whole window a second time)
                a0 = parts[0]
                parts.append(
                    np.zeros((a0.shape[0], extra) + a0.shape[2:], a0.dtype)
                )
            out.append(np.concatenate(parts, axis=1))
        return tuple(out)

    def _put_fused_window(self, *arrays):
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import batch_sharding

        mesh = self.mesh
        bx = self._batch_axes
        if self.n_proc == 1:
            return tuple(
                jax.device_put(a, batch_sharding(mesh, a.ndim, axis=bx, axis_dim=1))
                for a in arrays
            )
        return tuple(
            jax.make_array_from_process_local_data(
                batch_sharding(mesh, a.ndim, axis=bx, axis_dim=1), a
            )
            for a in arrays
        )

    def _train_epoch_fused(
        self, plan, faults: EpochFaults, epoch: int, dbs_probe: bool = False,
        packed: bool = False,
    ) -> Dict[str, float]:
        """``dbs_probe=True``: the fused-DBS mode — every worker padded to the
        fixed capacity width (one compiled scan for every plan), with the
        balancer's per-worker time signal measured by the standalone probe
        step after the epoch (untimed, like the elastic path's probes).

        ``packed=True``: the single-device packed mode — workers keep their
        TRUE bucketed widths, concatenated (then padded to the fixed
        ``_cap_packed`` width) into the same scan; the 1-chip psum is an
        identity, so this is the elastic combine's math with zero per-step
        dispatch. Injected synthetic load is the per-worker total (the chip
        serializes the workers either way)."""
        cfg = self.cfg
        self.timekeeper.reset()
        pad_to = self._cap_b if (dbs_probe and not packed) else None
        pack_total = self._cap_packed if packed else None
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import batch_sharding

        mesh = self.mesh
        bx = self._batch_axes
        if packed:
            slow = jax.device_put(
                np.array(
                    [faults.slow_iters_per_step.sum()], dtype=np.int32
                ),
                batch_sharding(mesh, 1, axis=bx),
            )
        elif self.n_proc == 1:
            slow = jax.device_put(
                faults.slow_iters_per_step.astype(np.int32),
                batch_sharding(mesh, 1, axis=bx),
            )
        else:
            slow = jax.make_array_from_process_local_data(
                batch_sharding(mesh, 1, axis=bx),
                faults.slow_iters_per_step.astype(np.int32)[
                    self.rank_lo : self.rank_lo + self.ws_local
                ],
            )
        seed = jnp.int32(cfg.seed * 31 + epoch)
        if self.n_proc == 1:
            # committed replicated, matching the AOT lowering spec — an
            # uncommitted scalar would call the compiled executable with a
            # mismatched input sharding
            seed = jax.device_put(seed, replicated_sharding(mesh))

        # Streaming: gather window k+1 on the prefetch thread while the device
        # runs window k (dispatch is async — the jit call returns immediately).
        # The per-step dropout/augment rng folds in state.step, not the scan
        # index, so windowed scans are bitwise-identical to one whole-epoch
        # scan. Peak host memory: two windows, not the epoch.
        ranges = self._chunk_ranges(plan.num_steps)
        metrics_total = np.zeros(4, dtype=np.float64)
        first_window = None
        use_cache = self._use_device_cache
        if use_cache:
            cache_x, cache_y = self._device_cache_replicated()
        with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(
                self._gather_fused_window, plan, *ranges[0], pad_to, use_cache,
                pack_total,
            )
            for i, _ in enumerate(ranges):
                # transfer vs dispatch tracks in the trace: the put span
                # includes any wait on the overlapped gather thread
                with self._trace.span("fused_put", cat="transfer"):
                    win = self._put_fused_window(*fut.result())
                if i + 1 < len(ranges):
                    fut = pool.submit(
                        self._gather_fused_window, plan, *ranges[i + 1], pad_to,
                        use_cache, pack_total,
                    )
                with self._trace.span("fused_dispatch", cat="dispatch"):
                    # service-registry resolution (multi-device AOT lowering):
                    # warm-started runs dispatch the pre-compiled executable;
                    # cold keys compile inline through the service (same wall,
                    # registered + sentinel-silent); multi-host stays lazy
                    if use_cache:
                        idxs, ws_ = win
                        args = (self.state, cache_x, cache_y, idxs, ws_, slow, seed)
                        fn = self._resolve_fused_epoch(
                            idxs.shape[0], idxs.shape[1], slow.shape[0], args
                        )
                        self.state, metrics = fn(*args)
                    else:
                        xs, ys, ws_ = win
                        if first_window is None and self._fused_sync_per_step is None:
                            # retained only on the run's first epoch, for the
                            # one-time sync/FLOPs probes below — not pinned later
                            first_window = (xs, ys, ws_)
                        args = (self.state, xs, ys, ws_, slow, seed)
                        fn = self._resolve_fused_epoch(
                            xs.shape[0], xs.shape[1], slow.shape[0], args
                        )
                        self.state, metrics = fn(*args)
                    metrics_total += np.asarray(jax.block_until_ready(metrics))
                heartbeat()
        metrics = metrics_total
        probe_overhead = 0.0
        if self._fused_sync_per_step is None:
            t0 = time.perf_counter()
            if first_window is None:
                # device-cache mode: materialize ONE step's batches for the
                # one-time sync/FLOPs probes (probe-overhead time, not wall)
                first_window = self._put_fused_window(
                    *self._gather_fused_window(
                        plan, 0, 1, pad_to, pack_total=pack_total
                    )
                )
            xs, ys, ws_ = first_window
            with self._trace.span("sync_probe", cat="probe"):
                self._fused_sync_per_step = self._probe_fused_sync(
                    xs, ys, ws_, slow, jnp.int32(cfg.seed * 31 + epoch)
                )
            if self._flops_per_padded_example is None:
                from dynamic_load_balance_distributeddnn_tpu.obs.flops import (
                    compiled_flops,
                )

                # the sync probe above already compiled this exact program
                # through the AOT service — reuse its executable for the
                # cost analysis instead of compiling a second copy
                pre = None
                if self._aot is not None:
                    pre = self._aot.get(
                        ("fused_step_probe", self._aot_gen)
                        + self._comm_sig
                        + tuple(int(s) for s in xs[0].shape)
                    )
                f = compiled_flops(
                    self.steps.fused_step_probe,
                    self.state, xs[0], ys[0], ws_[0], slow,
                    jnp.int32(cfg.seed * 31 + epoch),
                    compiled=pre,
                )
                # cost_analysis reports the PER-DEVICE partitioned module's
                # FLOPs (it processes global_batch / n_dev examples), so
                # normalize by the per-device slice — consistent with the
                # elastic path's single-device normalization
                per_dev_batch = max(xs.shape[1] // max(self.n_dev, 1), 1)
                self._flops_per_padded_example = (
                    f / per_dev_batch if f else -1.0
                )
            # one-time instrumentation (2 extra XLA compiles + probe steps);
            # excluded from the epoch wall so the benchmark's fused-arm
            # wallclock stays comparable to the elastic arm
            probe_overhead = time.perf_counter() - t0
        if dbs_probe:
            # The balancer's time signal: per-worker standalone probe steps at
            # the TRUE (plan-bucketed) shapes, untimed against the epoch wall
            # — the fused scan itself is one SPMD program with no per-worker
            # boundary to time.
            t0 = time.perf_counter()
            if (
                self.timing_model is None
                and self._probe_this_epoch
                and (cfg.dynamic_batch_size or self._needs_iter_cost)
            ):
                data = [
                    self._worker_inputs(
                        plan, self.rank_lo + r, 0, 1,
                        as_indices=self._use_device_cache,
                    )
                    for r in range(self.ws_local)
                ]
                with self._trace.span("probe", cat="probe"):
                    self._probe_workers(plan, data, faults, epoch)
                self._probes_ran = True
            if self.timing_model is not None:
                modeled = np.asarray(self.timing_model(plan), dtype=np.float64)
                for r in range(self.world_size):
                    self.timekeeper.add_compute(r, modeled[r])
            probe_overhead += time.perf_counter() - t0
        for r in range(self.world_size):
            self.timekeeper.add_injected(r, float(faults.virtual_seconds[r]))
        wloss, loss_sum, count = float(metrics[0]), float(metrics[1]), float(metrics[2])
        return {
            "loss": loss_sum / max(count, 1.0),
            "wloss": wloss / max(plan.num_steps, 1),
            "sync_time": self._fused_sync_per_step * plan.num_steps,
            "probe_overhead": probe_overhead,
            # executed padded examples (capacity layout runs cap_b per worker,
            # packed runs cap_packed total, regardless of true batches) — MFU
            "padded_examples": (
                float(self._cap_packed * plan.num_steps)
                if packed
                else float(self.world_size * self._cap_b * plan.num_steps)
                if dbs_probe
                else None
            ),
        }

    def _aot_fused_probe(self, name: str, fn, args, sig: tuple):
        """Resolve a fused-path probe executable through the AOT service's
        blocking ``compile_now`` (inline, deduped): the SAME compiled object
        then serves both the sync-probe timing and ``cost_analysis`` — no
        second copy of the step is ever compiled for FLOPs accounting.
        Single-host only (multi-host AOT lowering of the mesh program is
        untested armor we don't need: those runs keep the lazy path)."""
        if self._aot is None or self.n_proc > 1:
            return fn
        try:
            return self._aot.compile_now(
                (name, self._aot_gen) + self._comm_sig + sig, fn, args
            )
        except Exception as e:
            self.logger.warning(
                f"AOT compile_now({name}) failed: {e!r} — using lazy jit"
            )
            return fn

    def _probe_fused_sync(self, xs, ys, ws_, slow, seed, reps: int = 3) -> float:
        """Per-step collective cost on the fused path: time a full single
        step vs its comm-free twin (identical math, psums stripped) after
        warm-up; the delta is the sync time. If the delta drowns in timer
        noise, fall back to timing the standalone gradient psum. Restores the
        reference's compute/comm split contract (dbs.py:250, 297-299) on the
        path where comm is fused into the XLA program."""
        x0, y0, w0 = xs[0], ys[0], ws_[0]
        sig = tuple(int(s) for s in x0.shape)

        def timed(fn, *args) -> float:
            jax.block_until_ready(fn(*args))  # warm execute (pre-compiled)
            best = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(*args))
                best = min(best, time.perf_counter() - t0)
            heartbeat()
            return best

        full_args = (self.state, x0, y0, w0, slow, seed)
        f_full = self._aot_fused_probe(
            "fused_step_probe", self.steps.fused_step_probe, full_args, sig
        )
        f_local = self._aot_fused_probe(
            "fused_step_nocomm", self.steps.fused_step_nocomm, full_args, sig
        )
        t_full = timed(f_full, *full_args)
        t_local = timed(f_local, *full_args)
        # The standalone-psum fallback must run UNCONDITIONALLY: gating it on
        # the locally-measured delta would make processes execute different
        # collective programs in multi-host runs (timer noise differs per
        # host) and deadlock the mesh.
        zeros = jax.tree_util.tree_map(jnp.zeros_like, self.state.params)
        f_psum = self._aot_fused_probe("comm_probe", self.steps.comm_probe, (zeros,), ())
        t_psum = timed(f_psum, zeros)
        delta = t_full - t_local
        return float(delta) if delta > 0.0 else float(t_psum)

    def _worker_inputs(
        self,
        plan,
        rank: int,
        s0: int = 0,
        s1: Optional[int] = None,
        *,
        pad_to: Optional[int] = None,
        as_indices: bool = False,
    ):
        """Materialize one worker's steps [s0, s1) (default: the whole epoch):
        [n, b_pad, ...] batches, labels and per-example weights (the
        weighted-combine contract). The gather runs through the native C++
        runtime when available (multithreaded row pack; runtime/native.py),
        numpy otherwise — identical results.

        ``pad_to``: zero-pad the batch axis up to this width (weights 0 on the
        padding) — the fused-DBS capacity layout, where every worker presents
        the same static shape regardless of its true batch (SURVEY §7.3).

        ``as_indices``: device-cache mode — return ``(idx_i32, w)`` and let
        the compiled step gather the rows from the HBM-resident arrays
        (identical rows and weights; the host-side row pack is skipped)."""
        from dynamic_load_balance_distributeddnn_tpu.runtime import take_rows

        idx, mask = plan.epoch_indices(rank, s0, s1)
        w = np.stack(
            [
                example_weights(
                    mask[s],
                    total_true=int(plan.batch_sizes.sum()),
                    worker_count=int(mask[s].sum()),
                    world_size=self.world_size,
                    uniform_worker_weight=self.cfg.disable_enhancements,
                )
                for s in range(mask.shape[0])
            ]
        )
        if as_indices:
            if pad_to is not None and idx.shape[1] < pad_to:
                extra = pad_to - idx.shape[1]
                idx = np.pad(idx, ((0, 0), (0, extra)))
                w = np.pad(w, ((0, 0), (0, extra)))
            return idx.astype(np.int32), w
        x = take_rows(self.bundle.train_x, idx)
        y = take_rows(self.bundle.train_y, idx)
        if pad_to is not None and x.shape[1] < pad_to:
            extra = pad_to - x.shape[1]
            pad1 = ((0, 0), (0, extra))
            x = np.pad(x, pad1 + ((0, 0),) * (x.ndim - 2))
            y = np.pad(y, pad1[: y.ndim])
            w = np.pad(w, pad1)
        return x, y, w

    def _elastic_mode(self) -> str:
        """How the elastic hot loop executes (config.superstep):

        ``"scan"`` — ONE device hosts every worker (the full contention
        topology), so the per-step cross-worker combine is chip-local and a
        whole window runs as one compiled ``lax.scan`` carrying the
        TrainState: one dispatch per window, bitwise-identical math.

        ``"window"`` — workers span several devices, so step k's combine is
        a mesh collective that step k+1's gradients depend on; the per-step
        cadence stays, but each worker-step is ONE window-sliced executable
        call (on-device step indexing) instead of ~5 host-issued dispatches.

        ``"step"`` — the legacy per-step loop (superstep="off"), kept as the
        bitwise-parity and dispatch-overhead reference.

        shard_update composes with scan mode (the PR-13 fallback, closed):
        the superstep body routes into the axis-free zero-1 twin
        (``_zero1_update(..., with_comm=False, local_index=0)``), bitwise-
        identical to the windowed combine twin's identity collectives on
        the single-device mesh. The one remaining exclusion is
        shard_update x compress_grads — the quantized reduce-scatter is
        NOT an identity even over a size-1 axis (stochastic rounding), so
        that pair keeps the windowed per-step combine cadence."""
        if self.cfg.superstep == "off":
            return "step"
        if (
            self.topology.single_group
            and self.n_proc == 1
            and not (self.cfg.shard_update and self.cfg.compress_grads)
        ):
            return "scan"
        return "window"

    def _dispatch_superstep_window(
        self, staged_d: Dict, d: int, group, win_key, slow_dev, aux_windows
    ) -> None:
        """Scan mode: one compiled superstep for the whole worker group's
        window. ``staged_d[r]`` holds worker r's window arrays (+ rng keys);
        the per-worker tuples transpose into the scan's pytree inputs."""
        cols = tuple(zip(*(staged_d[r] for r in group)))
        slows = tuple(slow_dev[r] for r in group)
        self._superstep_keys.add(win_key)
        use_cache = self._use_device_cache
        name = "group_superstep_idx" if use_cache else "group_superstep"
        fn = None
        if self._aot is not None:
            fn = self._aot.get((name, win_key, d, self._aot_gen))
        if fn is None:
            fn = self.steps.group_superstep_idx if use_cache else self.steps.group_superstep
        with self._host_meter.dispatch():
            if use_cache:
                idxs, ws_, ks = cols
                self.state, aux = fn(
                    self.state, *self._device_cache_for(d), idxs, ws_, ks, slows
                )
            else:
                xs, ys, ws_, ks = cols
                self.state, aux = fn(self.state, xs, ys, ws_, ks, slows)
        aux_windows.append(aux)

    def _dispatch_combine_steps(
        self, staged: Dict, win: int, slow_dev, aux_acc, windowed: bool
    ) -> None:
        """Per-step combine cadence, shared by window mode and the legacy
        per-step mode (superstep="off" — the dispatch-overhead reference the
        superstep A/B in bench.py measures against). ``windowed`` picks how
        a worker-step gets its data: ONE window-sliced executable call (the
        step index rides in as a traced scalar, the window slices on device)
        vs host-side slicing plus the single-step executables (one dispatch
        per slice)."""
        topo = self.topology
        steps = self.steps
        use_cache = self._use_device_cache
        if windowed:
            step_first = steps.worker_step_first_win_idx if use_cache else steps.worker_step_first_win
            step_acc = steps.worker_step_acc_win_idx if use_cache else steps.worker_step_acc_win
        else:
            step_first = steps.worker_step_first_idx if use_cache else steps.worker_step_first
            step_acc = steps.worker_step_acc_idx if use_cache else steps.worker_step_acc
        # Resolve each worker's executables once per window: service-compiled
        # (AOT) when present, the lazy jit wrapper otherwise. Shapes come
        # from the staged arrays themselves so the key can never drift from
        # what is actually dispatched.
        suffix = ("_win" if windowed else "") + ("_idx" if use_cache else "")
        resolved = {}
        for d in topo.used_device_indices:
            for r in topo.groups[d]:
                arrs = staged[d][r]
                b = int(arrs[0].shape[1])
                wl = int(arrs[0].shape[0]) if windowed else None
                resolved[r] = (
                    self._aot_resolve("worker_first" + suffix, b, d, wl, step_first),
                    self._aot_resolve("worker_acc" + suffix, b, d, wl, step_acc),
                )
        up_name = self._combine_names()[0]
        combine = self._aot_resolve_combine(up_name, getattr(steps, up_name))
        for s in range(win):
            s_i = np.int32(s)
            with self._host_meter.dispatch():
                partials = {}
                views = shard_views(self.state.params, topo.devices)
                for d in topo.used_device_indices:
                    acc = None
                    cache = self._device_cache_for(d) if use_cache else ()
                    for r in topo.groups[d]:
                        arrs = staged[d][r]
                        if windowed:
                            args = cache + arrs + (s_i, slow_dev[r])
                        else:
                            args = cache + tuple(a[s] for a in arrs) + (
                                slow_dev[r],
                            )
                        f_first, f_acc = resolved[r]
                        if acc is None:
                            acc, aux = f_first(views[d], *args)
                        else:
                            acc, aux = f_acc(views[d], acc, *args)
                        aux_acc.append(aux)
                    partials[d] = acc
                stacked = stack_partials(
                    [partials[d] for d in topo.used_device_indices], self.mesh
                )
                self.state = combine(self.state, stacked)

    # ------------------------------------------- online window rebalancing
    # (ISSUE 11, balance/controller.py). The epoch-cadence loop re-solves
    # the partition once per epoch; under a time-varying straggler (the
    # sin/ramp schedules) that lag is the whole cost. At window cadence the
    # controller folds the per-window signal (EMA rates x the injector's
    # instantaneous multipliers, scaled by measured step-wall feedback) into
    # the same inverse-time solve, and — under hysteresis plus a regret-
    # style budget — retires the REMAINING windows under the new plan:
    # staged windows keep their data (nothing on device is re-staged,
    # train/pipeline.py), future windows re-slice the unvisited example
    # pool through data/partitioner.py build_remainder_plan.

    def _window_controller(self) -> Optional[OnlineRebalanceController]:
        cfg = self.cfg
        if cfg.rebalance != "window" or not cfg.dynamic_batch_size:
            return None
        if self.n_proc > 1:
            # the switch decision folds LOCALLY measured walls — a gate that
            # can diverge per process would desynchronize the combine
            # collectives mid-epoch
            if not self._window_rebalance_logged:
                self._window_rebalance_logged = True
                self.logger.warning(
                    "rebalance=window is single-process only — falling back "
                    "to epoch cadence"
                )
            return None
        if self._rebalance_ctl is None:
            topo = self.topology
            self._rebalance_ctl = OnlineRebalanceController(
                self.world_size,
                cfg.batch_size,
                [topo.groups[d] for d in topo.used_device_indices],
                bucket=(
                    cfg.bucket if (cfg.snap_to_bucket and self.SNAP_BATCHES) else 0
                ),
                max_share=min(1.0, cfg.capacity_factor / self.world_size),
                hysteresis=cfg.rebalance_hysteresis,
                margin=cfg.rebalance_margin,
                budget_frac=cfg.rebalance_budget_frac,
                rate_alpha=cfg.rebalance_rate_alpha,
                logger=self.logger,
            )
            # decision journal on the registry snapshot (ISSUE 15): the
            # controller's ledgers + last verdict become queryable live
            self.obs.attach(controller=self._rebalance_ctl)
        # refresh each call: the tree/wires (and therefore the modeled comm
        # floor) can change across re-resolutions while the controller lives
        self._rebalance_ctl.comm_step_s = self._modeled_comm_step_s()
        return self._rebalance_ctl

    def _window_rates(self) -> Optional[np.ndarray]:
        """Base (injection-free) per-worker per-example rates for the
        controller: the probe anchors when they exist, else the last
        epoch's multiplier-free compute vector normalized by the plan's
        per-worker example counts. None before any real signal exists
        (epoch 0 cold start) — the caller then evaluates on a unit base
        (the schedule's relative multipliers still steer the solve) but
        MUST NOT fold the placeholder into the controller's EMA: its
        arbitrary scale would drown the absolute compute-mode injection
        term for many evaluations (0.5-EMA half-life)."""
        c = self.per_example_cost.copy()
        if np.isfinite(c).all() and (c > 0).all():
            return np.maximum(c, 1e-12)
        clean = self._clean_compute_s
        examples = getattr(self, "_clean_examples", None)
        if (
            clean is not None
            and examples is not None
            and len(clean) == self.world_size
            and len(examples) == self.world_size
            and (clean > 0).all()
        ):
            # normalize by the example counts of the SAME epoch the seconds
            # were measured under, not the current plan's
            return np.maximum(clean / np.maximum(examples, 1.0), 1e-12)
        return None

    def _window_ctx(self, pl) -> FaultContext:
        """FaultContext against the CURRENT segment's batch sizes (after a
        switch the injected compute must track the new split, or the
        delivered slowdown factors drift off the schedule)."""
        return FaultContext(
            batch_sizes=self._scatter_full(pl.batch_sizes.astype(np.float64)),
            iter_cost_s=self._iter_cost_s if self._needs_iter_cost else None,
            per_example_cost_s=(
                self._scatter_full(self.per_example_cost)
                if np.isfinite(self.per_example_cost).all()
                else None
            ),
        )

    def _window_faults_at(self, t: float, pl) -> Optional[EpochFaults]:
        """The injector's instantaneous (window-cadence) fault view at
        epoch-time ``t``, compacted to the active fleet — None for
        injectors without a time-varying surface."""
        fa = getattr(self.injector, "faults_at", None)
        if fa is None:
            return None
        return self._faults_active(fa(t, self._window_ctx(pl)))

    def _effective_rates(
        self, rates: np.ndarray, wf: Optional[EpochFaults], batches: np.ndarray
    ) -> np.ndarray:
        """Compose the base rates with the window's fault view: virtual
        multipliers scale, compute-mode slow iters add their per-example
        equivalent at the current split."""
        eff = np.asarray(rates, dtype=np.float64).copy()
        if wf is None:
            return eff
        eff = eff * np.asarray(wf.time_multipliers, dtype=np.float64)
        if self._needs_iter_cost and self._iter_cost_s:
            extra = self._iter_cost_s * np.asarray(
                wf.slow_iters_per_step, dtype=np.float64
            )
            eff = eff + extra / np.maximum(
                np.asarray(batches, dtype=np.float64), 1.0
            )
        return eff

    def _aot_submit_candidate(
        self, batches: np.ndarray, ranges, j: int
    ) -> tuple:
        """Speculatively queue the executables a switch onto ``batches``
        would dispatch for windows >= j (scan: the superstep shape-tuple
        keys; ladder modes: the per-worker rungs at the remaining window
        lengths). The engine only EXECUTES a switch once these resolve —
        warm gating — so a switch never pays a foreground compile."""
        if self._aot is None:
            return ()
        cfg = self.cfg
        topo = self.topology
        padded = [
            -(-int(max(b, 1)) // cfg.bucket) * cfg.bucket for b in batches
        ]
        wins = tuple(sorted({s1 - s0 for s0, s1 in ranges[j:]}))
        keys: list = []
        if self._elastic_mode() == "scan":
            d0 = topo.used_device_indices[0]
            group_pad = [padded[self.rank_lo + r] for r in topo.groups[d0]]
            for win in wins:
                keys += self._aot_submit_superstep(
                    group_pad, win, speculative=True
                )
        else:
            win_arg = wins if self._elastic_mode() == "window" else ()
            for d in topo.used_device_indices:
                group = topo.groups[d]
                want_acc = len(group) > 1
                for r in group:
                    keys += self._aot_submit_worker_steps(
                        d, padded[self.rank_lo + r], win_arg, want_acc,
                        want_plain=True, speculative=True,
                    )
        return tuple(dict.fromkeys(keys))

    def _maybe_window_rebalance(
        self, ctl, plan, seg_plans, ranges, pipe, i, epoch,
        aux_acc, aux_windows, eval_state,
    ) -> None:
        """One controller evaluation at the boundary after window ``i``:
        fold the signal, propose, speculate at the candidate, and — when
        the hysteresis verdict is a warm-gated switch — re-slice the
        remaining windows under the new plan."""
        j = pipe.next_unlaunched()
        if j >= len(ranges):
            return  # every window already staged — no horizon left to act on
        s_switch = ranges[j][0]
        remaining = plan.num_steps - s_switch
        if remaining <= 0:
            return
        with self._trace.span(
            "controller", cat="solve", args={"window": i, "epoch": epoch}
        ):
            t_eval0 = time.perf_counter()
            cur_pl, cur_off = self._seg_for_step(seg_plans, s_switch)
            cur_batches = np.asarray(cur_pl.batch_sizes, dtype=np.int64)
            base = self._window_rates()
            if base is not None:
                ctl.observe_rates(base)
            t_next = float(epoch) + (ranges[j][0] + ranges[j][1]) / (
                2.0 * max(plan.num_steps, 1)
            )
            wf = self._window_faults_at(t_next, cur_pl)
            rates = ctl.rates
            if rates is None:
                rates = np.ones(self.world_size, dtype=np.float64)
            eff = self._effective_rates(rates, wf, cur_batches)
            # step-wall feedback (real clocks only): sync on the last
            # dispatched window and compare the measured wall of the steps
            # since the previous evaluation against the model's prediction
            if self.timing_model is None:
                last_aux = (aux_windows or aux_acc)[-1:] or None
                if last_aux is not None:
                    jax.block_until_ready(last_aux)
                now = time.perf_counter()
                # host-side dispatch walls since the last evaluation
                # (balance/timing.py mark_window): the measured wall below
                # includes them, the model predicts device compute only —
                # subtracting keeps the feedback scale a compute signal
                host_s, _, _ = self._host_meter.mark_window()
                done = ranges[i][1] - eval_state["step"]
                if eval_state["step"] > 0 and done > 0 and eval_state.get("pred_step"):
                    # compare against the prediction STORED at the previous
                    # evaluation — the same windows, the same schedule
                    # phase, the same batch split; modeling the past stretch
                    # with the NEXT window's fault view would bias the scale
                    # under exactly the time-varying schedules the
                    # controller targets
                    ctl.observe_wall(
                        max(now - eval_state["t"] - host_s, 1e-9),
                        eval_state["pred_step"] * done,
                    )
                eval_state["t"] = now
                eval_state["step"] = ranges[i][1]
            # position tag merged into the journal entry at decision time
            # (ISSUE 19): HOLD verdicts carry their epoch/window too, not
            # just the committed switches commit() annotates
            ctl.eval_context = {"epoch": int(epoch), "window": int(j)}
            dec = ctl.propose(eff, cur_batches, remaining)
            keys: tuple = ()
            if dec.candidate_batches is not None and not np.array_equal(
                dec.candidate_batches, cur_batches
            ):
                keys = self._aot_submit_candidate(
                    dec.candidate_batches, ranges, j
                )
            apply = dec.switch
            if apply and self._aot is not None and keys:
                missing = [k for k in keys if self._aot.get(k) is None]
                dead = [k for k in missing if self._aot.failed(k)]
                if dead:
                    # a candidate executable FAILED to compile: deferring
                    # would silently disable window rebalancing for the
                    # rest of the run (failed keys never resolve) — switch
                    # anyway and let dispatch's lazy-jit fallback compile
                    # foreground, logging once per key
                    for k in dead:
                        if k not in self._aot_failed_logged:
                            self._aot_failed_logged.add(k)
                            self.logger.warning(
                                f"online-dbs: candidate executable {k} "
                                "failed its background compile — switching "
                                "via the lazy fallback (one foreground "
                                "compile)"
                            )
                elif missing:
                    # warm gate: still compiling in the background — defer;
                    # the hysteresis re-evaluates at the next cadence
                    # boundary, by which time the speculative submit above
                    # has usually landed
                    ctl.note_deferred()
                    apply = False
            if apply:
                rplan = build_remainder_plan(
                    cur_pl, s_switch - cur_off, dec.candidate_batches,
                    bucket=self.cfg.bucket,
                )
                # the append is program-order safe only while the launch
                # frontier still sits at j: gather threads resolve steps
                # >= s_switch through this table, and only the controller
                # thread advances the frontier — assert that contract
                # instead of assuming it (G019 quiesce-discipline family)
                assert pipe.next_unlaunched() == j, (
                    "window rebalance raced the transfer pipeline: launch "
                    f"frontier moved {j} -> {pipe.next_unlaunched()} "
                    "during the solve"
                )
                seg_plans.append((s_switch, rplan))
                self.shares = np.asarray(dec.candidate_shares, dtype=np.float64)
                # the MEASURED switch cost covers the whole evaluation-to-
                # apply wall (device sync, signal build, solve, candidate
                # staging, remainder re-slice) — the host price an extra
                # switch actually pays. The plan build alone is microseconds
                # and would hollow out the margin/budget gates from the
                # second switch on.
                ev = ctl.commit(
                    dec,
                    time.perf_counter() - t_eval0,
                    epoch=int(epoch),
                    window=int(j),
                    step=int(s_switch),
                )
                self._rebalance_events.append(ev)
                self.recorder.meta["rebalance_events"] = self._rebalance_events
            if self.timing_model is None:
                # prediction for the stretch about to run, under the plan
                # that will actually govern it (the switched segment when
                # one was just applied) — next evaluation's feedback
                # reference
                nxt_pl, _ = self._seg_for_step(seg_plans, ranges[j][0])
                groups_list = [
                    self.topology.groups[d]
                    for d in self.topology.used_device_indices
                ]
                eval_state["pred_step"] = step_time(
                    eff, np.asarray(nxt_pl.batch_sizes, dtype=np.float64),
                    groups_list,
                )

    @staticmethod
    def _seg_for_step(seg_plans, s: int):
        """The (plan, step_offset) governing absolute epoch step ``s``:
        segments are (start_step, plan) in increasing order; a plan's local
        step index is ``s - start_step``."""
        pl, off = seg_plans[0][1], seg_plans[0][0]
        for start, p in seg_plans:
            if s >= start:
                pl, off = p, start
        return pl, off

    def _run_elastic_windows(
        self, plan, seg_plans, ranges, wkeys, faults: EpochFaults, epoch: int,
        aux_acc: List, aux_windows: List, aot_needed=(), controller=None,
    ):
        """The elastic window loop over an (extensible) segment schedule:
        gather/stage window k+1 on the transfer pipeline while window k
        dispatches, with each window's plan resolved through ``seg_plans``
        — the table a mid-epoch switch appends to for windows not yet
        staged. Shared by the epoch path and the switch-parity replay
        helper so both dispatch through identical machinery. Returns the
        first window's host data (the probes reuse it)."""
        cfg = self.cfg
        topo = self.topology
        mode = self._elastic_mode()
        meter = self._host_meter
        groups = topo.groups
        dev_order = topo.used_device_indices
        use_cache = self._use_device_cache

        def gather_window(s0: int, s1: int):
            # segment lookup by STEP: gather runs on pipeline threads, but
            # seg_plans only ever grows for windows the pipeline has not
            # launched yet — ordered by the executor's submit, program-order
            # safe (same discipline as _reshard_world's quiesced writes)
            pl, off = self._seg_for_step(seg_plans, s0)
            return [
                self._worker_inputs(
                    pl, self.rank_lo + r, s0 - off, s1 - off,
                    as_indices=use_cache,
                )
                for r in range(self.ws_local)
            ]

        def stage_window(d: int, i: int, data):
            """One device's puts for one window: each worker's arrays plus
            that window's absolute-step rng keys. Runs on the pipeline's
            per-device threads, concurrently across devices and with the
            controller's dispatch of the previous window."""
            w0, w1 = ranges[i]
            dev = topo.devices[d]
            staged = {}
            for r in groups[d]:
                gr = self.rank_lo + r
                kwin = wkeys[np.arange(w0, w1) * self.world_size + gr]
                staged[r] = tuple(
                    jax.device_put(a, dev) for a in data[r]
                ) + (jax.device_put(kwin, dev),)
            return staged

        # Per-worker constants for the whole epoch: one transfer, not one
        # per step (each device_put is a host round trip — 5 puts/worker/
        # step was most of the elastic path's dispatch overhead). Under a
        # time-varying schedule the values re-stage per window below.
        slow_dev = {}
        slow_vals: Dict[int, int] = {}
        for d in dev_order:
            dev = topo.devices[d]
            for r in groups[d]:
                gr = self.rank_lo + r
                slow_vals[r] = int(faults.slow_iters_per_step[gr])
                slow_dev[r] = jax.device_put(jnp.int32(slow_vals[r]), dev)
        time_varying = (
            getattr(self.injector, "faults_at", None) is not None
            and self._needs_iter_cost
        )

        eval_state = {"t": time.perf_counter(), "step": 0}
        first_data = None
        # Streaming host path, double-buffered per device: window k+1's host
        # gather AND its per-device puts run on the transfer pipeline while
        # window k dispatches/executes (train/pipeline.py). Window-local
        # rows, absolute-step rng keys — identical math to the whole-epoch
        # gather. Peak host memory: two windows, not the epoch.
        with WindowTransferPipeline(
            ranges, gather_window, stage_window, dev_order, meter=meter
        ) as pipe:
            # published for _quiesce_pipeline (G019): a recovery path
            # entered while this epoch's pipeline is live must drain it
            # before mutating the topology fields its threads read
            self._live_pipeline = pipe
            # kick window 0's gather/puts, then drain the compile barrier
            # while the staging threads work — compile time and transfer
            # time overlap instead of stacking
            pipe.prefetch(0)
            self._aot_wait_needed(aot_needed, epoch)
            for i, (w0, w1) in enumerate(ranges):
                # liveness at every window boundary: a mid-epoch preemption
                # is detected (and the epoch abandoned for re-solve) within
                # detect_misses windows, not at the next epoch
                self._check_health(epoch, w0 / max(plan.num_steps, 1))
                data, staged = pipe.get(i)
                if first_data is None:
                    first_data = data
                pl, _ = self._seg_for_step(seg_plans, w0)
                if time_varying:
                    # re-stage compute-mode injection at the window's
                    # instantaneous schedule value (scalar puts, only on
                    # change) — the injected load follows the schedule at
                    # window granularity, not the epoch mean
                    t_mid = float(epoch) + (w0 + w1) / (
                        2.0 * max(plan.num_steps, 1)
                    )
                    wf = self._window_faults_at(t_mid, pl)
                    if wf is not None:
                        for d in dev_order:
                            for r in groups[d]:
                                gr = self.rank_lo + r
                                v = int(wf.slow_iters_per_step[gr])
                                if slow_vals.get(r) != v:
                                    slow_vals[r] = v
                                    slow_dev[r] = jax.device_put(
                                        jnp.int32(v), topo.devices[d]
                                    )
                # one span per window (not per step): the dispatch track in
                # the trace shows window boundaries without per-step cost
                with self._trace.span("dispatch_window", cat="dispatch"):
                    if mode == "scan":
                        d0 = dev_order[0]
                        win_key = topo.group_shape_key(
                            [pl.workers[self.rank_lo + r].padded_batch
                             for r in groups[d0]],
                            w1 - w0,
                        )
                        self._dispatch_superstep_window(
                            staged[d0], d0, groups[d0], win_key, slow_dev,
                            aux_windows,
                        )
                    else:
                        self._dispatch_combine_steps(
                            staged, w1 - w0, slow_dev, aux_acc,
                            windowed=(mode == "window"),
                        )
                if controller is not None and (i + 1) % cfg.rebalance_every == 0:
                    self._maybe_window_rebalance(
                        controller, plan, seg_plans, ranges, pipe, i, epoch,
                        aux_acc, aux_windows, eval_state,
                    )
        # normal exit: the context manager already drained the pool; drop
        # the reference so _quiesce_pipeline skips the redundant close. On
        # exception paths the reference survives deliberately — recovery's
        # _reshard_world drains through it before touching topology.
        self._live_pipeline = None
        return first_data

    def _replay_window_segment(
        self, base_plan, rplan, s_offset: int, epoch: int, faults: EpochFaults
    ):
        """TEST/DEBUG: dispatch ONLY the remainder segment of an epoch from
        the CURRENT state — the 'fresh run started on the new plan from the
        same state' reference leg of the mid-epoch switch-parity contract
        (tests/test_online_dbs.py). Uses the same window loop, rng-key
        stream (absolute step indices over the BASE plan's step count) and
        dispatch machinery as the in-epoch switch path."""
        cfg = self.cfg
        base_key = jax.random.PRNGKey(cfg.seed * 7919 + epoch)
        wkeys = jax.random.split(
            base_key, self.world_size * max(base_plan.num_steps, 1)
        )
        ranges = [
            w for w in self._elastic_ranges(base_plan.num_steps)
            if w[0] >= s_offset
        ]
        aux_acc: List = []
        aux_windows: List = []
        self._run_elastic_windows(
            base_plan, [(s_offset, rplan)], ranges, wkeys, faults, epoch,
            aux_acc, aux_windows,
        )
        jax.block_until_ready(self.state.params)
        for aux in aux_windows:
            aux_acc.extend(np.asarray(aux, dtype=np.float64).reshape(-1, 4))
        return aux_acc

    def _train_epoch_elastic(self, plan, faults: EpochFaults, epoch: int) -> Dict[str, float]:
        cfg = self.cfg
        topo = self.topology
        self.timekeeper.reset()
        mode = self._elastic_mode()
        meter = self._host_meter
        meter.reset()

        # Local topo ranks r (0..ws_local-1) own global worker rank_lo + r.
        aux_acc: List = []
        aux_windows: List = []  # scan mode: [win, n_workers, 4] per window
        sync_probe = 0.0
        base_key = jax.random.PRNGKey(cfg.seed * 7919 + epoch)
        wkeys = jax.random.split(base_key, self.world_size * max(plan.num_steps, 1))

        use_cache = self._use_device_cache
        ranges = self._elastic_ranges(plan.num_steps)

        # AOT service: queue this plan's missing executables (concurrent
        # background compiles) + speculative adjacent rungs; the barrier
        # below overlaps with the first window's staging.
        aot_needed = self._aot_stage_plan(plan)

        # Segment schedule: the whole epoch under the boundary plan, until
        # the online controller (rebalance=window) appends a remainder
        # segment at a mid-epoch switch.
        seg_plans: List = [(0, plan)]
        first_data = self._run_elastic_windows(
            plan, seg_plans, ranges, wkeys, faults, epoch,
            aux_acc, aux_windows, aot_needed=aot_needed,
            controller=self._window_controller(),
        )
        if mode == "scan":
            # flatten the scanned aux back into the per-step path's exact
            # (step, worker) row order so the float64 metric summation below
            # reproduces per-step results bit for bit
            for aux in aux_windows:
                aux_acc.extend(np.asarray(aux, dtype=np.float64).reshape(-1, 4))
            cache_n = self.steps.superstep_cache_size()
            if cache_n > len(self._superstep_keys):
                self.logger.warning(
                    f"Epoch {epoch}: {cache_n} compiled superstep variants "
                    f"exceed the {len(self._superstep_keys)} dispatched "
                    "(shape, window) keys — a superstep input fell off its "
                    "static layout (graftlint G003/G006)"
                )
        data = first_data  # probes below reuse the first window's batches

        jax.block_until_ready(self.state.params)
        heartbeat()  # epoch pipeline drained
        # Probe AFTER the epoch's async pipeline has drained, so per-worker
        # timings measure that worker's executable alone, not queueing noise.
        # Compute-mode fault injection needs the probes too (per-example cost
        # calibration), even with the balancer off — otherwise a dbs-off A/B
        # arm would silently run without its injected straggler.
        dbs_probe_cost = 0.0
        if (
            self.timing_model is None
            and self._probe_this_epoch
            and (cfg.dynamic_batch_size or self._needs_iter_cost)
        ):
            t0p = time.perf_counter()
            with self._trace.span("probe", cat="probe"):
                sync_probe = self._probe_workers(plan, data, faults, epoch)
            dbs_probe_cost = time.perf_counter() - t0p
            self._sync_per_step = sync_probe
            # Replicated-state flag: everyone probes epoch 0 (pure config +
            # epoch), so gating later collectives on it can never diverge
            # across hosts even though LATER probe decisions are local.
            self._probes_ran = True
        else:
            sync_probe = self._sync_per_step
        if self.timing_model is not None:
            modeled = np.asarray(self.timing_model(plan), dtype=np.float64)
            for r in range(self.world_size):
                self.timekeeper.add_compute(r, modeled[r])
        for r in range(self.world_size):
            self.timekeeper.add_injected(r, float(faults.virtual_seconds[r]))

        flops_probe_overhead = 0.0
        if self._flops_per_padded_example is None:
            from dynamic_load_balance_distributeddnn_tpu.obs.flops import (
                compiled_flops,
            )

            # Cost analysis reads the ALREADY-COMPILED executable from the
            # AOT service when it holds this rung (zero extra compiles);
            # the lower+compile fallback only runs with the service off.
            # Excluded from the epoch wall either way (mirrors the fused
            # path's probe_overhead).
            t0 = time.perf_counter()
            d0 = topo.used_device_indices[0]
            r0 = topo.groups[d0][0]
            views = shard_views(self.state.params, topo.devices)
            b_pad = int(data[r0][0].shape[1])
            kind = "worker_first_idx" if use_cache else "worker_first"
            pre = None
            if self._aot is not None:
                pre = self._aot.get(self._aot_step_key(kind, b_pad, d0, None))
            if use_cache:
                idx0, w = data[r0]
                f = compiled_flops(
                    self.steps.worker_step_first_idx,
                    views[d0],
                    *self._device_cache_for(d0),
                    jnp.asarray(idx0[0]), jnp.asarray(w[0]),
                    base_key, jnp.int32(0),
                    compiled=pre,
                )
            else:
                x, y, w = data[r0]
                f = compiled_flops(
                    self.steps.worker_step_first,
                    views[d0],
                    jnp.asarray(x[0]), jnp.asarray(y[0]), jnp.asarray(w[0]),
                    base_key, jnp.int32(0),
                    compiled=pre,
                )
            self._flops_per_padded_example = f / max(b_pad, 1) if f else -1.0
            flops_probe_overhead = time.perf_counter() - t0

        wloss = float(np.sum([float(a[0]) for a in aux_acc]))
        loss_sum = float(np.sum([float(a[1]) for a in aux_acc]))
        count = float(np.sum([float(a[2]) for a in aux_acc]))
        if self.n_proc > 1:
            # Per-process partial sums -> global (per-epoch metadata, host path)
            from jax.experimental import multihost_utils

            sums = multihost_utils.process_allgather(
                np.array([wloss, loss_sum, count], dtype=np.float64)
            )
            wloss, loss_sum, count = np.asarray(sums).reshape(-1, 3).sum(axis=0)
        return {
            "loss": loss_sum / max(count, 1.0),
            "wloss": wloss / max(plan.num_steps, 1),
            "sync_time": sync_probe * plan.num_steps,
            "probe_overhead": flops_probe_overhead,
            # run_epoch excludes this from epoch_wall (all paths) and
            # accounts it under total_probe_s / the probe_time series —
            # do NOT subtract it again anywhere downstream
            "dbs_probe_cost": dbs_probe_cost,
            # host-side cost of driving the epoch (enqueue + transfer walls,
            # balance/timing.py HostOverheadMeter) — the quantity the
            # superstep path exists to shrink; bench.py reports the
            # per-step value as its dispatch-overhead A/B field
            "host_dispatch_s": meter.dispatch_s,
            "host_put_s": meter.put_s,
            "host_overhead_per_step_s": meter.per_step(plan.num_steps),
        }

    def _probe_workers(
        self, plan, data, faults: EpochFaults, epoch: int, reps: int = 3
    ) -> float:
        """Time each worker's step standalone (blocking, min over ``reps``)
        plus one combine — the balancer's signal. Called after the epoch's
        dispatch queue has drained. A full untimed warm pass runs first so
        every shape is compiled before any timing starts — otherwise a
        background compile of one worker's fresh shape contaminates another
        worker's host-side wall clock."""
        topo = self.topology
        cfg = self.cfg
        use_cache = self._use_device_cache
        key = jax.random.PRNGKey(cfg.seed * 104729 + epoch)
        views = shard_views(self.state.params, topo.devices)
        probe_step = (
            self.steps.worker_step_first_idx
            if use_cache
            else self.steps.worker_step_first
        )
        probe_kind = "worker_first_idx" if use_cache else "worker_first"
        staged = {}
        for d in topo.used_device_indices:
            dev = topo.devices[d]
            for r in topo.groups[d]:
                gr = self.rank_lo + r
                cache = self._device_cache_for(d) if use_cache else ()
                # AOT-compiled probe executable when the service holds this
                # rung (warm/stage submitted it); lazy jit otherwise
                b = int(data[r][0].shape[1])
                fn = self._aot_resolve(probe_kind, b, d, None, probe_step)
                staged[r] = (
                    cache
                    + tuple(jax.device_put(a[0], dev) for a in data[r])
                    + (
                        jax.device_put(key, dev),
                        jax.device_put(
                            jnp.int32(faults.slow_iters_per_step[gr]), dev
                        ),
                    ),
                    d,
                    fn,
                )
        # warm pass: execute everything once, untimed (with the AOT service
        # this compiles nothing — the executables already exist)
        for r, (args, d, fn) in staged.items():
            _, aux = fn(views[d], *args)
            jax.block_until_ready(aux)
            heartbeat()

        # Dispatch-overhead floor (config.probe_overhead_correction): every
        # blocking probe wall includes one dispatch+sync round trip that is
        # NOT per-example device compute — O(100us) locally, ~66 ms over the
        # axon tunnel (artifacts/STEPTIME_tpu.json round-5 measurement).
        # Measure it per device with a tiny jitted op under BOTH sync
        # disciplines a probe may hit (block_until_ready and a scalar
        # readback) and take the MIN, so the correction can only be
        # conservative; the subtraction below is additionally floored at 20%
        # of the raw wall so a pathological overhead estimate can never
        # zero out a real measurement.
        ovh_by_dev: dict = {}
        if getattr(cfg, "probe_overhead_correction", True):
            for d in topo.used_device_indices:
                tx = jax.device_put(jnp.float32(0.0), topo.devices[d])
                y = _tiny_sync_probe(tx)
                jax.block_until_ready(y)
                float(y)  # compile + warm both sync paths
                e_block = e_read = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    jax.block_until_ready(_tiny_sync_probe(tx))
                    e_block = min(e_block, time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    float(_tiny_sync_probe(tx))
                    e_read = min(e_read, time.perf_counter() - t0)
                ovh_by_dev[d] = min(e_block, e_read)
            self._probe_overhead_s = max(ovh_by_dev.values())
            # sanctioned bare wall: the dispatch-overhead estimate IS a raw
            # min-over-reps perf_counter pair by construction (a span cannot
            # express the paired-min discipline), and it is provenance
            # metadata, not a timed phase
            self.recorder.meta["probe_dispatch_overhead_s"] = round(  # graftlint: disable=G008
                self._probe_overhead_s, 6
            )

        def timed(d: int, args2, fn=probe_step):
            """(corrected wall, raw wall, last partial) of one probe step:
            min-over-reps blocking wall, minus the device's dispatch overhead
            for the corrected value. PAIRED measurements (the closed-loop
            iteration-cost tracking and _calibrate_iter_cost) must difference
            the RAW walls: the correction's 0.2*dt floor binds only on the
            small (clean) leg of a pair, so differencing corrected values
            re-introduces exactly the overhead the pairing exists to cancel.
            Standalone anchors (per-example cost, the solver's time vector)
            keep the corrected value."""
            dt, acc = float("inf"), None
            for _ in range(reps):
                t0 = time.perf_counter()
                acc, aux = fn(views[d], *args2)
                jax.block_until_ready(aux)
                dt = min(dt, time.perf_counter() - t0)
            heartbeat()
            return max(dt - ovh_by_dev.get(d, 0.0), 0.2 * dt), dt, acc

        lo, hi = self.rank_lo, self.rank_lo + self.ws_local
        init_epoch = bool(np.isnan(self.per_example_cost[lo:hi]).any())
        partials = {}
        for d in topo.used_device_indices:
            acc = None
            for r in topo.groups[d]:
                args, _, fn = staged[r]
                gr = self.rank_lo + r
                # probe with the non-donating first-step executable so reps
                # are safe; each worker is measured standalone
                dt, dt_raw, acc = timed(d, args, fn)
                # the probe wall doubles as the health monitor's latency
                # signal (original-rank indexed; SUSPECT verdicts feed the
                # degradation-ladder observability, the solver already
                # re-routes)
                self.health.observe_latency(self.active_ranks[gr], dt)
                w_plan = plan.workers[gr]
                self.timekeeper.add_compute(gr, dt * w_plan.steps)
                slow_n = float(faults.slow_iters_per_step[gr])
                if np.isnan(self.per_example_cost[gr]):
                    # First (injection-free) measurement seeds the clean
                    # cost; the refresh pass below re-anchors it fully warm,
                    # then it stays frozen. Re-deriving it every epoch by
                    # subtracting estimated injected cost is a positive
                    # feedback loop: any underestimate of the in-step
                    # iteration cost inflates "clean", which inflates next
                    # epoch's injection, without bound.
                    self.per_example_cost[gr] = max(dt, 1e-9) / max(
                        w_plan.batch_size, 1
                    )
                elif slow_n > 0 and not self._iter_cost_calibrated:
                    # Closed-loop iteration-cost tracking, ONLY until the
                    # fixed-point calibration has run. Two lessons from the
                    # round-3 TPU A/B (off-arm walls ramped 1.8->2.5s over 5
                    # "equal-injection" epochs):
                    #  - realized cost must come from a PAIRED measurement
                    #    (injected minus fresh-uninjected, below), not from
                    #    the frozen epoch-0 clean anchor: session drift
                    #    (tunnel RPC latency settling, chip clocks) between
                    #    the anchor and dt otherwise leaks into the estimate
                    #    and the EMA pumps slow_n without bound;
                    #  - once calibrated, the cost stays FROZEN so every
                    #    counted epoch injects the same strength — the A/B
                    #    contract the bench asserts per arm.
                    zero = jax.device_put(jnp.int32(0), topo.devices[d])
                    _, raw_clean, _ = timed(d, args[:-1] + (zero,), fn)
                    # raw-minus-raw: the per-probe dispatch overhead appears
                    # in both walls and cancels; corrected values would pair
                    # a floored clean leg against an unfloored injected leg
                    realized = (dt_raw - raw_clean) / slow_n
                    if realized > 0 and np.isfinite(realized):
                        prev = self._iter_cost_s or realized
                        self._iter_cost_s = 0.5 * prev + 0.5 * realized
                elif slow_n == 0:
                    # Uninjected re-probe: drift the clean-cost anchor slowly
                    # toward reality so the adaptive scheduler's model tracks
                    # genuine speed changes. No feedback risk — injected
                    # measurements never enter this branch (explicitly gated:
                    # an injected dt leaking in here compounds into runaway
                    # slow_iters), so the calibration anchor stays clean.
                    fresh = max(dt, 1e-9) / max(w_plan.batch_size, 1)
                    self.per_example_cost[gr] = (
                        0.7 * self.per_example_cost[gr] + 0.3 * fresh
                    )
            partials[d] = acc
        if init_epoch:
            # Anchor-refresh pass: the very first timed probes run cold
            # (allocator, host caches, tunnel RPC settling) and over-read the
            # clean cost ~2x (measured on both the CPU mesh and the TPU
            # tunnel). One more pass, now fully warm, re-anchors every
            # uninjected worker BEFORE the calibration sizes the injection
            # off these anchors — otherwise the straggler factors are scaled
            # against an inflated "clean" and overshoot for the whole run
            # (anchors freeze after this epoch).
            for d in topo.used_device_indices:
                for r in topo.groups[d]:
                    gr = self.rank_lo + r
                    args, _, fn = staged[r]
                    if float(faults.slow_iters_per_step[gr]) != 0:
                        # a worker can be injected on its very first probed
                        # epoch (LuckyFaultInjector seeds iter cost from the
                        # standalone estimate) — its anchor was seeded from a
                        # cold AND injected dt; re-anchor on a zero-slow probe
                        zero = jax.device_put(jnp.int32(0), topo.devices[d])
                        args = args[:-1] + (zero,)
                    dt, _, _ = timed(d, args, fn)
                    self.per_example_cost[gr] = max(dt, 1e-9) / max(
                        plan.workers[gr].batch_size, 1
                    )
        if (
            self._needs_iter_cost
            and not self._iter_cost_calibrated
            and float(np.max(faults.slow_iters_per_step)) == 0
        ):
            # Converge the in-step iteration cost on the injection-free epoch,
            # BEFORE the first injected epoch. Without this, injection ramps
            # up over the first few epochs as the closed loop corrects the
            # standalone seed estimate — and an A/B benchmark would compare
            # arms at different injection strengths (the early weak-injection
            # epochs win every min(), systematically favoring whichever arm
            # sampled more of them).
            self._calibrate_iter_cost(staged, timed, plan)
            self._iter_cost_calibrated = True
        stacked = stack_partials(
            [partials[d] for d in topo.used_device_indices], self.mesh
        )
        # warm (compile) untimed, then time the pure collective+update; the
        # combine twin resolves from the AOT registry (warm-submitted) so the
        # warm call is a dispatch, not a lazy compile
        probe_name = self._combine_names()[1]
        combine_probe = self._aot_resolve_combine(
            probe_name, getattr(self.steps, probe_name)
        )
        jax.block_until_ready(combine_probe(self.state, stacked).params)
        t0 = time.perf_counter()
        probed = combine_probe(self.state, stacked)
        jax.block_until_ready(probed.params)
        return time.perf_counter() - t0

    def _calibrate_iter_cost(self, staged, timed, plan) -> None:
        """Fixed-point iteration for the in-step synthetic-load cost: probe a
        step with a test trip count sized to ~double the clean step time,
        measure the realized per-iteration cost, and repeat until stable
        (each realized measurement IS the quantity being estimated, so this
        converges in 1-2 rounds). ``timed`` is _probe_workers' own probe
        timer, so calibration measures EXACTLY like the per-epoch tracking
        path — an asymmetry between the two is the kind of drift that caused
        the round-3 injection ramp. Runs on one worker, a handful of probe
        steps — calibration-epoch overhead only."""
        r0 = next(iter(staged))
        args, d, fn = staged[r0]
        gr = self.rank_lo + r0
        clean = float(self.per_example_cost[gr]) * max(
            plan.workers[gr].batch_size, 1
        )
        if not np.isfinite(clean) or clean <= 0:
            return
        dev = self.topology.devices[d]
        guess = self._iter_cost_s or calibrate_iter_cost()

        def timed_probe(slow_n: int) -> float:
            test_args = args[:-1] + (jax.device_put(jnp.int32(slow_n), dev),)
            # RAW wall: both legs of the paired delta below carry the same
            # dispatch overhead, so it cancels; the corrected value's 0.2*dt
            # floor fires only on the short clean leg and would bias the pair
            return timed(d, test_args, fn)[1]

        for _ in range(4):
            slow_n = max(int(round(clean / max(guess, 1e-12))), 1)
            # PAIRED measurement: a fresh uninjected step in the same breath,
            # so the delta isolates the synthetic load from session drift
            # (the frozen epoch-0 clean anchor bakes in early-session tunnel
            # latency — subtracting it mis-measured the realized cost ~3x on
            # the round-3 TPU run and the closed loop ramped injection).
            dt = timed_probe(slow_n)
            dt_clean = timed_probe(0)
            realized = (dt - dt_clean) / slow_n
            if realized <= 0 or not np.isfinite(realized):
                break
            done = abs(realized - guess) <= 0.05 * guess
            guess = realized
            if done:
                break
        self._iter_cost_s = guess
        self.logger.info(
            f"injection calibrated: {guess * 1e6:.2f}us/iter (in-step)"
        )

    # ------------------------------------------------------------- validate

    def _eval_sharded(self, xs, ys, mask=None, per_dev_cap: int = 1024,
                      cache_tag: Optional[str] = None):
        """Run ``fused_eval_step`` over the mesh on (xs, ys) in fixed-shape
        chunks (one compile), each chunk split across every device.
        ``mask``: optional per-element weight array (e.g. the LM's per-token
        mask, [n, bptt]); default is a per-row validity mask. Returns
        (loss_sum, correct, count)."""
        n = len(xs)
        # Evenly split the ceil'd chunk count so the final chunk wastes less
        # than one padded row per device (vs up to chunk-1 rows with a naive
        # cap-sized chunk), while keeping a single compiled shape.
        n_chunks = max(-(-n // (per_dev_cap * self.n_dev)), 1)
        per_dev = max(-(-n // (self.n_dev * n_chunks)), 1)
        chunk = per_dev * self.n_dev
        from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import batch_sharding

        bx = self._batch_axes

        def put(arr):
            if self.n_proc == 1:
                return jax.device_put(
                    arr, batch_sharding(self.mesh, arr.ndim, axis=bx)
                )
            rows = chunk // self.n_proc
            lo_p = self.proc_id * rows
            return jax.make_array_from_process_local_data(
                batch_sharding(self.mesh, arr.ndim, axis=bx),
                arr[lo_p : lo_p + rows],
            )

        # With the device cache on and a caller-declared stable input set
        # (cache_tag), the padded+sharded chunks upload once and are reused
        # every epoch — the reference re-walks its val DataLoader per epoch
        # on every rank (dbs.py:147). Untagged or cache-off calls stream one
        # chunk at a time (bounded HBM), exactly as before.
        cache_ok = self._use_device_cache and cache_tag is not None
        key = (cache_tag, chunk, n)
        cached = getattr(self, "_eval_chunk_cache", None)
        staged = None
        if cache_ok and cached is not None and cached[0] == key:
            staged = cached[1]
        elif cached is not None:
            # release before any restaging (drop BOTH references — the local
            # would otherwise pin the old chunk set in HBM through the loop)
            self._eval_chunk_cache = None
            cached = None

        loss_sum = correct = count = 0.0

        def run_chunk(xb, yb, mb):
            nonlocal loss_sum, correct, count
            stats = self.steps.fused_eval_step(self.state.params, xb, yb, mb)
            stats = np.asarray(jax.block_until_ready(stats))
            heartbeat()
            loss_sum += float(stats[0])
            correct += float(stats[1])
            count += float(stats[2])

        if staged is not None:
            for xb, yb, mb in staged:
                run_chunk(xb, yb, mb)
            return loss_sum, correct, count

        keep = [] if cache_ok else None
        for lo in range(0, n, chunk):
            hi = min(lo + chunk, n)
            pad = chunk - (hi - lo)
            xb = np.pad(xs[lo:hi], ((0, pad),) + ((0, 0),) * (xs.ndim - 1))
            yb = np.pad(ys[lo:hi], ((0, pad),) + ((0, 0),) * (ys.ndim - 1))
            if mask is None:
                mb = np.zeros(chunk, dtype=np.float32)
                mb[: hi - lo] = 1.0
            else:
                mb = np.pad(mask[lo:hi], ((0, pad),) + ((0, 0),) * (mask.ndim - 1))
            dx, dy, dm = put(xb), put(yb), put(mb)
            if keep is not None:
                keep.append((dx, dy, dm))
            run_chunk(dx, dy, dm)
        if keep is not None:
            self._eval_chunk_cache = (key, keep)
        return loss_sum, correct, count

    def validate(self) -> "tuple[float, float]":
        """Full-test-set loss/accuracy, sharded over the mesh (the reference
        redundantly evaluates the full test set on EVERY rank, dbs.py:141-161;
        here it is evaluated once, split across all devices — same math)."""
        loss_sum, correct, count = self._eval_sharded(
            self.bundle.test_x, self.bundle.test_y, cache_tag="vision_test"
        )
        return loss_sum / max(count, 1.0), 100.0 * correct / max(count, 1.0)
