"""Learning-rate schedule: the reference's "one-cycle policy".

In the reference, the warm-up phase is commented out (dbs.py:206-208) and only
the final-30% decay branch is live; that branch contains an evident typo
(``epoch - 0.7 * epoch`` for ``epoch - 0.7 * epoch_size``, dbs.py:210) that
makes the decay discontinuous. This implementation follows the *documented*
behavior (dbs.py:195-199): constant base LR, then a linear decay over the last
30% of epochs down to 0.01x — i.e. the live branch with the typo fixed.
Disabled entirely under `-de` (dbs.py:202-203).
"""

from __future__ import annotations


def one_cycle_lr(
    base_lr: float,
    epoch: int,
    epoch_size: int,
    enabled: bool = True,
    disable_enhancements: bool = False,
) -> float:
    if not enabled or disable_enhancements:
        return base_lr
    start = 0.7 * epoch_size
    if epoch >= start:
        frac = (epoch - start) / (0.3 * epoch_size)
        return base_lr - 0.99 * base_lr * frac
    return base_lr
