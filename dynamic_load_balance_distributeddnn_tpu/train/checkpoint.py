"""Checkpoint / resume.

The reference has no model checkpointing at all (SURVEY §5.4) — persistence is
a rank-0 metrics dump plus a log-file idempotence probe. This module is the
deliberate capability upgrade: orbax-backed checkpoints of the TrainState plus
a JSON sidecar with the DBS controller state (shares, node_times, wallclock),
so a resumed run continues balanced exactly where it left off.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


def _manager(ckpt_dir: str):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(ckpt_dir),
        options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
    )


def save_checkpoint(ckpt_dir: str, epoch: int, state, controller: Dict[str, Any]) -> None:
    """controller: shares / node_times / total_wallclock (JSON-serializable)."""
    import orbax.checkpoint as ocp

    mgr = _manager(ckpt_dir)
    mgr.save(epoch, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()
    if jax.process_index() != 0:
        # orbax coordinates the distributed array save across processes; the
        # controller sidecar is replicated host state, written once.
        return
    clean = {
        k: (np.asarray(v).tolist() if not np.isscalar(v) else float(v))
        for k, v in controller.items()
    }
    with open(os.path.join(ckpt_dir, f"controller_{epoch}.json"), "w") as f:
        json.dump(clean, f)


def restore_checkpoint(
    ckpt_dir: str, state_template
) -> Optional[Tuple[int, Any, Dict[str, Any]]]:
    """Returns (last_saved_epoch, state, controller) or None if absent.
    ``state_template`` is a live TrainState with the target shapes/shardings
    (the freshly initialized one)."""
    import orbax.checkpoint as ocp

    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    step = mgr.latest_step()
    if step is None:
        mgr.close()
        return None
    abstract = jax.tree_util.tree_map(
        ocp.utils.to_shape_dtype_struct, state_template
    )
    state = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    mgr.close()
    # Re-place every leaf onto the live template's sharding: orbax restores
    # values, but default placement (single-device scalars) would poison the
    # next jit with mixed device sets — params must come back replicated over
    # the mesh and the ZeRO-1 trace sharded along it.
    state = jax.tree_util.tree_map(
        lambda restored, tmpl: jax.device_put(
            restored, getattr(tmpl, "sharding", None)
        ),
        state,
        state_template,
    )
    controller: Dict[str, Any] = {}
    side = os.path.join(ckpt_dir, f"controller_{step}.json")
    if os.path.exists(side):
        with open(side) as f:
            controller = json.load(f)
    return step, state, controller
