"""Checkpoint / resume.

The reference has no model checkpointing at all (SURVEY §5.4) — persistence is
a rank-0 metrics dump plus a log-file idempotence probe. This module is the
deliberate capability upgrade: orbax-backed checkpoints of the TrainState plus
a JSON sidecar with the DBS controller state (shares, node_times, wallclock),
so a resumed run continues balanced exactly where it left off.

Manager lifecycle (ISSUE 6 satellite): one ``CheckpointManager`` is cached
per ``ckpt_dir`` for the life of the process — the old per-save
construct → ``wait_until_finished`` → ``close`` cycle paid manager setup AND
a full blocking drain inside every epoch tail. Saves are now non-blocking
(orbax commits on its background thread; the epoch tail only enqueues);
:func:`flush_checkpoints` is the explicit drain, called at run end and
before any elastic re-shard — the two places a half-committed checkpoint
could be observed (by the next process, or by a recovery that resumes from
"the last consistent state").
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

# One manager per absolute ckpt_dir, process-wide. The lock guards dict
# access; SEQUENTIAL sharing of a dir (test fixtures, bench retry loops,
# resume-after-run) is fully safe — a close=True flush evicts the entry and
# the next _manager() call builds a fresh one. A save racing a concurrent
# trainer's close on the SAME dir is armored at the save site (evict +
# retry with a fresh manager), not prevented.
_MANAGERS: Dict[str, Any] = {}
_LOCK = threading.Lock()


def _manager(ckpt_dir: str):
    import orbax.checkpoint as ocp

    path = os.path.abspath(ckpt_dir)
    with _LOCK:
        mgr = _MANAGERS.get(path)
        if mgr is None:
            mgr = ocp.CheckpointManager(
                path,
                options=ocp.CheckpointManagerOptions(max_to_keep=3, create=True),
            )
            _MANAGERS[path] = mgr
    return mgr


def flush_checkpoints(ckpt_dir: Optional[str] = None, close: bool = False) -> None:
    """Block until every pending async save under ``ckpt_dir`` (all cached
    dirs when None) has committed. ``close=True`` additionally closes and
    evicts the manager(s) — end-of-run hygiene so long-lived processes
    (test tiers, bench loops) don't accumulate orbax thread pools."""
    with _LOCK:
        if ckpt_dir is None:
            items = list(_MANAGERS.items())
        else:
            path = os.path.abspath(ckpt_dir)
            mgr = _MANAGERS.get(path)
            items = [(path, mgr)] if mgr is not None else []
        if close:
            for path, _ in items:
                _MANAGERS.pop(path, None)
    for _, mgr in items:
        mgr.wait_until_finished()
        if close:
            mgr.close()


def save_checkpoint(
    ckpt_dir: str, epoch: int, state, controller: Dict[str, Any],
    block: bool = False,
) -> None:
    """controller: shares / node_times / total_wallclock (JSON-serializable).

    Non-blocking by default: the save is enqueued on the cached manager's
    async machinery and the call returns (the epoch tail stops paying the
    serialization wall). Callers that need durability NOW — end of run, the
    elastic recovery path about to mutate the fleet — pass ``block=True``
    or call :func:`flush_checkpoints`."""
    import orbax.checkpoint as ocp

    multihost = jax.process_count() > 1
    if multihost:
        payload = state
    else:
        # Async-safety: the engine's hot-path executables DONATE the state
        # buffers (steps.py donate_argnums), so an in-flight background save
        # reading the live jax arrays is a use-after-free once the next step
        # dispatches. Snapshot to host with a FORCED copy (on the CPU
        # backend np.asarray can alias the device buffer) and hand orbax the
        # copy — the epoch tail pays one host memcpy instead of the full
        # serialize-to-disk wall.
        payload = jax.tree_util.tree_map(
            lambda t: np.array(t, copy=True), jax.device_get(state)
        )

    def _save(mgr) -> None:
        mgr.save(epoch, args=ocp.args.StandardSave(payload))
        # multi-host leaves are not fully addressable: orbax must read the
        # live distributed arrays, so that save stays synchronous (the next
        # epoch's donating steps would otherwise reuse the buffers under it)
        if multihost or block:
            mgr.wait_until_finished()

    try:
        _save(_manager(ckpt_dir))
    except Exception:  # noqa: BLE001 — closed-manager race, see _MANAGERS
        # a concurrent trainer's flush_checkpoints(close=True) on the same
        # dir can close the cached manager between our fetch and save:
        # evict the entry, drain-and-close the old manager (its background
        # commit must not race the retry into the same step dir), and retry
        # once on a fresh one — a second failure is a real save error and
        # propagates
        with _LOCK:
            old = _MANAGERS.pop(os.path.abspath(ckpt_dir), None)
        if old is not None:
            try:
                old.wait_until_finished()
                old.close()
            except Exception:  # noqa: BLE001 — already-closed is the expected case
                pass
        _save(_manager(ckpt_dir))
    if jax.process_index() != 0:
        # orbax coordinates the distributed array save across processes; the
        # controller sidecar is replicated host state, written once.
        return
    clean = {
        k: (np.asarray(v).tolist() if not np.isscalar(v) else float(v))
        for k, v in controller.items()
    }
    with open(os.path.join(ckpt_dir, f"controller_{epoch}.json"), "w") as f:
        json.dump(clean, f)


def materialize(tree) -> None:
    """Block until every jax-array leaf of ``tree`` is materialized. Used
    at recovery/restore boundaries so an async transfer's failure surfaces
    AT the stage that dispatched it (attributable, retryable) instead of
    poisoning a later stage's launches."""
    jax.block_until_ready(
        [
            x
            for x in jax.tree_util.tree_leaves(tree)
            if hasattr(x, "block_until_ready")
        ]
    )


def restore_checkpoint(
    ckpt_dir: str, state_template, template_fn=None
) -> Optional[Tuple[int, Any, Dict[str, Any]]]:
    """Returns (last_saved_epoch, state, controller) or None if absent.
    ``state_template`` is a live TrainState with the target shapes/shardings
    (the freshly initialized one).

    ``template_fn``: optional ``controller_sidecar -> template-or-None``
    hook, consulted BEFORE the orbax restore. Needed by the elastic ZeRO-1
    composition (ISSUE 13): a checkpoint taken at a reduced fleet carries
    1/N optimizer chunks padded to the SURVIVOR device count's multiple, so
    the fresh full-world template's shapes would not match the saved
    arrays — the engine rebuilds a template at the saved fleet size from
    the sidecar's ``active_ranks`` stamp, restores into it, then re-chunks
    through the ordinary reshard path."""
    import orbax.checkpoint as ocp

    if not os.path.isdir(ckpt_dir):
        return None
    mgr = _manager(ckpt_dir)
    # a writer sharing this process (resume-after-loss tests, bench retry
    # loops) may still be committing — a half-committed latest step must
    # never be restored
    mgr.wait_until_finished()
    step = mgr.latest_step()
    if step is None:
        return None
    if template_fn is not None:
        side_pre = os.path.join(ckpt_dir, f"controller_{step}.json")
        sidecar: Dict[str, Any] = {}
        if os.path.exists(side_pre):
            with open(side_pre) as f:
                sidecar = json.load(f)
        adjusted = template_fn(sidecar)
        if adjusted is not None:
            state_template = adjusted
    abstract = jax.tree_util.tree_map(
        ocp.utils.to_shape_dtype_struct, state_template
    )
    state = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    # materialize the raw restore before the re-place copies: orbax's
    # transfers dispatch async, and surfacing their failure HERE (rather
    # than poisoning the re-place launches downstream) is what lets the
    # elastic recovery retry loop attribute and rebuild
    materialize(state)
    # Re-place every leaf onto the live template's sharding: orbax restores
    # values, but default placement (single-device scalars) would poison the
    # next jit with mixed device sets — params must come back replicated over
    # the mesh and the ZeRO-1 trace sharded along it. Single-process only:
    # FORCED copy into a jax-OWNED buffer first (same discipline as the
    # engine's elastic _state_from_host) — on the CPU backend device_put
    # can zero-copy alias the buffer the orbax restore machinery owns, and
    # the hot-path executables DONATE these leaves; donation of an aliased
    # buffer double-frees once the restore tree is collected (observed:
    # segfault in addressable_shards a few steps into the first post-resume
    # epoch, heap-layout dependent). Multi-host leaves span non-addressable
    # devices (a host materialization would raise), so they re-place
    # directly — orbax owns no host-side alias of a distributed array.
    import jax.numpy as jnp

    copy_first = jax.process_count() == 1
    state = jax.tree_util.tree_map(
        lambda restored, tmpl: jax.device_put(
            jnp.array(restored, copy=True) if copy_first else restored,
            getattr(tmpl, "sharding", None),
        ),
        state,
        state_template,
    )
    controller: Dict[str, Any] = {}
    side = os.path.join(ckpt_dir, f"controller_{step}.json")
    if os.path.exists(side):
        with open(side) as f:
            controller = json.load(f)
    return step, state, controller
