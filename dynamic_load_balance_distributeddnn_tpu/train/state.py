"""Training state: params + SGD(momentum) optimizer state.

The reference's optimizer is ``optim.SGD(lr, momentum=0.9)`` with a
per-epoch learning-rate override for the one-cycle policy (dbs.py:369,
193-215). Here optax's sgd is wrapped in ``inject_hyperparams`` so the learning
rate lives *in the optimizer state* and can be set per epoch without
recompiling the update step.

State is replicated over the data mesh: every device holds the full params
and momentum, as every reference worker does (dbs.py:365-369). (Sharding the
optimizer state ZeRO-style is an available upgrade; the mesh machinery does
not foreclose it.)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # global step counter

    def learning_rate(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        hp = dict(self.opt_state.hyperparams)
        new = jnp.asarray(lr, dtype=jnp.float32)
        old = hp["learning_rate"]
        # Preserve the old leaf's placement: a bare jnp.asarray is an
        # UNCOMMITTED array, which changes the state's pjit signature (the
        # replicated NamedSharding becomes UnspecifiedValue) and silently
        # forks a second compiled variant of every executable the state
        # feeds — the engine's warm-start work would never be reused.
        if getattr(old, "_committed", False):
            new = jax.device_put(new, old.sharding)
        hp["learning_rate"] = new
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hp))


def make_optimizer(learning_rate: float, momentum: float = 0.9) -> optax.GradientTransformation:
    return optax.inject_hyperparams(optax.sgd)(
        learning_rate=learning_rate, momentum=momentum
    )


class ShardedSGDState(NamedTuple):
    """SGD(momentum) state with the momentum buffer FLAT and SHARDED over the
    data mesh — cross-replica weight-update sharding (the TPU-native ZeRO-1
    analogue, after arXiv 2004.13336): each replica reduce-scatters gradients,
    updates only its 1/n shard of the momentum, and all-gathers the weight
    delta. Memory for optimizer state drops n_dev-fold; the update math is
    identical to the replicated ``optax.sgd``.

    Mimics ``inject_hyperparams``' state surface (``hyperparams`` dict +
    ``_replace``) so ``TrainState.with_learning_rate`` and the one-cycle
    schedule work unchanged."""

    hyperparams: dict          # {"learning_rate": scalar} — replicated
    momentum: jnp.ndarray      # scalar decay factor — replicated
    trace: jnp.ndarray         # [padded_total] flat momentum, P('data')-sharded
    count: jnp.ndarray         # step counter


def shard_optimizer_state(state: TrainState, mesh, momentum: float = 0.9) -> TrainState:
    """Convert a replicated-optax TrainState into the sharded-update form:
    the momentum trace becomes one flat zero vector (padded to a mesh-size
    multiple) sharded over the data axis. Fresh-start conversion (trace is
    zero at init, like the reference's SGD, dbs.py:369)."""
    import jax.flatten_util  # noqa: F401  (registers the submodule)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import DATA_AXIS

    flat, _ = jax.flatten_util.ravel_pytree(state.params)
    n = len(mesh.devices.flat)
    padded = -(-flat.size // n) * n
    trace = jax.device_put(
        jnp.zeros((padded,), jnp.float32), NamedSharding(mesh, P(DATA_AXIS))
    )
    # Scalars committed REPLICATED over the mesh (not default-device): this
    # state doubles as the restore template, and a single-device-committed
    # leaf would clash with the mesh-wide jit after checkpoint resume.
    rep = NamedSharding(mesh, P())
    opt_state = ShardedSGDState(
        hyperparams={
            "learning_rate": jax.device_put(
                jnp.asarray(
                    state.opt_state.hyperparams["learning_rate"], jnp.float32
                ),
                rep,
            )
        },
        momentum=jax.device_put(jnp.asarray(momentum, jnp.float32), rep),
        trace=trace,
        count=jax.device_put(jnp.zeros((), jnp.int32), rep),
    )
    return state.replace(opt_state=opt_state)


def create_state(
    module,
    example_input: jnp.ndarray,
    tx: optax.GradientTransformation,
    seed: int = 1234,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> TrainState:
    """Initialize params deterministically from ``seed`` (the analogue of the
    reference's torch.manual_seed(1234) + initial cross-worker param averaging
    dbs.py:329/365-367 — replication by construction instead of by allreduce)."""

    def init_fn(key):
        params = module.init({"params": key, "dropout": key}, example_input, train=False)
        opt_state = tx.init(params)
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    key = jax.random.PRNGKey(seed)
    if sharding is not None:
        state = jax.jit(init_fn, out_shardings=sharding)(key)
    else:
        state = jax.jit(init_fn)(key)
    return state
