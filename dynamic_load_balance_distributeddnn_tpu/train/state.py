"""Training state: params + SGD(momentum) optimizer state.

The reference's optimizer is ``optim.SGD(lr, momentum=0.9)`` with a
per-epoch learning-rate override for the one-cycle policy (dbs.py:369,
193-215). Here optax's sgd is wrapped in ``inject_hyperparams`` so the learning
rate lives *in the optimizer state* and can be set per epoch without
recompiling the update step.

State is replicated over the data mesh by default: every device holds the
full params and momentum, as every reference worker does (dbs.py:365-369).
With ``--shard_update`` the optimizer state is converted to the GENERIC
ZeRO-1 form (:func:`shard_optimizer_state`): the transform is
re-initialized on the flat padded parameter vector so every param-shaped
state piece becomes one 1/n-sharded chunk vector — any elementwise optax
transform, not just the SGD twin the pre-PR-13 path hand-rolled.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # global step counter
    # Error-feedback residuals of the tree compressed gradient collective
    # (ISSUE 12, N-level since ISSUE 17): a TUPLE with one row-block per
    # hop 0..k-1 of the topology tree (every hop except the innermost
    # always-fp32 one), outermost hop first. Entry i is [n_devices, W_i]
    # f32 — each device's accumulated quantization error on the vector it
    # carries across hop i (widths from parallel/wire.py tree_hop_widths) —
    # sharded one row per device over the tree mesh. fp32 hops keep their
    # entry (identically zero), so the state layout is codec-independent.
    # None (an empty pytree subtree — no leaf, no signature change) on every
    # non-hierarchical run; attached by attach_comm_residual when
    # --grad_comm hier resolves. Carried in the state so it donates/
    # checkpoints/restores with the weights — dropping it between steps
    # would silently discard the compression error the biased wires (int4)
    # rely on re-injecting.
    comm_residual: Any = None

    def learning_rate(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        hp = dict(self.opt_state.hyperparams)
        new = jnp.asarray(lr, dtype=jnp.float32)
        old = hp["learning_rate"]
        # Preserve the old leaf's placement: a bare jnp.asarray is an
        # UNCOMMITTED array, which changes the state's pjit signature (the
        # replicated NamedSharding becomes UnspecifiedValue) and silently
        # forks a second compiled variant of every executable the state
        # feeds — the engine's warm-start work would never be reused.
        if getattr(old, "_committed", False):
            new = jax.device_put(new, old.sharding)
        hp["learning_rate"] = new
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hp))


def make_optimizer(learning_rate: float, momentum: float = 0.9) -> optax.GradientTransformation:
    return optax.inject_hyperparams(optax.sgd)(
        learning_rate=learning_rate, momentum=momentum
    )


def zero1_param_count(params) -> int:
    """Raveled parameter element count — ``ravel_pytree``'s flat size is
    exactly the sum of leaf sizes, so count leaves instead of materializing
    a flattened copy."""
    return int(sum(p.size for p in jax.tree_util.tree_leaves(params)))


def zero1_padded_size(params, n_shards: int) -> int:
    """Flat parameter count padded up to a multiple of the shard count —
    the single padding convention every ZeRO-1 site (state conversion,
    update math, reshard re-chunk, residual sizing) must share."""
    total = zero1_param_count(params)
    return -(-total // max(n_shards, 1)) * max(n_shards, 1)


def shard_optimizer_state(
    state: TrainState, mesh, tx: optax.GradientTransformation
) -> TrainState:
    """Convert a replicated-optax TrainState into the sharded-update form —
    GENERIC over optax transforms (the PR-13 tentpole): the optimizer is
    re-initialized on the FLAT padded parameter vector, so every
    param-shaped piece of its state (sgd's trace, adam's mu/nu) becomes one
    [padded_total] vector sharded 1/n over the mesh, while scalar leaves
    (inject_hyperparams' lr, adam's count) stay replicated. The update math
    is then the elementwise transform applied to this device's chunk —
    identical per element to the replicated per-leaf update (the uniform
    update shard of arXiv 2004.13336). Exactness holds for ELEMENTWISE
    transforms (sgd/momentum, adam(w), rmsprop, any chain of scale_by_*);
    transforms that reduce over the whole tree inside ``tx`` (e.g.
    clip_by_global_norm) would see only the chunk and are excluded — the
    engine's per-worker grad clip runs before the combine and composes
    fine.

    The inject_hyperparams state surface (``.hyperparams`` + ``._replace``)
    survives the conversion untouched, so ``with_learning_rate`` and the
    one-cycle schedule work unchanged. The chunk layout follows
    :func:`~..parallel.mesh.zero1_chunk_axes`: ``P('data')`` on a flat
    mesh, ``P(('device','host'))`` on a two-level one — device-major, the
    block order the hierarchical in-host reduce-scatter + cross-host hop
    produces."""
    import jax.flatten_util  # noqa: F401  (registers the submodule)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        zero1_chunk_axes,
    )

    flat, _ = jax.flatten_util.ravel_pytree(state.params)
    n = len(mesh.devices.flat)
    padded = zero1_padded_size(state.params, n)
    flat = jnp.pad(flat.astype(jnp.float32), (0, padded - flat.size))
    opt_state = tx.init(flat)
    # carry forward any already-applied hyperparam overrides (a state that
    # saw with_learning_rate before conversion) — tx.init re-reads factory
    # defaults
    old_hp = getattr(state.opt_state, "hyperparams", None)
    if old_hp is not None and hasattr(opt_state, "hyperparams"):
        hp = dict(opt_state.hyperparams)
        for k, v in old_hp.items():
            if k in hp:
                hp[k] = jnp.asarray(v, jnp.float32)
        opt_state = opt_state._replace(hyperparams=hp)
    chunked = NamedSharding(mesh, P(zero1_chunk_axes(mesh)))
    # Scalars committed REPLICATED over the mesh (not default-device): this
    # state doubles as the restore template, and a single-device-committed
    # leaf would clash with the mesh-wide jit after checkpoint resume.
    rep = NamedSharding(mesh, P())
    opt_state = jax.tree_util.tree_map(
        lambda l: jax.device_put(
            l, chunked if (l.ndim >= 1 and l.shape[0] == padded) else rep
        ),
        opt_state,
    )
    return state.replace(opt_state=opt_state)


def residual_chunk_size(
    params, devices_per_host: int, pad_multiple: int = 0
) -> int:
    """Per-device error-feedback chunk width of the TOP hop (kept for the
    two-level callers/tests): the raveled param count padded up to a
    multiple of the in-host device count (the reduce-scatter's divisibility
    requirement) — or of ``pad_multiple`` when the ZeRO-1 layout co-rides
    the combine (the sharded update pads to the TOTAL device count so the
    post-hop chunk re-splits evenly across hosts) — divided by the in-host
    count. The N-level generalization is
    ``parallel/wire.py tree_hop_widths`` (this is its ``widths[0]`` for a
    two-level tree)."""
    total = zero1_param_count(params)
    mult = max(pad_multiple, devices_per_host)
    padded = -(-total // mult) * mult
    return padded // devices_per_host


def attach_comm_residual(state: TrainState, mesh, pad_multiple: int = 0) -> TrainState:
    """Attach zero error-feedback residuals sized for ``mesh``'s tree
    factorization (>= 2 levels): a tuple with one [n_devices, W_i] f32
    row-block per hop 0..k-1, outermost hop first (widths from
    ``tree_hop_widths`` — the innermost hop is always fp32 and carries no
    residual). Each block's leading axis splits over ALL mesh axes,
    row-major — one row per device in the flat device order.
    ``pad_multiple``: the ZeRO-1 total-device padding when the sharded
    update rides the wire. Fresh runs start at zero error by definition;
    checkpoint restore replaces the zeros with the saved residuals through
    the ordinary state template."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel.wire import (
        tree_hop_widths,
    )

    names = tuple(mesh.axis_names)
    if len(names) < 2:
        raise ValueError("attach_comm_residual needs a tree mesh (>= 2 levels)")
    sizes = tuple(int(mesh.shape[a]) for a in names)
    n = int(np.prod(sizes))
    widths = tree_hop_widths(
        zero1_param_count(state.params), sizes, pad_multiple
    )
    sh = NamedSharding(mesh, P(names))
    residual = tuple(
        jax.device_put(jnp.zeros((n, w), jnp.float32), sh)
        for w in widths[:-1]  # hops 0..k-1; the innermost fp32 hop has none
    )
    return state.replace(comm_residual=residual)


def create_state(
    module,
    example_input: jnp.ndarray,
    tx: optax.GradientTransformation,
    seed: int = 1234,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> TrainState:
    """Initialize params deterministically from ``seed`` (the analogue of the
    reference's torch.manual_seed(1234) + initial cross-worker param averaging
    dbs.py:329/365-367 — replication by construction instead of by allreduce)."""

    def init_fn(key):
        params = module.init({"params": key, "dropout": key}, example_input, train=False)
        opt_state = tx.init(params)
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    key = jax.random.PRNGKey(seed)
    if sharding is not None:
        state = jax.jit(init_fn, out_shardings=sharding)(key)
    else:
        state = jax.jit(init_fn)(key)
    return state
