"""Training state: params + SGD(momentum) optimizer state.

The reference's optimizer is ``optim.SGD(lr, momentum=0.9)`` with a
per-epoch learning-rate override for the one-cycle policy (dbs.py:369,
193-215). Here optax's sgd is wrapped in ``inject_hyperparams`` so the learning
rate lives *in the optimizer state* and can be set per epoch without
recompiling the update step.

State is replicated over the data mesh: every device holds the full params
and momentum, as every reference worker does (dbs.py:365-369). (Sharding the
optimizer state ZeRO-style is an available upgrade; the mesh machinery does
not foreclose it.)
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
import optax


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray  # global step counter
    # Error-feedback residual of the hierarchical compressed gradient
    # collective (ISSUE 12): each device's accumulated quantization error on
    # its reduce-scattered chunk, [n_devices, chunk] sharded one row per
    # device over the two-level mesh. None (an empty pytree subtree — no
    # leaf, no signature change) on every non-hierarchical run; attached by
    # attach_comm_residual when --grad_comm hier resolves. Carried in the
    # state so it donates/checkpoints/restores with the weights — dropping
    # it between steps would silently discard the compression error the
    # biased wires (int4) rely on re-injecting.
    comm_residual: Any = None

    def learning_rate(self) -> float:
        return float(self.opt_state.hyperparams["learning_rate"])

    def with_learning_rate(self, lr: float) -> "TrainState":
        hp = dict(self.opt_state.hyperparams)
        new = jnp.asarray(lr, dtype=jnp.float32)
        old = hp["learning_rate"]
        # Preserve the old leaf's placement: a bare jnp.asarray is an
        # UNCOMMITTED array, which changes the state's pjit signature (the
        # replicated NamedSharding becomes UnspecifiedValue) and silently
        # forks a second compiled variant of every executable the state
        # feeds — the engine's warm-start work would never be reused.
        if getattr(old, "_committed", False):
            new = jax.device_put(new, old.sharding)
        hp["learning_rate"] = new
        return self.replace(opt_state=self.opt_state._replace(hyperparams=hp))


def make_optimizer(learning_rate: float, momentum: float = 0.9) -> optax.GradientTransformation:
    return optax.inject_hyperparams(optax.sgd)(
        learning_rate=learning_rate, momentum=momentum
    )


class ShardedSGDState(NamedTuple):
    """SGD(momentum) state with the momentum buffer FLAT and SHARDED over the
    data mesh — cross-replica weight-update sharding (the TPU-native ZeRO-1
    analogue, after arXiv 2004.13336): each replica reduce-scatters gradients,
    updates only its 1/n shard of the momentum, and all-gathers the weight
    delta. Memory for optimizer state drops n_dev-fold; the update math is
    identical to the replicated ``optax.sgd``.

    Mimics ``inject_hyperparams``' state surface (``hyperparams`` dict +
    ``_replace``) so ``TrainState.with_learning_rate`` and the one-cycle
    schedule work unchanged."""

    hyperparams: dict          # {"learning_rate": scalar} — replicated
    momentum: jnp.ndarray      # scalar decay factor — replicated
    trace: jnp.ndarray         # [padded_total] flat momentum, P('data')-sharded
    count: jnp.ndarray         # step counter


def shard_optimizer_state(state: TrainState, mesh, momentum: float = 0.9) -> TrainState:
    """Convert a replicated-optax TrainState into the sharded-update form:
    the momentum trace becomes one flat zero vector (padded to a mesh-size
    multiple) sharded over the data axis. Fresh-start conversion (trace is
    zero at init, like the reference's SGD, dbs.py:369)."""
    import jax.flatten_util  # noqa: F401  (registers the submodule)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import DATA_AXIS

    flat, _ = jax.flatten_util.ravel_pytree(state.params)
    n = len(mesh.devices.flat)
    padded = -(-flat.size // n) * n
    trace = jax.device_put(
        jnp.zeros((padded,), jnp.float32), NamedSharding(mesh, P(DATA_AXIS))
    )
    # Scalars committed REPLICATED over the mesh (not default-device): this
    # state doubles as the restore template, and a single-device-committed
    # leaf would clash with the mesh-wide jit after checkpoint resume.
    rep = NamedSharding(mesh, P())
    opt_state = ShardedSGDState(
        hyperparams={
            "learning_rate": jax.device_put(
                jnp.asarray(
                    state.opt_state.hyperparams["learning_rate"], jnp.float32
                ),
                rep,
            )
        },
        momentum=jax.device_put(jnp.asarray(momentum, jnp.float32), rep),
        trace=trace,
        count=jax.device_put(jnp.zeros((), jnp.int32), rep),
    )
    return state.replace(opt_state=opt_state)


def residual_chunk_size(params, devices_per_host: int) -> int:
    """Per-device error-feedback chunk width: the raveled param count padded
    up to a multiple of the in-host device count (the reduce-scatter's
    divisibility requirement) divided by it. ravel_pytree's flat size is
    exactly the sum of leaf sizes, so count leaves instead of
    materializing a full flattened copy at init. Must match the
    hierarchical combine's padding arithmetic (parallel/wire.py
    hier_tree_allreduce)."""
    total = int(sum(p.size for p in jax.tree_util.tree_leaves(params)))
    padded = -(-total // devices_per_host) * devices_per_host
    return padded // devices_per_host


def attach_comm_residual(state: TrainState, mesh) -> TrainState:
    """Attach a zero error-feedback residual sized for ``mesh``'s two-level
    factorization: [n_devices, chunk] f32, one row per device (leading axis
    split over BOTH mesh axes, row-major — the flat device order). Fresh
    runs start at zero error by definition; checkpoint restore replaces the
    zeros with the saved residual through the ordinary state template."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    names = tuple(mesh.axis_names)
    if len(names) != 2:
        raise ValueError("attach_comm_residual needs a two-level (host, device) mesh")
    n = int(np.prod(tuple(mesh.shape.values())))
    chunk = residual_chunk_size(state.params, int(mesh.shape[names[1]]))
    residual = jax.device_put(
        jnp.zeros((n, chunk), jnp.float32), NamedSharding(mesh, P(names))
    )
    return state.replace(comm_residual=residual)


def create_state(
    module,
    example_input: jnp.ndarray,
    tx: optax.GradientTransformation,
    seed: int = 1234,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> TrainState:
    """Initialize params deterministically from ``seed`` (the analogue of the
    reference's torch.manual_seed(1234) + initial cross-worker param averaging
    dbs.py:329/365-367 — replication by construction instead of by allreduce)."""

    def init_fn(key):
        params = module.init({"params": key, "dropout": key}, example_input, train=False)
        opt_state = tx.init(params)
        return TrainState(params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32))

    key = jax.random.PRNGKey(seed)
    if sharding is not None:
        state = jax.jit(init_fn, out_shardings=sharding)(key)
    else:
        state = jax.jit(init_fn)(key)
    return state
