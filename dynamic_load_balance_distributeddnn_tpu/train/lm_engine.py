"""Transformer-LM trainer — the sequence workload path.

Shares the DBS controller (solver, timing, faults, recorder) with the vision
Trainer; differs in the data plane, mirroring the reference's transformer
branch (dbs.py:253-288, 397-419; dataloader.py:100-110):

- the token *stream* is split contiguously by worker share (no shuffle,
  dataloader.py:106) and each worker folds its slice into
  ``bsz_r = share_r * B`` columns (batchify),
- steps consume bptt=35-token windows with next-token targets (utils.py:7-10),
- per-worker gradients are clipped to 0.25 before combining (dbs.py:274),
- validation is bptt-windowed NLL with eval batch 10 (dataloader.py:109) and
  "accuracy" defined as ``1 - val_loss`` (dbs.py:180-181 — the reference's
  convention, kept for series parity).

Because worker slice length and column count are both proportional to the
share, every worker sweeps the same number of windows — the equal-step
invariant again, now in token space.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from dynamic_load_balance_distributeddnn_tpu.data.corpus import (
    Corpus,
    batchify,
    bptt_windows,
)
from dynamic_load_balance_distributeddnn_tpu.data.partitioner import (
    EpochPlan,
    WorkerPlan,
    partition_indices,
)
from dynamic_load_balance_distributeddnn_tpu.models import build_model
from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import replicated_sharding
from dynamic_load_balance_distributeddnn_tpu.train.engine import Trainer
from dynamic_load_balance_distributeddnn_tpu.train.state import create_state, make_optimizer
from dynamic_load_balance_distributeddnn_tpu.train.steps import StepLibrary


class LMTrainer(Trainer):
    SNAP_BATCHES = False  # columns, not examples — keep the exact split

    # Reference LM hyperparameters (dbs.py:337-343)
    EMSIZE = 200
    NHEAD = 2
    NHID = 200
    NLAYERS = 2
    DROPOUT = 0.2

    def _setup_data(self, bundle) -> None:
        cfg = self.cfg
        if bundle is not None:
            self.corpus = bundle  # tests may inject a Corpus directly
        else:
            self.corpus = Corpus(cfg.lm_data_dir)
        for note in getattr(self.corpus, "notes", []):
            self.logger.warning(f"corpus: {note}")
        stream = self.corpus.train
        if cfg.n_train:
            stream = stream[: cfg.n_train]
        elif cfg.debug and len(stream) > 60_000:
            stream = stream[:60_000]
        self.train_stream = stream
        self.n_train = len(stream)
        self.bundle = None

    def _setup_model(self) -> None:
        cfg = self.cfg
        from dynamic_load_balance_distributeddnn_tpu.ops.pallas import set_use_pallas

        set_use_pallas(cfg.use_pallas)
        self.spec = build_model(
            "transformer",
            ntoken=self.corpus.ntokens,
            ninp=self.EMSIZE,
            nhead=self.NHEAD,
            nhid=self.NHID,
            nlayers=self.NLAYERS,
            dropout=self.DROPOUT,
            # separate knob: flash attention omits attention-prob dropout, a
            # training-semantics change, so it is NOT tied to use_pallas
            use_flash=cfg.use_flash_attention,
        )
        self.tx = make_optimizer(cfg.learning_rate, cfg.momentum)
        example = jnp.zeros((1, cfg.bptt), jnp.int32)
        self.state = create_state(
            self.spec.module,
            example,
            self.tx,
            seed=cfg.seed,
            sharding=replicated_sharding(self.mesh),
        )
        self._zero1_padded = 0
        if cfg.shard_update:
            # ZeRO-1 sharded update on the LM path (ISSUE 13): identical
            # flat-chunk conversion and combine-twin dispatch as the vision
            # engine — the update shard stays uniform even though the LM's
            # column batches are not
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                shard_optimizer_state,
                zero1_padded_size,
            )

            self._zero1_padded = zero1_padded_size(self.state.params, self.n_dev)
            self.state = shard_optimizer_state(self.state, self.mesh, self.tx)
        if self.grad_comm == "hier":
            from dynamic_load_balance_distributeddnn_tpu.train.state import (
                attach_comm_residual,
            )

            # hierarchical combine (ISSUE 12): the LM's elastic dispatch
            # rides the hier combine twins like the vision path — the
            # error-feedback residual travels in the TrainState
            self.state = attach_comm_residual(
                self.state, self.mesh,
                pad_multiple=self.n_dev if cfg.shard_update else 0,
            )
        grad_clip = cfg.grad_clip if cfg.grad_clip > 0 else 0.25  # dbs.py:274
        self.steps = StepLibrary(
            self.spec,
            self.mesh,
            self.tx,
            grad_clip=grad_clip,
            compute_dtype=jnp.bfloat16 if cfg.precision == "bfloat16" else None,
            use_pallas=cfg.use_pallas,
            shard_update=cfg.shard_update,
            grad_accum=cfg.grad_accum,
            compress_grads=cfg.compress_grads,
            remat=cfg.remat,
            grad_comm=self.grad_comm,
            grad_comm_wire=cfg.grad_comm_wire,
            grad_comm_wires=self._grad_comm_wires or None,
            zero1_padded=self._zero1_padded,
        )

    def _dummy_batch(self, b: int):
        """LM warm-up batch: ``b`` padded columns of bptt-token windows."""
        cfg = self.cfg
        return (
            np.zeros((b, cfg.bptt), dtype=np.int32),
            np.zeros((b, cfg.bptt), dtype=np.int32),
            np.zeros((b, cfg.bptt), dtype=np.float32),
        )

    # ------------------------------------------------------------- planning

    def _build_plan(self, epoch: int, batch_sizes: np.ndarray) -> EpochPlan:
        """LM plan: contiguous stream slices; a worker's "batch size" is its
        column count; steps = number of bptt windows of its folded slice."""
        cfg = self.cfg
        parts = partition_indices(self.n_train, self.shares, shuffle=False)
        workers = []
        num_steps = 0
        for rank, (token_range, cols) in enumerate(zip(parts, batch_sizes)):
            cols = int(max(cols, 1))
            nbatch = max(len(token_range) // cols, 2)
            steps = max(-(-(nbatch - 1) // cfg.bptt), 1)
            padded = -(-cols // cfg.bucket) * cfg.bucket
            workers.append(
                WorkerPlan(
                    rank=rank,
                    indices=token_range,
                    batch_size=cols,
                    padded_batch=padded,
                    steps=steps,
                )
            )
            num_steps = max(num_steps, steps)
        return EpochPlan(
            epoch=epoch,
            shares=self.shares.copy(),
            batch_sizes=np.asarray(batch_sizes, dtype=np.int64),
            workers=tuple(workers),
            num_steps=num_steps,
            global_batch=cfg.batch_size,
        )

    def _worker_inputs(
        self, plan: EpochPlan, rank: int, s0: int = 0, s1=None, *, pad_to=None,
        as_indices: bool = False
    ):
        # pad_to: the fused-DBS capacity layout — every worker presents
        # ``cap`` columns (padding masked to zero weight) so one compiled
        # scan serves every rebalanced plan, exactly as in the vision path.
        # as_indices: the vision device-cache mode — never active here (the
        # LM has no cacheable train arrays; _decide_device_cache returns
        # False), accepted for signature parity.
        assert not as_indices
        #
        # The epoch's windows are plan-deterministic, so they are built ONCE
        # per (epoch, rank, pad) and the chunked fused gather / probe calls
        # slice the cached arrays — token windows are small (the folded
        # stream), so whole-epoch residency is cheap, unlike images.
        if getattr(self, "_win_cache_epoch", None) != plan.epoch:
            self._win_cache_epoch = plan.epoch
            self._win_cache = {}
        key = (rank, pad_to)
        if key not in self._win_cache:
            # graftscope: the LM's host data plane — token-window folds are
            # built once per (epoch, rank, pad) and show as their own spans
            with self._trace.span(
                "lm_build_windows", cat="transfer", args={"rank": rank}
            ):
                self._win_cache[key] = self._build_windows(plan, rank, pad_to)
        x, y, weights = self._win_cache[key]
        if s1 is None:
            s1 = plan.num_steps
        return x[s0:s1], y[s0:s1], weights[s0:s1]

    def _build_windows(self, plan: EpochPlan, rank: int, pad_to):
        cfg = self.cfg
        w = plan.workers[rank]
        if len(w.indices):
            slice_tokens = self.train_stream[w.indices[0] : w.indices[-1] + 1]
        else:
            slice_tokens = np.zeros(0, dtype=np.int32)
        data = batchify(slice_tokens, w.batch_size)
        x, y, m = bptt_windows(
            data, cfg.bptt, pad_bsz=pad_to if pad_to is not None else w.padded_batch
        )
        # pad the step axis to the plan-wide count with fully masked windows
        if x.shape[0] < plan.num_steps:
            extra = plan.num_steps - x.shape[0]
            zpad = ((0, extra), (0, 0), (0, 0))
            x, y, m = (np.pad(a, zpad) for a in (x, y, m))
        # Per-token weights: worker weight p_r (or 1/ws under -de) spread over
        # the window's true token count — sum over all workers == 1.
        p_r = (
            1.0 / cfg.world_size
            if cfg.disable_enhancements
            else float(plan.shares[rank])
        )
        tok_counts = m.reshape(plan.num_steps, -1).sum(axis=1)
        weights = m * (
            p_r / np.maximum(tok_counts, 1.0)[:, None, None]
        ).astype(np.float32)
        return x, y, weights

    # ------------------------------------------------------------- validate

    def validate(self) -> Tuple[float, float]:
        """bptt-windowed NLL over the test stream, sharded over the mesh: the
        [windows, bsz, bptt] windows flatten to independent [rows, bptt]
        sequences (each row is one column's window — the model treats batch
        rows independently) and run through the same fused sharded eval as
        the vision path, in fixed-shape chunks."""
        cfg = self.cfg
        eval_bsz = 10  # dataloader.py:109
        stream = self.corpus.test
        if cfg.debug and len(stream) > 20_000:
            stream = stream[:20_000]
        data = batchify(stream, eval_bsz)
        x, y, m = bptt_windows(data, cfg.bptt)
        loss_sum, _, count = self._eval_sharded(
            x.reshape(-1, cfg.bptt),
            y.reshape(-1, cfg.bptt),
            mask=m.reshape(-1, cfg.bptt),
        )
        val_loss = loss_sum / max(count, 1.0)
        # "accuracy" = 1 - val_loss: the reference's LM convention
        # (dbs.py:180-181), not a real accuracy.
        return val_loss, 1.0 - val_loss
