from dynamic_load_balance_distributeddnn_tpu.train.state import TrainState, create_state
from dynamic_load_balance_distributeddnn_tpu.train.engine import Trainer

__all__ = ["TrainState", "create_state", "Trainer"]
