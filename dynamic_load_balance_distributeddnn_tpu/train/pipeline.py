"""Per-device double-buffered host→device window pipeline (ISSUE 2).

The elastic path used to drive its streaming windows with a single
``max_workers=1`` prefetch thread: window k+1's host gather overlapped the
device, but every ``jax.device_put`` was then issued serially from the
controller thread, in the middle of the dispatch loop. Here each window
flows through two stages on a shared thread pool:

1. **gather** — one task per window materializes the host arrays
   (numpy row-pack, or index/weight arrays in device-cache mode);
2. **stage** — one task PER LOCAL DEVICE issues that device's puts as soon
   as the gather lands, concurrently across devices and concurrently with
   the controller thread dispatching window k.

``get(i)`` blocks only on window i's staged buffers and immediately launches
window i+1, so steady state keeps exactly two windows in flight (peak host
memory: two windows, as before). Transfer walls are reported to the
:class:`~...balance.timing.HostOverheadMeter` from the staging threads, so
the engine's dispatch walls never include them.
"""

from __future__ import annotations

import concurrent.futures
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer


class WindowTransferPipeline:
    """Double-buffered (gather → per-device put) pipeline over step windows.

    ``ranges``: the epoch's ``(s0, s1)`` windows, in execution order.
    ``gather``: ``gather(s0, s1) -> data`` host materialization.
    ``stage``: ``stage(device_index, window_index, data) -> staged`` issues
    one device's puts for one window and returns the device buffers.
    ``device_indices``: the device indices ``stage`` is fanned out over.
    """

    def __init__(
        self,
        ranges: Sequence[Tuple[int, int]],
        gather: Callable,
        stage: Callable,
        device_indices: Sequence[int],
        meter=None,
    ):
        self._ranges = list(ranges)
        self._gather = gather
        self._stage = stage
        self._devices = list(device_indices)
        self._meter = meter
        # one slot per device puts + one for the gather of the next window
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=len(self._devices) + 1
        )
        self._inflight: Dict[int, Tuple] = {}
        self._launched_through = 0  # windows [0, N) whose gather/puts started

    def next_unlaunched(self) -> int:
        """First window index whose gather has NOT been kicked yet — the
        earliest window a mid-epoch plan switch may re-slice (ISSUE 11):
        windows already gathered/staged under the old plan are immutable
        (their device buffers exist; re-staging them would waste the
        transfer AND desynchronize the dispatch loop), so the online
        controller retires only windows from this index on under the new
        plan. The gather/stage callbacks see the switch through the
        engine's segment table, not through this pipeline — window
        boundaries are invariant across a switch by construction."""
        return self._launched_through

    def _stage_device(self, d: int, i: int, gather_fut) -> object:
        data = gather_fut.result()
        t0 = time.perf_counter()
        # graftscope transfer track: staging threads are named, so each
        # device's puts appear on their own timeline row in Perfetto
        with get_tracer().span("stage", cat="transfer", args={"window": i, "device": d}):
            staged = self._stage(d, i, data)
        if self._meter is not None:
            self._meter.add_put_s(time.perf_counter() - t0)
        return staged

    def _gather_window(self, i: int):
        with get_tracer().span("gather", cat="transfer", args={"window": i}):
            return self._gather(*self._ranges[i])

    def _launch(self, i: int) -> None:
        if i in self._inflight or not (0 <= i < len(self._ranges)):
            return
        gather_fut = self._pool.submit(self._gather_window, i)
        put_futs = {
            d: self._pool.submit(self._stage_device, d, i, gather_fut)
            for d in self._devices
        }
        self._inflight[i] = (gather_fut, put_futs)
        self._launched_through = max(self._launched_through, i + 1)

    def prefetch(self, i: int) -> None:
        """Kick window i's gather+puts without blocking on them — lets the
        controller overlap other work (e.g. the AOT compile barrier) with
        the first window's staging before the dispatch loop starts."""
        self._launch(i)

    def get(self, i: int) -> Tuple[object, Dict[int, object]]:
        """Window i's ``(host_data, {device_index: staged})``; prefetches
        window i+1 before blocking so its gather+puts overlap window i's
        execution."""
        self._launch(i)
        self._launch(i + 1)
        gather_fut, put_futs = self._inflight.pop(i)
        staged = {d: f.result() for d, f in put_futs.items()}
        return gather_fut.result(), staged

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "WindowTransferPipeline":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
