"""Long-context LM training: the sequence axis sharded over the mesh.

The DBS trainers parallelize over DATA (workers own example/token shares;
the balancer moves the shares). This trainer parallelizes over the SEQUENCE:
one logical batch of ``--bptt``-token windows has its time axis split across
every device, attention runs ring- or Ulysses-parallel over ICI
(parallel/ring.py, parallel/ulysses.py), and loss/grads psum back to
replicated. This is the regime the reference cannot reach at all — its
sequence handling stops at bptt=35 truncation (SURVEY §5.7) because the full
[T, T] attention lives on one GPU; here T scales with the mesh.

Selected via ``--seq_parallel ring|ulysses`` on the transformer model; the
param layout matches the single-device/DBS LM, so checkpoints move freely
between trainers.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dynamic_load_balance_distributeddnn_tpu.config import Config
from dynamic_load_balance_distributeddnn_tpu.data.corpus import (
    Corpus,
    batchify,
    bptt_windows,
)
from dynamic_load_balance_distributeddnn_tpu.models import build_model
from dynamic_load_balance_distributeddnn_tpu.obs import (
    MetricsRecorder,
    MetricsRegistry,
    init_logger,
)
from dynamic_load_balance_distributeddnn_tpu.obs.trace import EPOCH_CAT, get_tracer
from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import data_mesh, replicated_sharding
from dynamic_load_balance_distributeddnn_tpu.parallel.seq_parallel import (
    make_seq_parallel_apply,
    make_seq_parallel_value_and_grad,
    shard_tokens,
)
from dynamic_load_balance_distributeddnn_tpu.train.schedule import one_cycle_lr
from dynamic_load_balance_distributeddnn_tpu.train.state import create_state, make_optimizer

# reference LM dims (dbs.py:337-343) — kept so SP checkpoints interchange
# with the DBS LM trainer's
EMSIZE, NHEAD, NHID, NLAYERS, DROPOUT = 200, 2, 200, 2, 0.2


class SeqParallelLMTrainer:
    """Epoch loop for sequence-parallel LM training."""

    def __init__(self, cfg: Config, corpus: Optional[Corpus] = None,
                 log_to_file: bool = True):
        if cfg.model != "transformer":
            raise ValueError("seq_parallel training applies to the transformer LM")
        if cfg.seq_parallel not in ("ring", "ulysses"):
            raise ValueError("seq_parallel must be 'ring' or 'ulysses'")
        self.cfg = cfg
        self.logger = init_logger(cfg, rank=0, to_file=log_to_file)
        self.mesh = data_mesh()
        self.n_dev = len(self.mesh.devices.flat)
        if cfg.bptt % self.n_dev != 0:
            raise ValueError(
                f"bptt {cfg.bptt} must divide by the {self.n_dev}-device mesh"
            )
        if cfg.seq_parallel == "ulysses" and NHEAD % self.n_dev != 0:
            raise ValueError(
                f"ulysses needs num_heads ({NHEAD}) % n_devices ({self.n_dev}) == 0"
            )

        self.corpus = corpus if corpus is not None else Corpus(cfg.lm_data_dir)
        for note in getattr(self.corpus, "notes", []):
            self.logger.warning(f"corpus: {note}")
        stream = self.corpus.train
        if cfg.n_train:
            stream = stream[: cfg.n_train]
        elif cfg.debug and len(stream) > 60_000:
            stream = stream[:60_000]
        # [B, nbatch] token columns; steps consume [B, bptt] windows
        self.data = batchify(stream, max(cfg.batch_size, 1))
        self.val_data = batchify(self.corpus.valid, 10)  # eval bsz 10 (dataloader.py:109)

        dims = dict(
            ntoken=self.corpus.ntokens,
            ninp=EMSIZE, nhead=NHEAD, nhid=NHID, nlayers=NLAYERS,
            dropout=DROPOUT,
        )
        # init with the param-compatible single-device twin: the SP module's
        # collectives (axis_size/axis_index) only exist inside shard_map
        single = build_model("transformer", **dims).module
        self.module = build_model(
            "transformer", **dims, seq_axis="data", sp_mode=cfg.seq_parallel
        ).module
        self.tx = make_optimizer(cfg.learning_rate, cfg.momentum)
        self.state = create_state(
            single,
            jnp.zeros((1, cfg.bptt), jnp.int32),
            self.tx,
            seed=cfg.seed,
            sharding=replicated_sharding(self.mesh),
        )
        self._vg = make_seq_parallel_value_and_grad(
            self.mesh, self.module, train=True
        )
        self._eval_apply = make_seq_parallel_apply(self.mesh, self.module)
        clip = cfg.grad_clip if cfg.grad_clip > 0 else 0.25  # dbs.py:274

        @jax.jit
        def update(state, grads):
            if clip > 0:
                gnorm = optax.global_norm(grads)
                scale = jnp.minimum(1.0, clip / jnp.maximum(gnorm, 1e-12))
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)
            updates, opt_state = self.tx.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            return state.replace(
                params=params, opt_state=opt_state, step=state.step + 1
            )

        self._update = update
        self.recorder = MetricsRecorder()
        # graftscope: the engine owns the process-wide tracer config (same
        # contract as the DBS engines — unconditional, so an off run never
        # inherits an earlier traced run's enabled state) + the registry
        self._trace = get_tracer().configure(
            cfg.trace,
            ring_size=cfg.trace_ring,
            jax_annotations=cfg.trace_annotations,
        )
        self.obs = MetricsRegistry(recorder=self.recorder, tracer=self._trace)
        self.recorder.stamp_data_source(self.corpus)
        # SP walls never contained standalone probe steps (the SP engine has
        # no re-probe machinery); stamped so its artifacts carry the same
        # wall-definition schema as the vision/LM engines (ADVICE r4)
        self.recorder.meta["wall_excludes_probes"] = True
        if cfg.straggler:
            self.recorder.meta["straggler_factors"] = [
                float(f) for f in cfg.straggler_factors()
            ]
            self.recorder.meta["fault_mode"] = cfg.fault_mode
        self.total_wallclock = 0.0

    # ------------------------------------------------------------------ loop

    def _windows(self, data: np.ndarray):
        # no column padding: the SP batch is the full [bsz] column set; only
        # the tail window (short T) is masked out of the step loop
        return bptt_windows(data, self.cfg.bptt)

    def run_epoch(self, epoch: int) -> dict:
        tr = get_tracer()
        tr.set_epoch(epoch)
        try:
            with tr.span("epoch", cat=EPOCH_CAT):
                return self._run_epoch(epoch)
        finally:
            tr.set_epoch(None)

    def _run_epoch(self, epoch: int) -> dict:
        cfg = self.cfg
        tr = get_tracer()
        with tr.span("plan_solve"):
            if cfg.one_cycle_policy:
                lr = one_cycle_lr(cfg.learning_rate, epoch, cfg.epoch_size,
                                  disable=cfg.disable_enhancements)
                self.state = self.state.with_learning_rate(lr)
            xs, ys, ms = self._windows(self.data)
        with tr.span("train"):
            t0 = time.perf_counter()
            loss_sum, tok, n_done = 0.0, 0, 0
            for s in range(xs.shape[0]):
                # full-length windows only: the SP shard_map needs T % n_dev == 0
                if not ms[s].all():
                    continue
                x = shard_tokens(self.mesh, jnp.asarray(xs[s], jnp.int32))
                y = shard_tokens(self.mesh, jnp.asarray(ys[s], jnp.int32))
                loss, grads = self._vg(
                    self.state.params, x, y,
                    jax.random.fold_in(jax.random.PRNGKey(cfg.seed), epoch * 131071 + s),
                )
                self.state = self._update(self.state, grads)
                loss_sum += float(loss)
                tok += int(ms[s].sum())
                n_done += 1
            jax.block_until_ready(self.state.params)
            wall = time.perf_counter() - t0
        self.total_wallclock += wall
        train_loss = loss_sum / max(n_done, 1)
        with tr.span("validate"):
            val_loss, acc = self.validate()
        with tr.span("record"):
            tps = tok / wall if wall > 0 else 0.0
            self.logger.info(
                f"Epoch {epoch}: sp={cfg.seq_parallel} T={cfg.bptt} "
                f"train_loss {train_loss:.4f}, val_loss {val_loss:.4f}, "
                f"{tps:,.0f} tok/s, wall {wall:.3f}s"
            )
            self.recorder.record_epoch(
                epoch=epoch,
                train_loss=train_loss,
                train_time=wall,
                sync_time=0.0,
                val_loss=val_loss,
                accuracy=acc,
                partition=[1.0 / self.n_dev] * self.n_dev,
                node_time=[wall] * self.n_dev,
                wallclock_time=self.total_wallclock,
                tokens_per_s=tps,
            )
        return {"epoch_wall": wall, "loss": train_loss, "val_loss": val_loss}

    def validate(self) -> Tuple[float, float]:
        xs, ys, ms = self._windows(self.val_data)
        tot, cnt = 0.0, 0.0
        for s in range(xs.shape[0]):
            if not ms[s].all():
                continue
            logits = self._eval_apply(
                self.state.params, shard_tokens(self.mesh, jnp.asarray(xs[s], jnp.int32))
            )
            logits = np.asarray(logits, np.float32)
            logz = np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1)) + logits.max(-1)
            gold = np.take_along_axis(logits, ys[s][..., None], axis=-1)[..., 0]
            tot += float((logz - gold).sum())
            cnt += float(ys[s].size)
        val = tot / max(cnt, 1.0)
        return val, 1.0 - val  # "accuracy" = 1 - val_loss (dbs.py:180-181)

    def run(self, epochs: Optional[int] = None) -> MetricsRecorder:
        n = epochs if epochs is not None else self.cfg.epoch_size
        for e in range(n):
            self.run_epoch(e)
        self.logger.info(f"Total wallclock: {self.total_wallclock:.3f}s")
        self.recorder.save(self.cfg.stat_dir, self.cfg.base_filename())
        if self._trace.enabled:
            path = os.path.join(
                self.cfg.trace_dir,
                self.cfg.base_filename().format(0) + ".trace.json",
            )
            self._trace.save(path)
            self.logger.info(f"graftscope trace saved: {path}")
        return self.recorder
