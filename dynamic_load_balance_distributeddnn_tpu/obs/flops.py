"""FLOP accounting and MFU estimation.

The reference has no profiling beyond wall-clock (SURVEY §5.1). Here the
compiled step's own XLA cost model supplies per-step FLOPs
(``lowered.compile().cost_analysis()``), giving throughput (examples/s,
tokens/s) and MFU against the chip's peak — the "fast, or just correct?"
instrumentation the TPU build needs.

MFU is reported against the chip's **bf16 systolic-array peak** regardless of
the run's compute dtype (f32 runs will show correspondingly lower MFU); the
key name says so explicitly.
"""

from __future__ import annotations

from typing import Optional

import jax

# Per-chip dense peak matmul throughput, bf16, FLOP/s. Sources: public TPU
# spec sheets (per-chip, all MXUs).
_PEAK_BF16 = {
    "TPU v2": 45e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def chip_peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOP/s for one chip, or None when unknown (e.g. CPU)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "") or ""
    for name, peak in _PEAK_BF16.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return None


def compiled_flops(jitted_fn, *args, compiled=None) -> Optional[float]:
    """FLOPs of one execution of ``jitted_fn(*args)`` per XLA's cost model.
    Returns None when the backend doesn't expose cost analysis.

    ``compiled``: an already-compiled executable (``jax.stages.Compiled``,
    e.g. fetched from the AOT compile service) — its cost analysis is read
    directly and NOTHING is recompiled. Without it this function lowers and
    compiles a second copy of the step just to ask for its cost, which on a
    big model is a whole duplicate XLA compile."""
    try:
        if compiled is None:
            compiled = jitted_fn.lower(*args).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # some backends wrap in a list
            cost = cost[0] if cost else {}
        val = float(cost.get("flops", 0.0))
        return val if val > 0 else None
    except Exception:
        return None


def mfu(flops_per_second: Optional[float], n_devices: int = 1, device=None) -> Optional[float]:
    """Model FLOP utilization in [0,1] vs the mesh's aggregate bf16 peak."""
    peak = chip_peak_flops(device)
    if peak is None or flops_per_second is None:
        return None
    return flops_per_second / (peak * max(n_devices, 1))
