from dynamic_load_balance_distributeddnn_tpu.obs.logging import init_logger
from dynamic_load_balance_distributeddnn_tpu.obs.recorder import MetricsRecorder

__all__ = ["init_logger", "MetricsRecorder"]
