from dynamic_load_balance_distributeddnn_tpu.obs.logging import init_logger
from dynamic_load_balance_distributeddnn_tpu.obs.recorder import MetricsRecorder
from dynamic_load_balance_distributeddnn_tpu.obs.registry import MetricsRegistry
from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
    Tracer,
    attribution,
    configure as configure_tracer,
    get_tracer,
    load_trace,
)

__all__ = [
    "init_logger",
    "MetricsRecorder",
    "MetricsRegistry",
    "Tracer",
    "attribution",
    "configure_tracer",
    "get_tracer",
    "load_trace",
]
