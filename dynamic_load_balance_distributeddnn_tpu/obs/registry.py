"""Unified metrics registry: one handle over the run's observability surfaces.

Before graftscope, a caller wanting "where did this run's time go" had to
know four unrelated objects: the :class:`~..obs.recorder.MetricsRecorder`
(nine per-epoch series + extras), the
:class:`~..balance.timing.HostOverheadMeter` (dispatch/put walls), the
compile guards (:mod:`..analysis.guards` counters + per-engine
``CompileTracker``), and the AOT compile service's stats. The registry binds
them behind one object the engine owns:

* ``registry.last(name)`` / ``registry.series(name)`` — recorder access with
  the None-for-absent contract (optional series like ``examples_per_s``
  exist only on some paths);
* ``registry.snapshot()`` — one JSON-safe dict of everything measurable
  *right now*: recorder last-values, host-meter walls, compile counts
  (foreground/background), AOT service stats, tracer state. The engine logs
  it at end of run; tests and the bench read single keys out of it;
* meters registered once (``attach(...)``) so future surfaces (a new meter,
  a new service) join the snapshot without new plumbing at every call site.

The registry holds *references*, not copies — it is a view, never a second
source of truth, so it can never drift from the objects it unifies.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dynamic_load_balance_distributeddnn_tpu.obs.recorder import MetricsRecorder
from dynamic_load_balance_distributeddnn_tpu.obs.trace import Tracer, get_tracer


def device_peak_memory() -> Dict:
    """Per-device peak-memory series (ISSUE 13 satellite) — the datum the
    zero1 A/B reports. Where the backend provides ``device.memory_stats()``
    (TPU/GPU runtimes), one row per local device with ``bytes_in_use`` and
    ``peak_bytes_in_use``; CPU backends expose no per-device allocator, so
    the fallback reports the process's peak RSS (and tracemalloc's peak
    when tracing is active) — a coarser but honest host-side ceiling.

    Mid-rendezvous safe: while the distributed runtime is torn down
    (retire_runtime -> establish), ``jax.local_devices()`` can raise — a
    snapshot taken then degrades to ``{"source": "unavailable"}`` instead of
    propagating and killing the caller's whole snapshot."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception as e:  # noqa: BLE001 — torn-down runtime mid-rendezvous
        return {"source": "unavailable", "error": str(e)[:200]}

    out: Dict = {"source": "memory_stats", "per_device": []}
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend without an allocator API
            stats = None
        if stats:
            out["per_device"].append(
                {
                    "device": str(d),
                    "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(
                        stats.get(
                            "peak_bytes_in_use", stats.get("bytes_in_use", 0)
                        )
                    ),
                }
            )
    if not out["per_device"]:
        import resource
        import sys
        import tracemalloc

        out["source"] = "host_rss"
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS
        out["host_peak_rss_bytes"] = int(
            ru if sys.platform == "darwin" else ru * 1024
        )
        if tracemalloc.is_tracing():
            _cur, peak = tracemalloc.get_traced_memory()
            out["tracemalloc_peak_bytes"] = int(peak)
    return out


class MetricsRegistry:
    def __init__(
        self,
        recorder: Optional[MetricsRecorder] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.recorder = recorder if recorder is not None else MetricsRecorder()
        self.tracer = tracer if tracer is not None else get_tracer()
        self.host_meter = None  # balance.timing.HostOverheadMeter
        self.compile_tracker = None  # analysis.guards.CompileTracker
        self.aot_service = None  # runtime.compiler.AOTCompileService
        self.health = None  # runtime.health.WorkerHealth
        self.controller = None  # balance.controller.OnlineRebalanceController
        self.scheduler = None  # runtime.scheduler.MultiStreamEngine

    def attach(self, **surfaces) -> "MetricsRegistry":
        """Register observability surfaces by their well-known slot name
        (``host_meter``, ``compile_tracker``, ``aot_service``, ``health``,
        ``controller``, ``scheduler``). Unknown names raise — a typo'd
        attach would silently hollow the snapshot."""
        for name, obj in surfaces.items():
            if name not in (
                "host_meter", "compile_tracker", "aot_service", "health",
                "controller", "scheduler",
            ):
                raise ValueError(f"unknown registry surface {name!r}")
            setattr(self, name, obj)
        return self

    # ------------------------------------------------------- recorder facade

    def series(self, name: str) -> List:
        """A recorder series by name ([] for a series never recorded)."""
        return self.recorder.data.get(name, [])

    def last(self, name: str):
        """Last recorded value of a series (None when absent/empty)."""
        return self.recorder.last(name)

    # ------------------------------------------------------------- snapshot

    def snapshot(self) -> Dict:
        """JSON-safe point-in-time view across every attached surface."""
        out: Dict = {
            "recorder": {
                k: self.recorder.last(k)
                for k, v in self.recorder.data.items()
                if v
            },
            "trace": {
                "mode": self.tracer.mode,
                # O(1): events() would COPY the whole deque (up to 1M
                # tuples) just to take a length
                "events": self.tracer.event_count() if self.tracer.enabled else 0,
            },
        }
        # gradient-collective wire accounting (ISSUE 12): per-epoch bytes
        # each link class carried, plus the combine structure they were
        # measured under — the grad_comm bench reads these per arm
        comm = {
            k: self.recorder.last(k)
            for k in ("comm_bytes_ici", "comm_bytes_dcn")
            if self.recorder.last(k) is not None
        }
        if comm:
            comm["grad_comm"] = self.recorder.meta.get("grad_comm", "flat")
            for k in ("grad_comm_levels", "grad_comm_wires"):
                if k in self.recorder.meta:
                    comm[k] = self.recorder.meta[k]
            # bandwidth-probe verdict (ISSUE 17): the measured hier/flat
            # wall ratio, the gate it was judged against, and the per-level
            # link rates the codec choice was made from — queryable live,
            # not only a log line
            bw = self.recorder.meta.get("link_bandwidth")
            if isinstance(bw, dict):
                comm["probe"] = {
                    k: bw[k]
                    for k in (
                        "wall_ratio", "gate_ratio", "hier_wins",
                        "level_bytes_per_s", "levels",
                    )
                    if k in bw
                }
            out["comm"] = comm
        # per-device peak-memory series (ISSUE 13): backend allocator stats
        # where available, host-RSS fallback on CPU — what the zero1 A/B
        # cites for the optimizer-state shrink
        out["memory"] = device_peak_memory()
        if self.host_meter is not None:
            m = self.host_meter
            out["host"] = {
                "dispatch_s": round(m.dispatch_s, 6),
                "put_s": round(m.put_s, 6),
                "dispatches": m.dispatches,
            }
        # process-wide compile counters are always available (guards installs
        # its jax.monitoring listener lazily)
        from dynamic_load_balance_distributeddnn_tpu.analysis.guards import (
            background_compile_count,
            compile_count,
        )

        total = compile_count()
        bg = background_compile_count()
        out["compiles"] = {"total": total, "background": bg, "foreground": total - bg}
        if self.aot_service is not None:
            out["aot"] = {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in self.aot_service.stats().items()
            }
        if self.health is not None:
            out["health"] = self.health.snapshot()
        if self.controller is not None:
            # the online-DBS decision journal's live surface (ISSUE 15):
            # ledgers, decision count, and the most recent verdict with the
            # inputs it was decided on
            out["controller"] = self.controller.snapshot()
        if self.scheduler is not None:
            # the OUTER loop's decision journal (ISSUE 19): the many-stream
            # engine's per-window device-allocation verdicts in the same
            # journal shape as the inner controller's
            out["scheduler"] = self.scheduler.snapshot()
        return out
