"""graftscope: run-wide span tracing with Perfetto-exportable output.

The DBS feedback loop re-partitions from *measurements*, yet the repo's
timing story used to be fragmented across the recorder's per-epoch series,
``HostOverheadMeter``, ``CompileTracker`` events and a dozen bare
``perf_counter()`` walls — no single artifact said where an epoch's wall
actually went. This module is that artifact's source: a span tracer the hot
paths call around every phase (plan/solve, AOT barrier, dispatch, transfer,
probe, validation), whose buffer exports as Chrome-trace-event JSON loadable
in Perfetto/chrome://tracing, summarizable by the ``graftscope`` CLI, and
joinable with device timelines via an optional ``jax.profiler`` annotation
bridge.

Design constraints, in order:

* **near-zero cost when disabled** — the tracer ships enabled in no default
  config, so every call site must degrade to one attribute check. A disabled
  ``span()`` returns a shared singleton no-op context manager: no object,
  no dict, no closure is allocated (tests assert zero allocations). Call
  sites therefore pass span attributes as an optional ``args`` dict rather
  than ``**kwargs`` (a kwargs dict would be materialized by the *call*
  before the enabled check can run).
* **thread-aware** — events record the OS thread id and name at emit time;
  the AOT compile pool, the transfer pipeline's staging threads, and the
  controller each get their own named track in Perfetto.
* **bounded when asked** — ``mode="ring"`` keeps the last ``ring_size``
  events in a deque (long runs can trace forever and keep the tail);
  ``mode="on"`` keeps everything.
* **no wall-clock surprises** — timestamps come from ``time.perf_counter``
  (monotonic), rebased to the tracer's epoch so exported ``ts`` values are
  small; span emission never syncs a device and never touches jax unless
  the annotation bridge is explicitly enabled.

Event tuples are ``(name, cat, ph, ts_us, dur_us, tid, args)`` with
``ph in ("X", "i", "C")`` — complete spans, instant events (watchdog
heartbeats), counters. ``args`` additionally carries the tracer's *current
epoch* (``set_epoch``) so offline attribution can group spans per epoch
without parsing span nesting across threads.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from functools import wraps
from typing import Dict, List, Optional, Tuple

_LOG = logging.getLogger("graftscope")

# Phase taxonomy: spans with cat="phase" are the NON-OVERLAPPING controller
# segments that tile an epoch span (cat="epoch"); attribution() sums them.
# Deeper instrumentation uses the other categories so nested spans never
# double-count into the per-phase table.
EPOCH_CAT = "epoch"
PHASE_CAT = "phase"


class _NullSpan:
    """Shared do-nothing context manager for the disabled path. A singleton:
    ``tracer.span(...)`` returns THIS object when tracing is off, so the
    disabled fast path allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records on ``__exit__``. Separate from the tracer so
    spans can nest freely and cross threads (each span captures its own
    thread id at entry)."""

    __slots__ = ("_tracer", "name", "cat", "args", "_t0", "_jax_ctx")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0
        self._jax_ctx = None

    def __enter__(self):
        if self._tracer._jax_bridge:
            try:
                import jax

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:  # pragma: no cover - profiler not active/available
                self._jax_ctx = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(*exc)
            except Exception:  # pragma: no cover
                pass
        self._tracer._emit(self.name, self.cat, "X", self._t0, t1 - self._t0, self.args)
        return False


class Tracer:
    """Span/instant/counter recorder with Chrome-trace export.

    ``mode``: ``"off"`` (every call degrades to the singleton no-op),
    ``"on"`` (unbounded buffer), ``"ring"`` (keep the last ``ring_size``
    events). ``jax_annotations=True`` additionally wraps each span in a
    ``jax.profiler.TraceAnnotation`` so host spans line up with device
    timelines when a profiler trace (``--profile_dir``) is active.
    """

    def __init__(
        self,
        mode: str = "off",
        ring_size: int = 1_000_000,
        jax_annotations: bool = False,
    ):
        self.configure(mode, ring_size=ring_size, jax_annotations=jax_annotations)

    # ------------------------------------------------------------- lifecycle

    def configure(
        self,
        mode: str,
        ring_size: int = 1_000_000,
        jax_annotations: bool = False,
    ) -> "Tracer":
        if mode not in ("off", "on", "ring"):
            raise ValueError(f"trace mode must be 'off', 'on' or 'ring', got {mode!r}")
        # a reconfigure retires any attached flight-recorder spool: the next
        # run must not stream into the previous run's file (the writer
        # drains synchronously, so a clean reconfigure loses nothing)
        old_spool = getattr(self, "_spool", None)
        if old_spool is not None:
            old_spool.close()
        self._spool = None
        self.mode = mode
        # deliberately unlocked: `enabled` is a write-once-per-configure
        # bool read by every span() call on pipeline/compile-pool threads —
        # the DISABLED-mode contract is ONE attribute check with zero
        # allocations, and a momentarily stale read only drops/keeps one
        # span around a reconfigure (configure happens at run boundaries,
        # never under live traffic)
        self.enabled = mode != "off"  # graftlint: disable=G012
        self._jax_bridge = bool(jax_annotations) and self.enabled
        # deque.append is atomic under the GIL — pipeline/compile-pool
        # threads emit without a lock on the hot path
        self._events: deque = deque(maxlen=ring_size if mode == "ring" else None)
        self._epoch_base = time.perf_counter()
        # wall-clock twin of the perf_counter base: perf_counter is not
        # comparable across processes, so cross-process stitching
        # (merge_trace_files) realigns each file's events by the difference
        # of these unix stamps
        self._base_unix = time.time()
        self._current_epoch: Optional[int] = None
        # Per-job tagging (many-stream engine): a thread that calls
        # set_job() gets THREAD-LOCAL job + epoch state, so concurrent job
        # threads stamp their own spans without stomping the global epoch
        # the single-job engine uses. Threads that never set a job tag see
        # the same behavior as before job tags existed (global epoch, no
        # job key). Deliberately unlocked: threading.local() stores every
        # thread's tags in per-thread slots — the "cross-thread" writes
        # never touch shared state — and this rebind happens only at run
        # boundaries (same contract as `enabled` above).
        self._tls = threading.local()  # graftlint: disable=G012
        self._thread_names: Dict[int, str] = {}
        return self

    def reset(self) -> None:
        """Drop buffered events; keep the mode (and any attached spool —
        the spool records the rebase so offline realignment stays exact)."""
        self._events.clear()
        self._epoch_base = time.perf_counter()
        self._base_unix = time.time()
        self._current_epoch = None
        if self._spool is not None:
            self._spool.note_rebase(self._base_unix)

    # --------------------------------------------------- flight recorder

    def attach_spool(self, spool) -> None:
        """Stream every subsequently emitted event into ``spool`` (an
        :class:`~.spool.SpoolWriter`) alongside the in-memory buffer — the
        crash-durable sink. The spool adopts this tracer's ``base_unix``
        (realignment key) and thread-name map. One spool at a time; a
        reconfigure or :meth:`detach_spool` closes it."""
        if self._spool is not None:
            self._spool.close()
        spool._thread_names_src = self._thread_names
        spool._write_meta(self._base_unix)
        self._spool = spool

    def detach_spool(self):
        """Close and detach the spool (drains synchronously). Returns the
        writer (for byte accounting) or None."""
        sp = self._spool
        self._spool = None
        if sp is not None:
            sp.close()
        return sp

    def set_epoch(self, epoch: Optional[int]) -> None:
        """Stamp subsequent events with this epoch index (attribution key).
        The engine sets it at each epoch boundary; None = outside any epoch
        (warm-up, teardown). On a thread carrying a job tag (:meth:`set_job`)
        the epoch is stored thread-locally — concurrent jobs each run their
        own epoch counter without racing on the global."""
        if getattr(self._tls, "job", None) is not None:
            self._tls.epoch = epoch
        else:
            self._current_epoch = epoch

    def set_job(self, job: Optional[str]) -> None:
        """Tag THIS THREAD's subsequently emitted events with a job id
        (many-stream engine: one thread drives one job's epochs). The tag
        and the epoch index both become thread-local for the calling
        thread, so `graftscope summarize --by-job` can attribute wall per
        tenant; ``None`` clears the tag (the thread rejoins the global
        epoch stream)."""
        self._tls.job = job
        if job is None:
            self._tls.epoch = None

    # -------------------------------------------------------------- emitters

    def span(self, name: str, cat: str = PHASE_CAT, args: Optional[dict] = None):
        """Context manager timing one region. Disabled mode returns the
        shared no-op singleton — pass attributes via the ``args`` dict (not
        ``**kwargs``, which would allocate before this check could run)."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def traced(self, name: Optional[str] = None, cat: str = PHASE_CAT):
        """Decorator twin of :meth:`span` — times every call of the wrapped
        function under ``name`` (default: the function's __qualname__)."""

        def deco(fn):
            label = name or fn.__qualname__

            @wraps(fn)
            def wrapper(*a, **kw):
                if not self.enabled:
                    return fn(*a, **kw)
                with _Span(self, label, cat, None):
                    return fn(*a, **kw)

            return wrapper

        return deco

    def instant(self, name: str, cat: str = "instant", args: Optional[dict] = None) -> None:
        """Zero-duration marker (watchdog heartbeats, faults, rebalances)."""
        if not self.enabled:
            return
        self._emit(name, cat, "i", time.perf_counter(), 0.0, args)

    def counter(self, name: str, value: float, cat: str = "counter") -> None:
        """Counter sample (compile counts, queue depths) — renders as a
        stacked track in Perfetto."""
        if not self.enabled:
            return
        self._emit(name, cat, "C", time.perf_counter(), 0.0, {"value": float(value)})

    def _emit(self, name, cat, ph, t0: float, dur: float, args) -> None:
        tid = threading.get_ident()
        if tid not in self._thread_names:
            # dict writes are GIL-atomic; a benign race re-writes the same name
            self._thread_names[tid] = threading.current_thread().name
        job = getattr(self._tls, "job", None)
        if job is not None:
            epoch = getattr(self._tls, "epoch", None)
        else:
            epoch = self._current_epoch
        if epoch is not None or job is not None:
            args = dict(args) if args else {}
            if epoch is not None:
                args.setdefault("epoch", epoch)
            if job is not None:
                args.setdefault("job", job)
        rec = (
            name,
            cat,
            ph,
            (t0 - self._epoch_base) * 1e6,  # us, Chrome-trace's unit
            dur * 1e6,
            tid,
            args,
        )
        self._events.append(rec)
        sp = self._spool
        if sp is not None:
            sp.put(rec)

    # --------------------------------------------------------------- export

    def events(self) -> List[Tuple]:
        return list(self._events)

    def event_count(self) -> int:
        """Buffered-event count, O(1): ``len`` on the deque — never copy a
        potentially million-tuple buffer just to measure it (the registry's
        snapshot calls this on every poll)."""
        return len(self._events)

    def chrome_events(self) -> List[dict]:
        """Buffered events as Chrome-trace-event dicts (the ``traceEvents``
        list), plus thread-name metadata so Perfetto labels the tracks.

        Snapshots (``list(...)`` — one C-level call, atomic under the GIL)
        before the Python-level loops: background threads (AOT pool,
        transfer pipeline) may still be emitting, and iterating the live
        deque/dict while they append raises RuntimeError mid-export."""
        pid = os.getpid()
        out: List[dict] = []
        for tid, tname in sorted(list(self._thread_names.items())):
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        for name, cat, ph, ts, dur, tid, args in list(self._events):
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": round(ts, 3),
                "pid": pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(dur, 3)
            if args:
                ev["args"] = args
            out.append(ev)
        return out

    def save(self, path: str) -> str:
        """Write the buffer as Chrome-trace JSON (open in Perfetto via
        ui.perfetto.dev or chrome://tracing). Returns the path."""
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        payload = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            # cross-process alignment key (see merge_trace_files); extra
            # top-level keys are legal Chrome-trace metadata
            "graftscope": {"base_unix": self._base_unix},
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path

    def summary(self) -> Dict:
        """Per-epoch per-phase attribution of the buffered events (see
        :func:`attribution`)."""
        return attribution(self.chrome_events())


# ---------------------------------------------------------------- attribution


def load_trace(path: str) -> List[dict]:
    """Chrome-trace JSON -> the traceEvents list (accepts both the object
    form this module writes and a bare event array)."""
    return _load_trace_payload(path)[0]


def _load_trace_payload(path: str) -> "Tuple[List[dict], Optional[float]]":
    """(traceEvents, graftscope base_unix or None) from one trace file."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict):
        base = (data.get("graftscope") or {}).get("base_unix")
        return list(data.get("traceEvents", [])), base
    return list(data), None


def merged_names(path: str) -> List[str]:
    """Basenames of worker trace files already stitched into ``path`` (the
    ``graftscope.merged`` marker merge_trace_files writes) — so a second
    stitch pass (the engine merges at save; `graftscope summarize` stitches
    siblings) skips them instead of double-counting their spans."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(data, dict):
        return list((data.get("graftscope") or {}).get("merged", []))
    return []


def merge_trace_events(
    paths: List[str], skipped: Optional[List[str]] = None
) -> List[dict]:
    """Stitch several trace files' events into one pid-tagged stream.

    The first path is the PRIMARY (its timeline is the reference frame);
    each additional file — e.g. the compile workers' per-process span files
    (runtime/compile_worker.py) — contributes its events shifted into the
    primary's clock using the ``graftscope.base_unix`` stamps both files
    carry (perf_counter timelines are per-process; the unix-time twin of the
    tracer base makes them comparable to wall-clock accuracy). Files from
    pids the primary doesn't know get a ``process_name`` metadata event
    derived from their filename, so Perfetto labels the worker tracks.

    A truncated or mid-write EXTRA file (the chaos harness kills processes
    during ``save``) is skipped with a warning and its basename appended to
    ``skipped`` (when a list is passed) — one torn worker file must not
    cost the whole merge. The primary still raises: there is no reference
    frame without it."""
    out: List[dict] = []
    base0: Optional[float] = None
    for i, path in enumerate(paths):
        try:
            events, base = _load_trace_payload(path)
        except (OSError, ValueError) as exc:
            if i == 0:
                raise
            _LOG.warning(
                "graftscope: skipping unreadable trace file %s (%s)",
                path, exc,
            )
            if skipped is not None:
                skipped.append(os.path.basename(path))
            continue
        if i == 0:
            base0 = base
        shift_us = 0.0
        if i > 0 and base is not None and base0 is not None:
            shift_us = (base - base0) * 1e6
        named = {
            e.get("pid")
            for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        pids = {e.get("pid") for e in events if e.get("pid") is not None}
        label = os.path.basename(path)
        for suffix in (".json", ".trace"):
            if label.endswith(suffix):
                label = label[: -len(suffix)]
        for pid in sorted(p for p in pids - named if p is not None):
            out.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": label if i > 0 else "trainer"},
                }
            )
        for ev in events:
            if shift_us and "ts" in ev:
                ev = dict(ev)
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            out.append(ev)
    return out


def merge_trace_files(
    primary: str, extra_paths: List[str], out_path: Optional[str] = None
) -> str:
    """Merge ``extra_paths`` (compile-worker trace files) into ``primary``
    (in place by default) so one artifact holds the run's host spans AND the
    workers' compile walls as pid-tagged tracks. Returns the written path."""
    out_path = out_path or primary
    extras = [p for p in extra_paths if os.path.exists(p)]
    paths = [primary] + extras
    _, base = _load_trace_payload(primary)
    skipped: List[str] = []
    events = merge_trace_events(paths, skipped=skipped)
    payload = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        # record what was stitched so a later pass (summarize auto-stitching
        # siblings) skips these files instead of double-counting; torn files
        # surface in ``skipped`` rather than silently vanishing
        "graftscope": {
            "merged": sorted(
                set(merged_names(primary))
                | ({os.path.basename(p) for p in extras} - set(skipped))
            )
        },
    }
    if skipped:
        payload["graftscope"]["skipped"] = sorted(skipped)
    if base is not None:
        payload["graftscope"]["base_unix"] = base
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out_path)
    return out_path


def attribution(events: List[dict]) -> Dict:
    """Per-epoch wall attribution from Chrome-trace events.

    Epoch spans (cat=="epoch") define each epoch's wall; phase spans
    (cat=="phase") carrying the same ``args.epoch`` tile it — the
    instrumentation contract keeps phases non-overlapping on the controller
    thread, so their plain sum is the attributed wall. Returns::

        {"epochs": {epoch: {"wall_s", "phases": {name: s}, "coverage"}},
         "phase_totals_s": {name: s},
         "coverage_min": float | None}

    ``coverage`` is attributed/wall per epoch; ``coverage_min`` the worst
    epoch — the quantity the bench's >=0.95 acceptance reads.
    """
    walls: Dict[int, float] = {}
    phases: Dict[int, Dict[str, float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        epoch = (ev.get("args") or {}).get("epoch")
        if epoch is None:
            continue
        dur_s = float(ev.get("dur", 0.0)) / 1e6
        if ev.get("cat") == EPOCH_CAT:
            walls[epoch] = walls.get(epoch, 0.0) + dur_s
        elif ev.get("cat") == PHASE_CAT:
            phases.setdefault(epoch, {})
            phases[epoch][ev["name"]] = phases[epoch].get(ev["name"], 0.0) + dur_s
    epochs: Dict[int, Dict] = {}
    totals: Dict[str, float] = {}
    coverage_min: Optional[float] = None
    for epoch in sorted(walls):
        per = phases.get(epoch, {})
        wall = walls[epoch]
        cov = (sum(per.values()) / wall) if wall > 0 else None
        epochs[epoch] = {
            "wall_s": round(wall, 6),
            "phases": {k: round(v, 6) for k, v in sorted(per.items())},
            "coverage": round(cov, 4) if cov is not None else None,
        }
        for k, v in per.items():
            totals[k] = totals.get(k, 0.0) + v
        if cov is not None:
            coverage_min = cov if coverage_min is None else min(coverage_min, cov)
    return {
        "epochs": epochs,
        "phase_totals_s": {k: round(v, 6) for k, v in sorted(totals.items())},
        "coverage_min": round(coverage_min, 4) if coverage_min is not None else None,
    }


def attribution_by_job(events: List[dict]) -> Dict:
    """Per-JOB wall attribution (many-stream engine): epoch spans carrying
    an ``args.job`` tag (set by :meth:`Tracer.set_job` on each job's driver
    thread) group per tenant instead of per epoch index. Returns::

        {"jobs": {job: {"wall_s", "epochs", "phases": {name: s}}}}

    Untagged spans (a single-job run) land under the ``"-"`` pseudo-job,
    so `graftscope summarize --by-job` degrades gracefully on legacy
    traces."""
    jobs: Dict[str, Dict] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        if args.get("epoch") is None and ev.get("cat") not in (
            EPOCH_CAT, PHASE_CAT,
        ):
            continue
        job = str(args.get("job", "-"))
        dur_s = float(ev.get("dur", 0.0)) / 1e6
        rec = jobs.setdefault(
            job, {"wall_s": 0.0, "epochs": set(), "phases": {}}
        )
        if ev.get("cat") == EPOCH_CAT:
            rec["wall_s"] += dur_s
            if args.get("epoch") is not None:
                rec["epochs"].add(args["epoch"])
        elif ev.get("cat") == PHASE_CAT:
            rec["phases"][ev["name"]] = rec["phases"].get(ev["name"], 0.0) + dur_s
    return {
        "jobs": {
            job: {
                "wall_s": round(rec["wall_s"], 6),
                "epochs": len(rec["epochs"]),
                "phases": {
                    k: round(v, 6) for k, v in sorted(rec["phases"].items())
                },
            }
            for job, rec in sorted(jobs.items())
        }
    }


# -------------------------------------------------------------- global tracer

# One process-wide tracer: the instrumented modules (engine, pipeline, AOT
# service, solver, watchdog) fetch it by function call so a single configure()
# — from config or tests — flips every call site at once. Ships disabled.
_TRACER = Tracer(mode="off")


def get_tracer() -> Tracer:
    return _TRACER


def configure(
    mode: str, ring_size: int = 1_000_000, jax_annotations: bool = False
) -> Tracer:
    """(Re)configure the process-wide tracer; returns it. ``mode="off"``
    restores the zero-cost disabled state (buffer dropped)."""
    return _TRACER.configure(
        mode, ring_size=ring_size, jax_annotations=jax_annotations
    )
