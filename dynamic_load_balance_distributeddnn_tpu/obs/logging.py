"""Run logging.

File + stream logger whose format injects the run's world size, learning rate
and dbs/ft switches into every line, and whose file name encodes the full
config — the same observability contract as the reference (dbs_logging.py:5-34,
filename scheme dbs.py:54-61), minus the per-process fan-out: one controller
process logs for all logical workers, tagging lines with worker ranks where
relevant.
"""

from __future__ import annotations

import logging
import os
import socket
import time

from dynamic_load_balance_distributeddnn_tpu.config import Config

_FORMAT = (
    "%(asctime)s [%(world_size)s:%(lr)s:dbs_%(dbs)s:ft_%(ft)s] "
    "[%(filename)s:%(lineno)d] %(levelname)s %(message)s"
)


def _has_checkpoint(ckpt_dir: str) -> bool:
    """Structural twin of ``restore_checkpoint``'s found-a-checkpoint
    condition, cheap enough for logger init (no orbax import): the orbax
    manager creates the directory and writes per-step entries only on save,
    so a non-empty ckpt_dir means at least one save landed."""
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return False
    try:
        return any(os.scandir(ckpt_dir))
    except OSError:
        return False


def init_logger(cfg: Config, rank: int = 0, to_file: bool = True) -> logging.LoggerAdapter:
    extra = {
        "world_size": cfg.world_size,
        "lr": cfg.learning_rate,
        "dbs": "enabled" if cfg.dynamic_batch_size else "disabled",
        "ft": "enabled" if cfg.fault_tolerance else "disabled",
    }
    logger = logging.getLogger(f"{socket.gethostname()}.dbs_tpu")
    for h in logger.handlers[:]:
        logger.removeHandler(h)
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    formatter = logging.Formatter(_FORMAT)

    sh = logging.StreamHandler()
    sh.setFormatter(formatter)
    logger.addHandler(sh)

    if to_file:
        os.makedirs(cfg.log_dir, exist_ok=True)
        path = os.path.join(cfg.log_dir, cfg.base_filename().format(rank) + ".log")
        # A checkpoint-resumable run that re-inits its logger must not
        # truncate the history it is resuming (the old "w+" lost every
        # pre-crash line); append there, and tag each (re)start so the log
        # reads as one run with visible restart boundaries. "Resuming" is
        # keyed on a checkpoint ACTUALLY existing (the condition under which
        # the engine's _maybe_restore restores), not just on ckpt_dir being
        # set — a deliberately fresh run of a checkpointable config (dir
        # cleaned, or never saved) keeps truncate semantics, as does every
        # non-checkpointed config (a re-run of the same config IS a fresh
        # run — the reference's behavior, dbs_logging.py:29).
        resuming = _has_checkpoint(cfg.ckpt_dir) and os.path.exists(path)
        fh = logging.FileHandler(path, "a" if resuming else "w")
        fh.setFormatter(formatter)
        logger.addHandler(fh)
        start_kind = "resumed" if resuming else "started"
        # emitted through the handler so the tag carries the run-context
        # format fields, as the first line of this (re)start's segment
        logging.LoggerAdapter(logger, extra).info(
            f"==== run {start_kind} (pid {os.getpid()}, "
            f"{time.strftime('%Y-%m-%dT%H:%M:%S')}) ===="
        )

    return logging.LoggerAdapter(logger, extra)


def _done_sentinel(cfg: Config) -> str:
    return os.path.join(cfg.log_dir, cfg.base_filename().format(0) + ".done")


def mark_run_done(cfg: Config) -> None:
    """Record successful completion. The reference probes the rank-0 *log*
    (dbs.py:528-534), but the log is created at startup, so a crashed run
    would be skipped forever; a separate sentinel written only after the
    metrics are saved fixes that while keeping run-level idempotence."""
    os.makedirs(cfg.log_dir, exist_ok=True)
    with open(_done_sentinel(cfg), "w") as f:
        f.write("done\n")


def run_already_done(cfg: Config) -> bool:
    """Idempotence probe for completed runs (reference behavior,
    dbs.py:528-534, hardened via the post-completion sentinel)."""
    return os.path.isfile(_done_sentinel(cfg))
