"""Per-epoch metrics recorder.

Records the reference's nine per-epoch series (dbs.py:316-326, 429-438):
epoch, train_loss, train_time, sync_time, val_loss, accuracy, partition,
node_time, wallclock_time — and persists them as ``.npy`` under ``stat_dir``
with the config-encoded filename (dbs.py:440-442; unlike the reference, the
directory is created if missing). A JSON sidecar is written too, since the
judge and bench tooling read JSON more happily than pickled object arrays.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

SERIES = (
    "epoch",
    "train_loss",
    "train_time",
    "sync_time",
    "val_loss",
    "accuracy",
    "partition",
    "node_time",
    "wallclock_time",
)


def _pythonize(v):
    """Recursively coerce numpy/jax scalars and arrays to plain Python so the
    series stay JSON-serializable regardless of which execution path (fused,
    elastic, multi-host allgather) produced them."""
    if isinstance(v, np.ndarray):
        return v.tolist() if v.ndim else v.item()
    if isinstance(v, (np.floating, np.integer, np.bool_)):
        return v.item()
    if isinstance(v, (list, tuple)):
        return [_pythonize(x) for x in v]
    if hasattr(v, "item") and not isinstance(v, (int, float, bool, str)):
        try:
            return v.item()
        except Exception:
            return v
    return v


def _pythonize_meta(meta: Dict) -> Dict:
    return {k: _pythonize(v) for k, v in meta.items()}


class MetricsRecorder:
    def __init__(self):
        self.data: Dict[str, List] = {k: [] for k in SERIES}
        # Run-level facts that are not per-epoch series (e.g. whether the
        # data was a synthetic stand-in); saved under "_meta" in the JSON
        # sidecar, kept out of the reference-parity .npy payload.
        self.meta: Dict[str, object] = {}

    def stamp_data_source(self, src) -> None:
        """Record data provenance (synthetic stand-in? which fallbacks?) from
        a DatasetBundle or Corpus — every trainer stamps its recorder so the
        saved artifacts can't be mistaken for real-data results."""
        self.meta["synthetic"] = bool(getattr(src, "synthetic", False))
        notes = list(getattr(src, "notes", []))
        if notes:
            self.meta["data_notes"] = notes

    def record_epoch(self, **kw) -> None:
        """The reference's nine series are mandatory; extra keyword series
        (e.g. ``examples_per_s``, ``mfu_bf16_peak`` — the TPU build's
        throughput/MFU instrumentation) are recorded alongside them."""
        missing = set(SERIES) - set(kw)
        if missing:
            raise ValueError(f"missing series: {sorted(missing)}")
        for k, v in kw.items():
            self.data.setdefault(k, []).append(_pythonize(v))

    def save(self, stat_dir: str, base_filename: str, rank: int = 0) -> str:
        os.makedirs(stat_dir, exist_ok=True)
        stem = base_filename.format(rank)
        npy_path = os.path.join(stat_dir, stem + ".npy")
        np.save(npy_path, self.data)  # dict payload, like the reference
        payload = dict(self.data)
        if self.meta:
            payload["_meta"] = _pythonize_meta(self.meta)
        with open(os.path.join(stat_dir, stem + ".json"), "w") as f:
            json.dump(payload, f)
        return npy_path

    def last(self, key: str):
        """Last recorded value of a series, or None. Optional series
        (``examples_per_s``, ``host_dispatch_s``, ...) exist only on the
        paths that emit them, so an absent key is an answerable question
        (None), not a KeyError."""
        series = self.data.get(key)
        return series[-1] if series else None

    @classmethod
    def load(cls, npy_path: str) -> "MetricsRecorder":
        """Round-trip a saved artifact: the pickled ``.npy`` dict payload
        (``allow_pickle=True`` — np.save wraps the dict in an object array)
        plus, when present, the JSON sidecar's ``_meta`` (run-level facts
        live only there; the .npy keeps reference parity). Accepts the
        ``.npy`` path or the bare stem."""
        if not npy_path.endswith(".npy"):
            npy_path = npy_path + ".npy"
        payload = np.load(npy_path, allow_pickle=True).item()
        rec = cls()
        rec.data = {k: list(v) for k, v in payload.items() if k != "_meta"}
        json_path = npy_path[: -len(".npy")] + ".json"
        if os.path.exists(json_path):
            with open(json_path) as f:
                sidecar = json.load(f)
            rec.meta = dict(sidecar.get("_meta", {}))
        return rec
