"""``graftscope`` console entry point: read a Chrome-trace JSON written by
the span tracer (``--trace on|ring``) and answer "where did the wall go"
without opening Perfetto.

Usage::

    graftscope summarize traces/run.trace.json            # per-phase table
    graftscope summarize traces/run.trace.json --epoch 3  # one epoch only
    graftscope diff before.trace.json after.trace.json    # phase deltas
    graftscope summarize run.trace.json --json            # machine-readable

Exit status: 0 on success, 2 on usage/IO errors.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

from dynamic_load_balance_distributeddnn_tpu.obs.trace import attribution, load_trace


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(header), line(["-" * w for w in widths])]
    out += [line(r) for r in rows]
    return "\n".join(out)


def summarize(path: str, epoch: Optional[int] = None, as_json: bool = False) -> str:
    att = attribution(load_trace(path))
    epochs = att["epochs"]
    if epoch is not None:
        epochs = {k: v for k, v in epochs.items() if int(k) == epoch}
        if not epochs:
            raise ValueError(f"epoch {epoch} not present in {path}")
    if as_json:
        return json.dumps(
            {"epochs": epochs, "phase_totals_s": att["phase_totals_s"],
             "coverage_min": att["coverage_min"]}
        )
    out = []
    for ep, info in sorted(epochs.items(), key=lambda kv: int(kv[0])):
        wall = info["wall_s"]
        rows = [
            [name, f"{secs:.4f}", f"{100.0 * secs / wall:5.1f}%" if wall else "-"]
            for name, secs in sorted(
                info["phases"].items(), key=lambda kv: -kv[1]
            )
        ]
        unattributed = wall - sum(info["phases"].values())
        rows.append(
            ["(unattributed)", f"{unattributed:.4f}",
             f"{100.0 * unattributed / wall:5.1f}%" if wall else "-"]
        )
        cov = info["coverage"]
        head = f"epoch {ep}: wall {wall:.4f}s"
        if cov is not None:
            head += f", attribution {cov * 100:.1f}%"
        out.append(head)
        out.append(_fmt_table(rows, ["phase", "seconds", "% wall"]))
        out.append("")
    totals = att["phase_totals_s"]
    if totals and epoch is None:
        rows = [
            [name, f"{secs:.4f}"]
            for name, secs in sorted(totals.items(), key=lambda kv: -kv[1])
        ]
        out.append("run totals:")
        out.append(_fmt_table(rows, ["phase", "seconds"]))
        if att["coverage_min"] is not None:
            out.append(f"worst-epoch attribution: {att['coverage_min'] * 100:.1f}%")
    return "\n".join(out).rstrip()


def diff(path_a: str, path_b: str, as_json: bool = False) -> str:
    """Phase-total deltas B - A: the first stop of every perf PR review
    ('which phase did this change actually move?')."""
    a = attribution(load_trace(path_a))["phase_totals_s"]
    b = attribution(load_trace(path_b))["phase_totals_s"]
    names = sorted(set(a) | set(b))
    deltas: Dict[str, Dict] = {}
    for name in names:
        va, vb = a.get(name, 0.0), b.get(name, 0.0)
        deltas[name] = {
            "a_s": round(va, 6),
            "b_s": round(vb, 6),
            "delta_s": round(vb - va, 6),
            "ratio": round(vb / va, 4) if va > 0 else None,
        }
    if as_json:
        return json.dumps(deltas)
    rows = [
        [
            name,
            f"{d['a_s']:.4f}",
            f"{d['b_s']:.4f}",
            f"{d['delta_s']:+.4f}",
            f"{d['ratio']:.3f}x" if d["ratio"] is not None else "new",
        ]
        for name, d in sorted(deltas.items(), key=lambda kv: kv[1]["delta_s"])
    ]
    return _fmt_table(rows, ["phase", "A (s)", "B (s)", "delta", "B/A"])


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftscope",
        description=(
            "Summarize/diff graftscope traces (Chrome-trace JSON from "
            "--trace on|ring; open the same file in ui.perfetto.dev for "
            "the timeline view)."
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize", help="per-phase epoch-attribution table")
    s.add_argument("trace")
    s.add_argument("--epoch", type=int, default=None)
    s.add_argument("--json", action="store_true")
    d = sub.add_parser("diff", help="phase-total deltas between two traces")
    d.add_argument("trace_a")
    d.add_argument("trace_b")
    d.add_argument("--json", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "summarize":
            print(summarize(args.trace, epoch=args.epoch, as_json=args.json))
        else:
            print(diff(args.trace_a, args.trace_b, as_json=args.json))
    except (OSError, ValueError, KeyError) as exc:
        print(f"graftscope: {exc}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
