"""``graftscope`` console entry point: read a Chrome-trace JSON written by
the span tracer (``--trace on|ring``) and answer "where did the wall go"
without opening Perfetto.

Usage::

    graftscope summarize traces/run.trace.json            # per-phase table
    graftscope summarize traces/run.trace.json --epoch 3  # one epoch only
    graftscope diff before.trace.json after.trace.json    # phase deltas
    graftscope summarize run.trace.json --json            # machine-readable
    graftscope merge run.trace.json -o merged.json        # + worker traces

``summarize`` and ``merge`` automatically stitch compile-worker trace files
(``compile_worker_*.trace.json``, written per process by the AOT service's
process backend — runtime/compile_worker.py) found next to the run trace,
so compile walls attribute across processes as pid-tagged tracks
(``--no-workers`` reads the run trace alone).

Exit status: 0 on success, 2 on usage/IO errors.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
    attribution,
    load_trace,
    merge_trace_events,
    merge_trace_files,
    merged_names,
)


def _worker_traces(path: str) -> List[str]:
    """Compile-worker span files sitting next to a run trace that are NOT
    already stitched into it (the engine merges at save and records the
    filenames in the trace's ``graftscope.merged`` marker — re-stitching
    those would double-count their compile walls)."""
    done = set(merged_names(path))
    pattern = os.path.join(os.path.dirname(path) or ".", "compile_worker_*.trace.json")
    return sorted(
        p
        for p in glob.glob(pattern)
        if os.path.abspath(p) != os.path.abspath(path)
        and os.path.basename(p) not in done
    )


def _load_stitched(path: str, with_workers: bool) -> "tuple[List[dict], List[str]]":
    """(events, worker-trace provenance): stitches un-merged sibling worker
    files in; provenance also includes files the engine already merged, so
    the per-pid compile table renders for pre-stitched traces too."""
    workers = _worker_traces(path) if with_workers else []
    stitched = (workers + merged_names(path)) if with_workers else []
    if workers:
        return merge_trace_events([path] + workers), stitched
    return load_trace(path), stitched


def _compile_walls_by_pid(events: List[dict]) -> Dict[int, float]:
    """Total cat=="compile" span seconds per pid — the cross-process compile
    attribution the worker stitching exists for."""
    walls: Dict[int, float] = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("cat") == "compile":
            pid = ev.get("pid", 0)
            walls[pid] = walls.get(pid, 0.0) + float(ev.get("dur", 0.0)) / 1e6
    return walls


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(header), line(["-" * w for w in widths])]
    out += [line(r) for r in rows]
    return "\n".join(out)


def summarize(
    path: str,
    epoch: Optional[int] = None,
    as_json: bool = False,
    with_workers: bool = True,
) -> str:
    events, workers = _load_stitched(path, with_workers)
    att = attribution(events)
    compile_walls = _compile_walls_by_pid(events) if workers else {}
    epochs = att["epochs"]
    if epoch is not None:
        epochs = {k: v for k, v in epochs.items() if int(k) == epoch}
        if not epochs:
            raise ValueError(f"epoch {epoch} not present in {path}")
    if as_json:
        payload = {"epochs": epochs, "phase_totals_s": att["phase_totals_s"],
                   "coverage_min": att["coverage_min"]}
        if workers:
            payload["worker_traces"] = workers
            payload["compile_wall_s_by_pid"] = {
                str(k): round(v, 6) for k, v in sorted(compile_walls.items())
            }
        return json.dumps(payload)
    out = []
    for ep, info in sorted(epochs.items(), key=lambda kv: int(kv[0])):
        wall = info["wall_s"]
        rows = [
            [name, f"{secs:.4f}", f"{100.0 * secs / wall:5.1f}%" if wall else "-"]
            for name, secs in sorted(
                info["phases"].items(), key=lambda kv: -kv[1]
            )
        ]
        unattributed = wall - sum(info["phases"].values())
        rows.append(
            ["(unattributed)", f"{unattributed:.4f}",
             f"{100.0 * unattributed / wall:5.1f}%" if wall else "-"]
        )
        cov = info["coverage"]
        head = f"epoch {ep}: wall {wall:.4f}s"
        if cov is not None:
            head += f", attribution {cov * 100:.1f}%"
        out.append(head)
        out.append(_fmt_table(rows, ["phase", "seconds", "% wall"]))
        out.append("")
    totals = att["phase_totals_s"]
    if totals and epoch is None:
        rows = [
            [name, f"{secs:.4f}"]
            for name, secs in sorted(totals.items(), key=lambda kv: -kv[1])
        ]
        out.append("run totals:")
        out.append(_fmt_table(rows, ["phase", "seconds"]))
        if att["coverage_min"] is not None:
            out.append(f"worst-epoch attribution: {att['coverage_min'] * 100:.1f}%")
    if workers:
        out.append("")
        out.append(
            f"stitched {len(workers)} compile-worker trace file(s); "
            "compile wall by pid:"
        )
        out.append(
            _fmt_table(
                [[str(pid), f"{secs:.4f}"] for pid, secs in sorted(compile_walls.items())],
                ["pid", "compile s"],
            )
        )
    return "\n".join(out).rstrip()


def diff(path_a: str, path_b: str, as_json: bool = False) -> str:
    """Phase-total deltas B - A: the first stop of every perf PR review
    ('which phase did this change actually move?')."""
    a = attribution(load_trace(path_a))["phase_totals_s"]
    b = attribution(load_trace(path_b))["phase_totals_s"]
    names = sorted(set(a) | set(b))
    deltas: Dict[str, Dict] = {}
    for name in names:
        va, vb = a.get(name, 0.0), b.get(name, 0.0)
        deltas[name] = {
            "a_s": round(va, 6),
            "b_s": round(vb, 6),
            "delta_s": round(vb - va, 6),
            "ratio": round(vb / va, 4) if va > 0 else None,
        }
    if as_json:
        return json.dumps(deltas)
    rows = [
        [
            name,
            f"{d['a_s']:.4f}",
            f"{d['b_s']:.4f}",
            f"{d['delta_s']:+.4f}",
            f"{d['ratio']:.3f}x" if d["ratio"] is not None else "new",
        ]
        for name, d in sorted(deltas.items(), key=lambda kv: kv[1]["delta_s"])
    ]
    return _fmt_table(rows, ["phase", "A (s)", "B (s)", "delta", "B/A"])


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftscope",
        description=(
            "Summarize/diff graftscope traces (Chrome-trace JSON from "
            "--trace on|ring; open the same file in ui.perfetto.dev for "
            "the timeline view)."
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize", help="per-phase epoch-attribution table")
    s.add_argument("trace")
    s.add_argument("--epoch", type=int, default=None)
    s.add_argument("--json", action="store_true")
    s.add_argument("--no-workers", action="store_true",
                   help="do not stitch sibling compile_worker_*.trace.json")
    d = sub.add_parser("diff", help="phase-total deltas between two traces")
    d.add_argument("trace_a")
    d.add_argument("trace_b")
    d.add_argument("--json", action="store_true")
    m = sub.add_parser(
        "merge",
        help="write the run trace with sibling compile-worker traces "
        "stitched in (one Perfetto-loadable artifact)",
    )
    m.add_argument("trace")
    m.add_argument("-o", "--out", default=None,
                   help="output path (default: rewrite the run trace)")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "summarize":
            print(
                summarize(
                    args.trace,
                    epoch=args.epoch,
                    as_json=args.json,
                    with_workers=not args.no_workers,
                )
            )
        elif args.cmd == "merge":
            workers = _worker_traces(args.trace)
            out = merge_trace_files(args.trace, workers, out_path=args.out)
            print(f"merged {len(workers)} worker trace(s) -> {out}")
        else:
            print(diff(args.trace_a, args.trace_b, as_json=args.json))
    except (OSError, ValueError, KeyError) as exc:
        print(f"graftscope: {exc}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
