"""``graftscope`` console entry point: read a Chrome-trace JSON written by
the span tracer (``--trace on|ring``) and answer "where did the wall go"
without opening Perfetto.

Usage::

    graftscope summarize traces/run.trace.json            # per-phase table
    graftscope summarize traces/run.trace.json --epoch 3  # one epoch only
    graftscope diff before.trace.json after.trace.json    # phase deltas
    graftscope summarize run.trace.json --json            # machine-readable
    graftscope merge run.trace.json -o merged.json        # + worker traces
    graftscope postmortem spools/                         # crash stitcher
    graftscope decisions traces/run.trace.json            # DBS journal
    graftscope decisions spools/ --outcome committed --csv  # filtered export
    graftscope replay runs/bench.json --margin 6          # counterfactual
    graftscope sweep --grid small --random 8              # knob sweep
    graftscope conformance spools/                        # protocol replay

``summarize`` and ``merge`` automatically stitch compile-worker trace files
(``compile_worker_*.trace.json``, written per process by the AOT service's
process backend — runtime/compile_worker.py) found next to the run trace,
so compile walls attribute across processes as pid-tagged tracks
(``--no-workers`` reads the run trace alone).

``postmortem`` (ISSUE 15) is the flight-recorder reader: it merges every
``*.spool`` file (crash-durable spools from ``--trace_spool``, torn tails
tolerated) and any sibling ``*.trace.json`` in a directory into ONE
pid-tagged Perfetto trace — survivors' rendezvous state-machine spans next
to the victim's last spooled events, realigned by each file's unix-time
base — and prints a textual incident report (detection → drain → rebuild
per process). ``decisions`` renders the decision journal — the online-DBS
controller's switch/hold verdicts AND the outer many-stream allocator's
``pool_decision`` rows — with each row's derived outcome, filterable by
``--outcome``/``--since`` and exportable with ``--csv``, so "why did epoch
7 rebalance?" is answerable offline.

``replay`` and ``sweep`` (ISSUE 19) are the device-free controller lab
(balance/replaylab.py): ``replay`` re-runs a recorded decision journal
(bench artifact, trace, spool, or spool directory) through a fresh
controller — with no overrides it is a strict parity gate (every recorded
verdict must reproduce bit-for-bit), with ``--hysteresis/--margin/
--budget-frac/--rate-alpha`` it answers the counterfactual "what would the
run have done under different knobs". ``sweep`` grids (and optionally
randomizes) knobs over the synthesized scenario library — spike bursts,
correlated rack brownouts, diurnal load, kill-storms — and ranks them by
geomean speedup over the never-switch baseline. Both check every journal
against the controller invariants (switch spend within budget, no switch
without modeled gain clearing the gates, ledger monotonicity).

``conformance`` (ISSUE 16, graftrdzv) replays the recorded ``rdzv_*``
instants of every spool/trace under a directory against the rendezvous
PROTOCOL automaton (analysis/flow/proto.py): per process agreed(g) must
precede torn(g) must precede established(g) with strictly increasing
established generations, and across processes every establishment of one
generation must agree on roster and coordinator — so each real chaos-test
postmortem doubles as a checked protocol trace.

Exit status: 0 on success, 1 when ``conformance`` finds protocol
violations or ``replay``/``sweep`` find parity drift / invariant
violations, 2 on usage/IO errors (including an empty or missing spool
directory, or a ``decisions`` query matching no rows).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
    attribution,
    attribution_by_job,
    load_trace,
    merge_trace_events,
    merge_trace_files,
    merged_names,
)


def _worker_traces(path: str) -> List[str]:
    """Compile-worker span files sitting next to a run trace that are NOT
    already stitched into it (the engine merges at save and records the
    filenames in the trace's ``graftscope.merged`` marker — re-stitching
    those would double-count their compile walls)."""
    done = set(merged_names(path))
    pattern = os.path.join(os.path.dirname(path) or ".", "compile_worker_*.trace.json")
    return sorted(
        p
        for p in glob.glob(pattern)
        if os.path.abspath(p) != os.path.abspath(path)
        and os.path.basename(p) not in done
    )


def _load_stitched(
    path: str, with_workers: bool
) -> "tuple[List[dict], List[str], List[str]]":
    """(events, worker-trace provenance, skipped): stitches un-merged
    sibling worker files in; provenance also includes files the engine
    already merged, so the per-pid compile table renders for pre-stitched
    traces too. Torn/mid-write worker files land in ``skipped`` (the chaos
    harness kills processes during save) instead of failing the load."""
    workers = _worker_traces(path) if with_workers else []
    stitched = (workers + merged_names(path)) if with_workers else []
    skipped: List[str] = []
    if workers:
        events = merge_trace_events([path] + workers, skipped=skipped)
        stitched = [w for w in stitched if os.path.basename(w) not in skipped]
        return events, stitched, skipped
    return load_trace(path), stitched, skipped


def _compile_walls_by_pid(events: List[dict]) -> Dict[int, float]:
    """Total cat=="compile" span seconds per pid — the cross-process compile
    attribution the worker stitching exists for."""
    walls: Dict[int, float] = {}
    for ev in events:
        if ev.get("ph") == "X" and ev.get("cat") == "compile":
            pid = ev.get("pid", 0)
            walls[pid] = walls.get(pid, 0.0) + float(ev.get("dur", 0.0)) / 1e6
    return walls


def _fmt_table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def line(cells):
        return "  ".join(str(c).ljust(w) for c, w in zip(cells, widths)).rstrip()

    out = [line(header), line(["-" * w for w in widths])]
    out += [line(r) for r in rows]
    return "\n".join(out)


def summarize_by_job(
    path: str, as_json: bool = False, with_workers: bool = True
) -> str:
    """Per-TENANT wall attribution for many-stream traces: one row per job
    tag (``Tracer.set_job``), with epoch count, total epoch wall, and the
    dominant phases. Single-job traces render under the ``-`` pseudo-job."""
    events, workers, skipped = _load_stitched(path, with_workers)
    att = attribution_by_job(events)
    if as_json:
        payload = dict(att)
        if skipped:
            payload["skipped_traces"] = skipped
        return json.dumps(payload)
    jobs = att["jobs"]
    if not jobs:
        return "no epoch spans recorded (run with --trace on|ring)"
    rows = []
    for job, info in jobs.items():
        top = sorted(info["phases"].items(), key=lambda kv: -kv[1])[:3]
        rows.append(
            [
                job,
                str(info["epochs"]),
                f"{info['wall_s']:.4f}",
                ", ".join(f"{n} {s:.3f}s" for n, s in top) or "-",
            ]
        )
    out = [_fmt_table(rows, ["job", "epochs", "wall (s)", "top phases"])]
    if skipped:
        out.append(
            f"skipped {len(skipped)} unreadable worker trace file(s): "
            + ", ".join(skipped)
        )
    return "\n".join(out)


def summarize(
    path: str,
    epoch: Optional[int] = None,
    as_json: bool = False,
    with_workers: bool = True,
) -> str:
    events, workers, skipped = _load_stitched(path, with_workers)
    att = attribution(events)
    compile_walls = _compile_walls_by_pid(events) if workers else {}
    epochs = att["epochs"]
    if epoch is not None:
        epochs = {k: v for k, v in epochs.items() if int(k) == epoch}
        if not epochs:
            raise ValueError(f"epoch {epoch} not present in {path}")
    if as_json:
        payload = {"epochs": epochs, "phase_totals_s": att["phase_totals_s"],
                   "coverage_min": att["coverage_min"]}
        if workers:
            payload["worker_traces"] = workers
            payload["compile_wall_s_by_pid"] = {
                str(k): round(v, 6) for k, v in sorted(compile_walls.items())
            }
        if skipped:
            payload["skipped_traces"] = skipped
        return json.dumps(payload)
    out = []
    for ep, info in sorted(epochs.items(), key=lambda kv: int(kv[0])):
        wall = info["wall_s"]
        rows = [
            [name, f"{secs:.4f}", f"{100.0 * secs / wall:5.1f}%" if wall else "-"]
            for name, secs in sorted(
                info["phases"].items(), key=lambda kv: -kv[1]
            )
        ]
        unattributed = wall - sum(info["phases"].values())
        rows.append(
            ["(unattributed)", f"{unattributed:.4f}",
             f"{100.0 * unattributed / wall:5.1f}%" if wall else "-"]
        )
        cov = info["coverage"]
        head = f"epoch {ep}: wall {wall:.4f}s"
        if cov is not None:
            head += f", attribution {cov * 100:.1f}%"
        out.append(head)
        out.append(_fmt_table(rows, ["phase", "seconds", "% wall"]))
        out.append("")
    totals = att["phase_totals_s"]
    if totals and epoch is None:
        rows = [
            [name, f"{secs:.4f}"]
            for name, secs in sorted(totals.items(), key=lambda kv: -kv[1])
        ]
        out.append("run totals:")
        out.append(_fmt_table(rows, ["phase", "seconds"]))
        if att["coverage_min"] is not None:
            out.append(f"worst-epoch attribution: {att['coverage_min'] * 100:.1f}%")
    if workers:
        out.append("")
        out.append(
            f"stitched {len(workers)} compile-worker trace file(s); "
            "compile wall by pid:"
        )
        out.append(
            _fmt_table(
                [[str(pid), f"{secs:.4f}"] for pid, secs in sorted(compile_walls.items())],
                ["pid", "compile s"],
            )
        )
    if skipped:
        out.append("")
        out.append(
            f"skipped {len(skipped)} unreadable (torn/mid-write) worker "
            f"trace file(s): {', '.join(skipped)}"
        )
    return "\n".join(out).rstrip()


def diff(path_a: str, path_b: str, as_json: bool = False) -> str:
    """Phase-total deltas B - A: the first stop of every perf PR review
    ('which phase did this change actually move?')."""
    a = attribution(load_trace(path_a))["phase_totals_s"]
    b = attribution(load_trace(path_b))["phase_totals_s"]
    names = sorted(set(a) | set(b))
    deltas: Dict[str, Dict] = {}
    for name in names:
        va, vb = a.get(name, 0.0), b.get(name, 0.0)
        deltas[name] = {
            "a_s": round(va, 6),
            "b_s": round(vb, 6),
            "delta_s": round(vb - va, 6),
            "ratio": round(vb / va, 4) if va > 0 else None,
        }
    if as_json:
        return json.dumps(deltas)
    rows = [
        [
            name,
            f"{d['a_s']:.4f}",
            f"{d['b_s']:.4f}",
            f"{d['delta_s']:+.4f}",
            f"{d['ratio']:.3f}x" if d["ratio"] is not None else "new",
        ]
        for name, d in sorted(deltas.items(), key=lambda kv: kv[1]["delta_s"])
    ]
    return _fmt_table(rows, ["phase", "A (s)", "B (s)", "delta", "B/A"])


# ------------------------------------------------------------- postmortem


def _is_postmortem_output(path: str) -> bool:
    """Does this trace carry the postmortem stitcher's own metadata marker?
    A previous run's output (under ANY -o name) must never be re-ingested
    as a source — its trace-only tracks would double-count."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return False
    return isinstance(data, dict) and bool(
        (data.get("graftscope") or {}).get("postmortem")
    )


def _gather_sources(
    dir_or_file: str, exclude: "Optional[set]" = None
) -> "tuple[List[Dict], List[str]]":
    """Load every spool and trace under a directory (or the single file
    given) into per-source dicts ``{"label", "pid", "ident", "base_unix",
    "events", "truncated", "dropped", "kind"}``. Unreadable files are
    skipped and reported, never fatal — this is the crash path.
    ``exclude`` holds resolved paths to never ingest (the run's own output);
    earlier postmortem outputs are recognized by their metadata marker."""
    exclude = {os.path.abspath(p) for p in (exclude or ())}
    if os.path.isdir(dir_or_file):
        spools = sorted(glob.glob(os.path.join(dir_or_file, "*.spool")))
        traces = sorted(
            p
            for p in glob.glob(os.path.join(dir_or_file, "*.trace.json"))
            if os.path.abspath(p) not in exclude
            and not _is_postmortem_output(p)
        )
    elif dir_or_file.endswith(".spool"):
        spools, traces = [dir_or_file], []
    else:
        spools, traces = [], [dir_or_file]
    from dynamic_load_balance_distributeddnn_tpu.obs.trace import (
        _load_trace_payload,
    )
    from dynamic_load_balance_distributeddnn_tpu.obs.spool import (
        spool_to_chrome,
    )
    sources: List[Dict] = []
    skipped: List[str] = []
    for path in spools:
        label = os.path.basename(path)
        try:
            got = spool_to_chrome(path)
        except (OSError, ValueError) as exc:
            print(f"graftscope: skipping {label}: {exc}", file=sys.stderr)
            skipped.append(label)
            continue
        got.update(label=label[: -len(".spool")], kind="spool")
        sources.append(got)
    spool_pids = {s["pid"] for s in sources}
    for path in traces:
        label = os.path.basename(path)
        try:
            events, base = _load_trace_payload(path)
        except (OSError, ValueError) as exc:
            print(f"graftscope: skipping {label}: {exc}", file=sys.stderr)
            skipped.append(label)
            continue
        # a process's SPOOL is the canonical record: a run trace saved by
        # the same pid (e.g. --trace_dir pointing into the spool dir, or a
        # survivor's end-of-run save copied next to the spools) holds the
        # same events and would double-count every span; keep only the
        # tracks of pids with no spool (merged compile workers, etc.)
        dup = {
            e.get("pid")
            for e in events
            if e.get("pid") in spool_pids
        }
        if dup:
            events = [e for e in events if e.get("pid") not in spool_pids]
            print(
                f"graftscope: {label}: dropping pid(s) "
                f"{sorted(int(p) for p in dup)} already covered by a spool",
                file=sys.stderr,
            )
            if not events:
                continue
        pids = sorted(
            {e.get("pid") for e in events if e.get("pid") is not None}
        )
        sources.append(
            {
                "label": label[: -len(".trace.json")]
                if label.endswith(".trace.json")
                else label,
                "kind": "trace",
                "pid": pids[0] if pids else 0,
                "ident": None,
                "base_unix": base,
                "events": events,
                "truncated": False,
                "dropped": 0,
            }
        )
    return sources, skipped


def _merge_sources(sources: List[Dict]) -> "tuple[List[dict], Optional[float]]":
    """Shift every source's events into ONE timeline: the reference frame is
    the EARLIEST ``base_unix`` (the first process to come up), the same
    unix-twin realignment ``merge_trace_events`` uses. Sources with no base
    stamp land unshifted (best effort beats dropped evidence)."""
    bases = [s["base_unix"] for s in sources if s["base_unix"] is not None]
    base0 = min(bases) if bases else None
    out: List[dict] = []
    for s in sources:
        shift_us = 0.0
        if base0 is not None and s["base_unix"] is not None:
            shift_us = (s["base_unix"] - base0) * 1e6
        named = {
            e.get("pid")
            for e in s["events"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        pids = {e.get("pid") for e in s["events"] if e.get("pid") is not None}
        for pid in sorted(p for p in pids - named if p is not None):
            out.append(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": s["label"]},
                }
            )
        for ev in s["events"]:
            if shift_us and "ts" in ev:
                ev = dict(ev)
                ev["ts"] = round(ev["ts"] + shift_us, 3)
            out.append(ev)
    return out, base0


# span/instant categories that narrate an incident, in rough ladder order
_INCIDENT_SPAN_CATS = ("recover", "rdzv")
_INCIDENT_INSTANT_CATS = ("elastic", "rdzv", "fault", "health")


def _incident_report(
    sources: List[Dict], merged: List[dict], base0: Optional[float]
) -> Dict:
    """Structured incident report over the merged, realigned events: per
    process, the last spooled evidence and the recovery spans (detection →
    drain → rebuild); fleet-wide, the chronological instant-event
    timeline."""

    def _wall(ts_us: float) -> Optional[float]:
        return None if base0 is None else round(base0 + ts_us / 1e6, 3)

    procs: Dict[int, Dict] = {}
    for s in sources:
        procs.setdefault(int(s.get("pid") or 0), {}).update(
            source=s["label"],
            kind=s["kind"],
            ident=s.get("ident"),
            truncated=bool(s.get("truncated")),
            dropped=int(s.get("dropped") or 0),
        )
    timeline: List[Dict] = []
    for ev in merged:
        pid = ev.get("pid", 0)
        info = procs.setdefault(int(pid), {"source": str(pid), "kind": "?"})
        if ev.get("ph") == "M":
            continue
        info["events"] = info.get("events", 0) + 1
        ts = float(ev.get("ts", 0.0))
        end = ts + float(ev.get("dur", 0.0))
        if end >= info.get("last_ts", float("-inf")):
            info["last_ts"] = end
        tail = info.setdefault("_tail", [])
        tail.append({"name": ev.get("name"), "ts_us": round(ts, 1)})
        if len(tail) > 8:
            del tail[0]
        if ev.get("ph") == "X" and ev.get("cat") in _INCIDENT_SPAN_CATS:
            info.setdefault("recovery_spans", []).append(
                {
                    "name": ev.get("name"),
                    "start_s": round(ts / 1e6, 4),
                    "dur_s": round(float(ev.get("dur", 0.0)) / 1e6, 4),
                    "wall_unix": _wall(ts),
                }
            )
        if ev.get("ph") == "i" and ev.get("cat") in _INCIDENT_INSTANT_CATS:
            timeline.append(
                {
                    "ts_us": round(ts, 1),
                    "wall_unix": _wall(ts),
                    "pid": pid,
                    "cat": ev.get("cat"),
                    "name": ev.get("name"),
                    "args": ev.get("args") or {},
                }
            )
    timeline.sort(key=lambda e: e["ts_us"])
    decisions = sum(
        1
        for ev in merged
        if ev.get("ph") == "i" and ev.get("cat") == "decision"
    )
    for info in procs.values():
        info["last_events"] = info.pop("_tail", [])
        if "last_ts" in info:
            info["last_seen_unix"] = _wall(info.pop("last_ts"))
        if "recovery_spans" in info:
            info["recovery_spans"].sort(key=lambda s: s["start_s"])
    return {
        "processes": {str(pid): info for pid, info in sorted(procs.items())},
        "timeline": timeline,
        "decision_events": decisions,
    }


def _render_incident(report: Dict, out_trace: str) -> str:
    lines: List[str] = [f"merged Perfetto trace: {out_trace}", ""]
    for pid, info in report["processes"].items():
        head = f"process {pid} ({info.get('kind', '?')}:{info.get('source')})"
        if info.get("ident") is not None:
            head += f" ident={info['ident']}"
        if info.get("truncated"):
            head += "  [TORN TAIL: died mid-write]"
        lines.append(head)
        lines.append(
            f"  events: {info.get('events', 0)}"
            + (
                f", dropped at spool: {info['dropped']}"
                if info.get("dropped")
                else ""
            )
            + (
                f", last seen unix {info['last_seen_unix']}"
                if info.get("last_seen_unix") is not None
                else ""
            )
        )
        if info.get("last_events"):
            tail = ", ".join(e["name"] for e in info["last_events"])
            lines.append(f"  last events: {tail}")
        for sp in info.get("recovery_spans", ()):
            lines.append(
                f"  recovery span {sp['name']}: start +{sp['start_s']:.3f}s, "
                f"{sp['dur_s']:.3f}s"
            )
        lines.append("")
    if report["timeline"]:
        lines.append("fleet timeline (detection → drain → rebuild):")
        rows = []
        for ev in report["timeline"]:
            args = ev["args"]
            brief = ", ".join(
                f"{k}={args[k]}"
                for k in ("peer", "reason", "ranks", "procs", "gen", "roster",
                          "worker", "verdict", "signal", "phase", "epoch")
                if k in args
            )
            rows.append(
                [
                    f"+{ev['ts_us'] / 1e6:.3f}s",
                    f"p{ev['pid']}",
                    ev["cat"],
                    ev["name"],
                    brief,
                ]
            )
        lines.append(_fmt_table(rows, ["t", "proc", "cat", "event", "detail"]))
    if report["decision_events"]:
        lines.append("")
        lines.append(
            f"{report['decision_events']} controller decision event(s) "
            "recorded — `graftscope decisions` renders the journal"
        )
    return "\n".join(lines).rstrip()


def postmortem(
    dir_or_file: str, out: Optional[str] = None, as_json: bool = False
) -> str:
    """Stitch every spool/trace under ``dir_or_file`` into one Perfetto
    trace and produce the incident report. Returns the rendered report (or
    its JSON form)."""
    out_trace = out or (
        os.path.join(dir_or_file, "postmortem.trace.json")
        if os.path.isdir(dir_or_file)
        else dir_or_file + ".postmortem.trace.json"
    )
    sources, skipped = _gather_sources(dir_or_file, exclude={out_trace})
    if not sources:
        raise ValueError(f"no readable spool/trace files under {dir_or_file}")
    merged, base0 = _merge_sources(sources)
    payload = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "graftscope": {
            # the marker _gather_sources keys on: this artifact is an
            # OUTPUT, never a source for a later stitch
            "postmortem": True,
            "merged": [s["label"] for s in sources],
            "skipped": skipped,
            "truncated": [
                s["label"] for s in sources if s.get("truncated")
            ],
        },
    }
    if base0 is not None:
        payload["graftscope"]["base_unix"] = base0
    tmp = out_trace + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, out_trace)
    report = _incident_report(sources, merged, base0)
    report["skipped"] = skipped
    report["trace"] = out_trace
    if as_json:
        return json.dumps(report)
    return _render_incident(report, out_trace)


# -------------------------------------------------------------- decisions


def _decision_events(path: str) -> List[dict]:
    """cat=="decision" instants from a trace file, spool file, or directory
    of spools — the controller journal's offline surface."""
    if os.path.isdir(path) or path.endswith(".spool"):
        sources, _ = _gather_sources(path)
        if not sources:
            # an empty/missing spool dir used to render the friendly
            # "no decision events" note and exit 0 — masking a wrong path
            # in CI scripts; no evidence at all is an error, not a journal
            raise ValueError(f"no readable spool/trace files under {path}")
        events, _ = _merge_sources(sources)
    else:
        events = load_trace(path)
    return [
        e
        for e in events
        if e.get("ph") == "i" and e.get("cat") == "decision"
    ]


def _paired_decisions(evs: List[dict]) -> "tuple[List[dict], int]":
    """Normalize decision instants into rows with a derived ``outcome``.
    The live journal annotates outcomes in place, but the trace stream
    keeps each ``dbs_decision`` as decided and interleaves ``dbs_switch``/
    ``dbs_deferred`` after it — pairing re-derives what actually happened.
    Also returns the largest ``journal_dropped`` count seen (ring-eviction
    honesty for the header). ``dbs_config`` instants are construction
    metadata, not verdicts — skipped here (the replay lab reads them)."""
    rows: List[dict] = []
    last: Optional[dict] = None
    dropped = 0
    for e in evs:
        name = e.get("name")
        a = dict(e.get("args") or {})
        dropped = max(dropped, int(a.get("journal_dropped", 0) or 0))
        if name == "dbs_config":
            continue
        row = {"name": name, "ts": e.get("ts"), "args": a}
        if name in ("dbs_decision", "pool_decision"):
            row["outcome"] = a.get("outcome") or (
                "pending" if a.get("switch") else "hold"
            )
            last = row
        elif name == "dbs_switch":
            row["outcome"] = "committed"
            if last is not None and last["name"] == "dbs_decision":
                last["outcome"] = "committed"
        elif name == "dbs_deferred":
            row["outcome"] = "deferred"
            if last is not None and last["name"] == "dbs_decision":
                last["outcome"] = "deferred"
        rows.append(row)
    return rows, dropped


def _decision_row_cells(row: dict) -> List[str]:
    a = row["args"]
    if row["name"] == "dbs_deferred":
        return ["-", "-", "deferred", "-", "-", "-", "-", "-",
                "engine warm-gate", row["outcome"]]
    if row["name"] == "pool_decision":
        # the OUTER loop's verdicts (many-stream device allocation): the
        # win column carries the modeled makespan gain, the batches column
        # the proposed per-tenant device counts
        verdict = "MIGRATE" if a.get("switch") else "hold"
        gain = a.get("modeled_gain")
        return [
            str(a.get("epoch", "-")),
            str(a.get("window", "-")),
            verdict,
            a.get("reason", "-"),
            "-" if gain is None else f"{gain:.4f}",
            "-", "-", "-",
            str(a.get("proposed_counts", "-")),
            row["outcome"],
        ]
    verdict = "SWITCH" if a.get("switch") else "hold"
    if row["name"] == "dbs_switch":
        verdict = "committed"
    return [
        str(a.get("epoch", a.get("eval", "-"))),
        str(a.get("window", "-")),
        verdict,
        a.get("reason", "-"),
        f"{a.get('predicted_win_s', 0.0):.4f}",
        f"{a.get('cur_step_s', 0.0):.4f}",
        f"{a.get('new_step_s', 0.0):.4f}",
        f"{a.get('cost_est_s', a.get('switch_cost_s', 0.0)):.4f}",
        str(a.get("candidate_batches", a.get("batches", "-"))),
        row["outcome"],
    ]


_DECISION_HEADER = ["epoch", "win", "verdict", "reason", "win_s", "cur_step",
                    "new_step", "cost_s", "batches", "outcome"]


def decisions(
    path: str,
    as_json: bool = False,
    outcome: Optional[str] = None,
    since: Optional[int] = None,
    as_csv: bool = False,
) -> str:
    """Render the decision journal (inner DBS controller AND the outer
    many-stream allocator): one row per evaluation with verdict, reason,
    derived outcome, and the inputs behind it. ``outcome`` filters to
    committed/deferred/hold rows; ``since`` keeps rows at epoch >= N (rows
    with no epoch tag are dropped under the filter); ``as_csv`` exports
    the table machine-readably. An empty result — no decision events at
    all, or none surviving the filters — raises (exit 2), consistent with
    postmortem/conformance."""
    rows, dropped = _paired_decisions(_decision_events(path))
    if outcome is not None:
        rows = [r for r in rows if r["outcome"] == outcome]
    if since is not None:
        rows = [
            r
            for r in rows
            if r["args"].get("epoch") is not None
            and int(r["args"]["epoch"]) >= int(since)
        ]
    if not rows:
        raise ValueError(
            f"no controller decision events under {path}"
            + (" (after filters)" if outcome is not None or since is not None
               else " (run with --rebalance window and --trace on|ring)")
        )
    if as_json:
        return json.dumps(
            [
                {"name": r["name"], "ts": r["ts"], "outcome": r["outcome"],
                 **r["args"]}
                for r in rows
            ]
        )
    cells = [_decision_row_cells(r) for r in rows]
    if as_csv:
        import csv
        import io

        buf = io.StringIO()
        w = csv.writer(buf)
        w.writerow(_DECISION_HEADER)
        w.writerows(cells)
        return buf.getvalue().rstrip("\n")
    head = f"{len(rows)} decision row(s)"
    if dropped:
        head += (
            f" — journal_dropped={dropped} older evaluation(s) evicted "
            "from the ring (the journal head is truncated)"
        )
    return head + "\n" + _fmt_table(cells, _DECISION_HEADER)


# ---------------------------------------------------------- controller lab


def replay_cmd(
    path: str, knobs: Dict, as_json: bool = False
) -> "tuple[str, bool]":
    """``graftscope replay``: re-run a recorded decision journal through a
    fresh controller (balance/replaylab.py). With no knob overrides this
    is the strict parity gate; with overrides it is a counterfactual.
    Returns ``(rendered, ok)`` — ``ok=False`` (exit 1) on parity drift or
    invariant violations."""
    from dynamic_load_balance_distributeddnn_tpu.balance import replaylab

    overrides = {k: v for k, v in knobs.items() if v is not None}
    corpus = replaylab.load_corpus(path)
    report = replaylab.replay(corpus, knobs=overrides or None)
    ok = not report["mismatches"] and not report["invariant_violations"]
    if as_json:
        return json.dumps(report), ok
    lines = [
        f"replay: {report['entries']} journal entr(ies) from "
        f"{report.get('label')} [{report['mode']}]",
        "  knobs: "
        + ", ".join(f"{k}={v}" for k, v in report["knobs"].items()),
        f"  recorded: {report['recorded']['switches']} switch(es), "
        f"{report['recorded']['deferred']} deferred, modeled wall "
        f"{report['recorded']['modeled_wall_s']}s "
        f"(spend {report['recorded']['switch_spend_s']}s)",
        f"  replayed: {report['replayed']['switches']} switch(es), "
        f"{report['replayed']['deferred']} deferred, modeled wall "
        f"{report['replayed']['modeled_wall_s']}s "
        f"(spend {report['replayed']['switch_spend_s']}s, ledger "
        f"spent {report['replayed']['spent_s']}s / credit "
        f"{report['replayed']['credit_s']}s)",
        f"  never-switch hold wall: {report['hold_modeled_wall_s']}s",
    ]
    if report["mode"] == "strict":
        lines.append(
            "  parity: OK — recorded verdict sequence reproduced"
            if report["parity"]
            else f"  parity: DRIFT — {len(report['mismatches'])} mismatch(es)"
        )
        for m in report["mismatches"][:10]:
            lines.append(f"    entry {m['index']}: {m['field']} — {m['detail']}")
    for v in report["invariant_violations"][:10]:
        lines.append(
            f"  INVARIANT VIOLATION @ eval {v['eval']}: {v['invariant']} "
            f"({v['detail']})"
        )
    if report["invariant_violations"]:
        lines.append(
            f"  invariants: {len(report['invariant_violations'])} violation(s)"
        )
    else:
        lines.append("  invariants: clean")
    return "\n".join(lines), ok


def sweep_cmd(
    scenarios: Optional[str],
    world_size: int,
    grid: str,
    n_random: int,
    seed: int,
    as_json: bool = False,
    out: Optional[str] = None,
) -> "tuple[str, bool]":
    """``graftscope sweep``: device-free knob sweep over the synthesized
    scenario library, ranked by geometric-mean speedup over the hold
    baseline. ``ok=False`` (exit 1) when any simulated journal violates
    the controller invariants."""
    from dynamic_load_balance_distributeddnn_tpu.balance import replaylab

    lib = replaylab.builtin_scenarios(world_size)
    if scenarios:
        want = [s.strip() for s in scenarios.split(",") if s.strip()]
        by_name = {sc.name: sc for sc in lib}
        unknown = [w for w in want if w not in by_name]
        if unknown:
            raise ValueError(
                f"unknown scenario(s) {unknown}; available: "
                + ", ".join(sorted(by_name))
            )
        lib = [by_name[w] for w in want]
    knob_sets = replaylab.knob_grid(grid)
    if n_random > 0:
        knob_sets = knob_sets + replaylab.random_knobs(n_random, seed=seed)
    report = replaylab.sweep(lib, knob_sets)
    ok = report["invariant_violations"] == 0
    if out:
        with open(out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    if as_json:
        return json.dumps(report), ok
    rows = [
        [
            str(i + 1),
            json.dumps(r["knobs"]) if isinstance(r["knobs"], dict)
            else r["knobs"],
            f"{r['score']:.4f}",
            str(r["switches"]),
            f"{r['spent_s']:.4f}",
        ]
        for i, r in enumerate(report["results"][:10])
    ]
    lines = [
        f"sweep: {report['candidates']} knob set(s) x "
        f"{len(report['scenarios'])} scenario(s) "
        f"({', '.join(report['scenarios'])})",
        _fmt_table(rows, ["rank", "knobs", "speedup_vs_hold", "switches",
                          "spent_s"]),
    ]
    if report["best"] and report["default"]:
        lines.append(
            f"best {report['best']['score']:.4f} vs default "
            f"{report['default']['score']:.4f} "
            f"(x{report['best_vs_default']})"
        )
    lines.append(
        "invariants: clean across every simulated journal"
        if ok
        else f"invariants: {report['invariant_violations']} VIOLATION(S)"
    )
    if out:
        lines.append(f"full ranked report -> {out}")
    return "\n".join(lines), ok


# ------------------------------------------------------------ conformance


def conformance(dir_or_file: str, as_json: bool = False) -> "tuple[str, bool]":
    """Replay every recorded ``rdzv_*`` instant under ``dir_or_file``
    against the rendezvous PROTOCOL automaton. Returns ``(rendered, ok)``;
    the CLI maps ``ok=False`` to exit status 1 so the chaos harness can
    gate on it."""
    from dynamic_load_balance_distributeddnn_tpu.analysis.flow.proto import (
        check_conformance,
    )

    sources, skipped = _gather_sources(dir_or_file)
    if not sources:
        raise ValueError(f"no readable spool/trace files under {dir_or_file}")
    merged, _base0 = _merge_sources(sources)
    violations, stats = check_conformance(merged)
    ok = not violations
    if as_json:
        return (
            json.dumps(
                {
                    "ok": ok,
                    "violations": violations,
                    "stats": stats,
                    "skipped": skipped,
                }
            ),
            ok,
        )
    lines: List[str] = []
    if stats["events"] == 0:
        # sources existed but none carried protocol instants: report it
        # rather than calling silence conformant-looking
        lines.append(
            "conformance: no rdzv_* instants recorded under "
            f"{dir_or_file} (nothing to validate)"
        )
        return "\n".join(lines), ok
    for v in violations:
        lines.append(f"VIOLATION: {v}")
    verdict = "OK" if ok else f"{len(violations)} violation(s)"
    gens = ", ".join(str(g) for g in stats["generations"]) or "-"
    procs = ", ".join(str(p) for p in stats["processes"])
    lines.append(
        f"conformance: {verdict} — {stats['events']} protocol event(s) "
        f"across process(es) [{procs}], established generation(s) [{gens}]"
    )
    counts = ", ".join(
        f"{name}×{n}" for name, n in sorted(stats["counts"].items())
    )
    if counts:
        lines.append(f"  instants: {counts}")
    if skipped:
        lines.append(
            f"  skipped {len(skipped)} unreadable file(s): "
            + ", ".join(skipped)
        )
    return "\n".join(lines), ok


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftscope",
        description=(
            "Summarize/diff graftscope traces (Chrome-trace JSON from "
            "--trace on|ring; open the same file in ui.perfetto.dev for "
            "the timeline view)."
        ),
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("summarize", help="per-phase epoch-attribution table")
    s.add_argument("trace")
    s.add_argument("--epoch", type=int, default=None)
    s.add_argument("--json", action="store_true")
    s.add_argument("--no-workers", action="store_true",
                   help="do not stitch sibling compile_worker_*.trace.json")
    s.add_argument("--by-job", action="store_true",
                   help="attribute wall per tenant (many-stream traces: one "
                   "row per job tag instead of per epoch index)")
    d = sub.add_parser("diff", help="phase-total deltas between two traces")
    d.add_argument("trace_a")
    d.add_argument("trace_b")
    d.add_argument("--json", action="store_true")
    m = sub.add_parser(
        "merge",
        help="write the run trace with sibling compile-worker traces "
        "stitched in (one Perfetto-loadable artifact)",
    )
    m.add_argument("trace")
    m.add_argument("-o", "--out", default=None,
                   help="output path (default: rewrite the run trace)")
    pm = sub.add_parser(
        "postmortem",
        help="flight-recorder stitcher: merge every *.spool (crash-durable "
        "spools, torn tails tolerated) and *.trace.json under a directory "
        "into one pid-tagged Perfetto trace + a textual incident report",
    )
    pm.add_argument("dir", help="directory of spools/traces (or one file)")
    pm.add_argument("-o", "--out", default=None,
                    help="merged trace path (default: "
                    "<dir>/postmortem.trace.json)")
    pm.add_argument("--json", action="store_true",
                    help="structured incident report instead of text")
    dc = sub.add_parser(
        "decisions",
        help="render the online-DBS controller's decision journal (every "
        "switch/hold verdict with its recorded inputs) from a trace, "
        "spool, or spool directory",
    )
    dc.add_argument("path")
    dc.add_argument("--json", action="store_true")
    dc.add_argument("--outcome", choices=("committed", "deferred", "hold"),
                    default=None,
                    help="only rows whose derived outcome matches")
    dc.add_argument("--since", type=int, default=None, metavar="EPOCH",
                    help="only rows at epoch >= EPOCH (rows with no epoch "
                    "tag, e.g. outer pool_decision rows, are dropped)")
    dc.add_argument("--csv", action="store_true",
                    help="CSV export of the decision table")
    rp = sub.add_parser(
        "replay",
        help="controller lab: re-run a recorded decision journal (corpus "
        "JSON, trace, spool, or spool directory) through a fresh "
        "controller — strict parity gate by default, counterfactual with "
        "knob overrides (exit 1 on parity drift or invariant violations)",
    )
    rp.add_argument("path", help="corpus/snapshot JSON, trace file, .spool, "
                    "or spool directory")
    rp.add_argument("--hysteresis", type=float, default=None)
    rp.add_argument("--margin", type=float, default=None)
    rp.add_argument("--budget-frac", type=float, default=None)
    rp.add_argument("--rate-alpha", type=float, default=None)
    rp.add_argument("--json", action="store_true")
    sw = sub.add_parser(
        "sweep",
        help="controller lab: device-free knob sweep over the synthesized "
        "scenario library (spike/brownout/diurnal/kill-storm ...), ranked "
        "by geomean speedup over the hold baseline (exit 1 on invariant "
        "violations in any simulated journal)",
    )
    sw.add_argument("--scenarios", default=None,
                    help="comma-separated subset of builtin scenario names "
                    "(default: all)")
    sw.add_argument("--world-size", type=int, default=4)
    sw.add_argument("--grid", choices=("small", "full"), default="small",
                    help="knob grid density (default small: 18 points)")
    sw.add_argument("--random", type=int, default=0, metavar="N",
                    help="add N seeded log-uniform random knob sets")
    sw.add_argument("--seed", type=int, default=0)
    sw.add_argument("--json", action="store_true")
    sw.add_argument("-o", "--out", default=None,
                    help="also write the full ranked JSON report here")
    cf = sub.add_parser(
        "conformance",
        help="replay recorded rdzv_* instants against the rendezvous "
        "PROTOCOL automaton (exit 1 on protocol violations) — every "
        "chaos-test spool directory doubles as a checked protocol trace",
    )
    cf.add_argument("dir", help="directory of spools/traces (or one file)")
    cf.add_argument("--json", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        if args.cmd == "summarize":
            if args.by_job:
                if args.epoch is not None:
                    raise ValueError("--by-job and --epoch are exclusive")
                print(
                    summarize_by_job(
                        args.trace,
                        as_json=args.json,
                        with_workers=not args.no_workers,
                    )
                )
            else:
                print(
                    summarize(
                        args.trace,
                        epoch=args.epoch,
                        as_json=args.json,
                        with_workers=not args.no_workers,
                    )
                )
        elif args.cmd == "merge":
            workers = _worker_traces(args.trace)
            out = merge_trace_files(args.trace, workers, out_path=args.out)
            print(f"merged {len(workers)} worker trace(s) -> {out}")
        elif args.cmd == "postmortem":
            print(postmortem(args.dir, out=args.out, as_json=args.json))
        elif args.cmd == "decisions":
            print(
                decisions(
                    args.path,
                    as_json=args.json,
                    outcome=args.outcome,
                    since=args.since,
                    as_csv=args.csv,
                )
            )
        elif args.cmd == "replay":
            text, ok = replay_cmd(
                args.path,
                {
                    "hysteresis": args.hysteresis,
                    "margin": args.margin,
                    "budget_frac": args.budget_frac,
                    "rate_alpha": args.rate_alpha,
                },
                as_json=args.json,
            )
            print(text)
            if not ok:
                return 1
        elif args.cmd == "sweep":
            text, ok = sweep_cmd(
                args.scenarios,
                args.world_size,
                args.grid,
                args.random,
                args.seed,
                as_json=args.json,
                out=args.out,
            )
            print(text)
            if not ok:
                return 1
        elif args.cmd == "conformance":
            text, ok = conformance(args.dir, as_json=args.json)
            print(text)
            if not ok:
                return 1
        else:
            print(diff(args.trace_a, args.trace_b, as_json=args.json))
    except (OSError, ValueError, KeyError) as exc:
        print(f"graftscope: {exc}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
