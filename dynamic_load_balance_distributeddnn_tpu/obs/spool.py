"""Crash-durable trace spool: the flight recorder's disk sink (ISSUE 15).

graftscope's buffer lives in a process-local deque saved only at end of run
(``Tracer.save``), so the SIGKILL'd and wedged processes the elastic
machinery exists to survive die *with their evidence*. This module streams
the same event tuples to an append-only per-process spool file through a
background flusher thread, so a hard kill loses at most the last flush
interval of events — the victim's timeline survives its process.

File format — length-framed JSONL, built for torn tails:

    <nbytes> <json-body>\n

Each frame is one line: the decimal byte-length of the JSON body, a space,
the body, a newline. A process killed mid-``write`` leaves a final frame
whose body is shorter than its header claims (or a header with no body at
all); the reader detects exactly that and returns every complete frame plus
``truncated=True`` — no record boundary is ever guessed from JSON repair.

Frame bodies:

* ``{"t": "meta", ...}`` — spool identity: pid, logical ident, the tracer's
  ``base_unix`` (the unix-time twin of its ``perf_counter`` base — the same
  cross-process realignment key ``merge_trace_events`` uses), written at
  attach and re-written when the tracer rebases (``Tracer.reset``);
* ``{"t": "ev", "events": [...], "threads": {...}, "dropped": n}`` — a
  batch of raw tracer tuples ``(name, cat, ph, ts_us, dur_us, tid, args)``
  plus any thread names first seen since the previous flush.

Writer contract (the hot-path side):

* ``put()`` is one bounded-deque append — no lock, no serialization, no
  I/O on the emitting thread (``deque.append`` is GIL-atomic; a full queue
  drops the OLDEST buffered events, counted and reported in the next
  frame's ``dropped``);
* the flusher thread wakes every ``flush_interval_s`` OR when the queue
  crosses ``watermark`` events, serializes the drained batch, writes one
  frame, and optionally ``fsync``\\ s (``fsync=True`` trades flush latency
  for power-loss durability; the default survives process death, which is
  the chaos harness's fault model);
* ``close()`` drains synchronously — a clean exit loses nothing.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

# queue sentinel: a rebase record carries the tracer's NEW base_unix after
# Tracer.reset() (events before/after it are in different timebases)
_REBASE = "__rebase__"


def _json_default(o):
    """Last-resort serializer: spool frames must never kill the flusher
    thread over an exotic arg value (numpy scalar, Path, ...)."""
    try:
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        if isinstance(o, np.ndarray):
            return o.tolist()
    except Exception:  # pragma: no cover - numpy always present here
        pass
    return str(o)


class SpoolWriter:
    """Append-only spool file with a background flusher thread."""

    def __init__(
        self,
        path: str,
        *,
        base_unix: Optional[float] = None,
        ident: Optional[int] = None,
        pid: Optional[int] = None,
        flush_interval_s: float = 0.25,
        watermark: int = 512,
        max_queue: int = 65536,
        fsync: bool = False,
    ):
        if flush_interval_s <= 0:
            raise ValueError("flush_interval_s must be > 0")
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.path = path
        self.fsync = bool(fsync)
        self.watermark = int(watermark)
        self._q: deque = deque(maxlen=int(max_queue))
        self._enqueued = 0   # approximate (unlocked int adds) — drop accounting
        self._flushed = 0    # records consumed from the queue (incl. drops)
        self._dropped_pending = 0  # drops awaiting their report frame
        self.bytes_written = 0
        self._f = open(path, "ab")
        self._thread_names_src: Optional[Dict[int, str]] = None
        self._threads_sent: set = set()
        self._io_lock = threading.Lock()  # close() vs flusher file writes
        # one flush at a time: flush()/close() callers vs the flusher thread
        # — drain + frame write must stay atomic or frames interleave and
        # the drop accounting races
        self._flush_lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._pid = int(pid) if pid is not None else os.getpid()
        self._ident = ident
        self._write_meta(
            base_unix if base_unix is not None else time.time(), ident
        )
        self._flusher = threading.Thread(
            target=self._run,
            args=(float(flush_interval_s),),
            daemon=True,
            name="trace-spool",
        )
        self._flusher.start()

    # ------------------------------------------------------------ hot path

    def put(self, rec: tuple) -> None:
        """Enqueue one tracer event tuple. Never blocks, never touches the
        file. A full queue drops the oldest events.

        Deliberately unlocked (same contract as the tracer's emit path):
        ``deque.append`` is GIL-atomic, and the flusher only ever
        ``popleft``\\ s — the two ends never contend on an element. The
        ``_enqueued`` counter is approximate by design (drop accounting,
        not a ledger); a lost increment under-counts drops by one."""
        self._q.append(rec)  # graftlint: disable=G012
        self._enqueued += 1  # graftlint: disable=G012
        if len(self._q) >= self.watermark:
            self._wake.set()  # graftlint: disable=G012

    def note_rebase(self, base_unix: float) -> None:
        """The tracer rebased (``reset()``): queue a meta frame so events
        after this point realign against the NEW unix stamp."""
        self._q.append((_REBASE, float(base_unix)))  # graftlint: disable=G012
        self._enqueued += 1  # graftlint: disable=G012
        self._wake.set()  # graftlint: disable=G012

    # ----------------------------------------------------------- flushing

    def _run(self, interval_s: float) -> None:
        while not self._stop.is_set():
            self._wake.wait(interval_s)
            # Event.clear is internally locked; the worst race (a set()
            # landing between wait and clear) costs one early wake-up
            self._wake.clear()  # graftlint: disable=G012
            try:
                self._flush_once()
            except Exception:  # noqa: BLE001 — a sick disk must not kill the run
                pass

    def _drain(self) -> List[tuple]:
        out: List[tuple] = []
        q = self._q
        while True:
            try:
                out.append(q.popleft())  # graftlint: disable=G012
            except IndexError:
                return out

    def _new_thread_names(self) -> Dict[str, str]:
        src = self._thread_names_src
        if not src:
            return {}
        fresh = {}
        for tid, name in list(src.items()):
            if tid not in self._threads_sent:
                self._threads_sent.add(tid)
                fresh[str(tid)] = name
        return fresh

    def _write_frame(self, body: Dict) -> None:
        data = json.dumps(body, default=_json_default).encode("utf-8")
        frame = str(len(data)).encode("ascii") + b" " + data + b"\n"
        with self._io_lock:
            if self._f.closed:
                return
            self._f.write(frame)
            self._f.flush()
            if self.fsync:
                os.fsync(self._f.fileno())
            self.bytes_written += len(frame)

    def _write_meta(self, base_unix: float, ident: Optional[int] = None) -> None:
        meta: Dict = {
            "t": "meta",
            "pid": self._pid,
            "base_unix": float(base_unix),
            "written_unix": time.time(),
        }
        if ident is None:
            ident = self._ident
        if ident is not None:
            meta["ident"] = int(ident)
        self._write_frame(meta)

    def _flush_once(self) -> None:
        with self._flush_lock:
            batch = self._drain()
            if not batch:
                return
            # drop accounting ONCE over the whole drained batch — rebase
            # sentinels count as consumed records, so a reset never reads
            # as a drop: dropped = enqueued - already consumed - this
            # batch - still queued (approximate by design: the counters
            # are unlocked; an under-count loses one drop, never invents
            # one)
            dropped = max(
                self._enqueued - self._flushed - len(batch) - len(self._q), 0
            )
            self._flushed += len(batch) + dropped
            self._dropped_pending += dropped
            # split around rebase sentinels so frame order preserves timebases
            run: List[tuple] = []
            for rec in batch:
                if len(rec) == 2 and rec[0] == _REBASE:
                    self._emit_events(run)
                    run = []
                    self._write_meta(rec[1])
                else:
                    run.append(rec)
            self._emit_events(run)

    def _emit_events(self, events: List[tuple]) -> None:
        if not events:
            return
        body: Dict = {"t": "ev", "events": [list(e) for e in events]}
        threads = self._new_thread_names()
        if threads:
            body["threads"] = threads
        if self._dropped_pending:
            body["dropped"] = int(self._dropped_pending)
            self._dropped_pending = 0  # report each overflow once
        self._write_frame(body)

    def flush(self) -> None:
        """Synchronous drain of everything queued so far."""
        self._flush_once()

    def close(self) -> None:
        """Drain and close. Idempotent; the flusher thread exits."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._wake.set()
        self._flusher.join(timeout=5.0)
        try:
            self._flush_once()
        except Exception:  # noqa: BLE001 — closing a sick spool stays quiet
            pass
        with self._io_lock:
            if not self._f.closed:
                self._f.close()


# ---------------------------------------------------------------- reading


def read_spool(path: str) -> Dict:
    """Parse one spool file, tolerating a torn final record.

    Returns ``{"meta": first-meta-dict-or-None, "segments": [(base_unix,
    [event tuples])...], "threads": {tid: name}, "dropped": n,
    "truncated": bool, "frames": n}``. ``segments`` groups events by the
    meta frame (timebase) preceding them; a file with no rebase has one
    segment. A header or body shorter than the framing claims — the
    SIGKILL-mid-write case — terminates the parse with ``truncated=True``;
    everything before it is returned intact.
    """
    with open(path, "rb") as f:
        data = f.read()
    meta: Optional[Dict] = None
    segments: List[Tuple[Optional[float], List[tuple]]] = []
    cur_base: Optional[float] = None
    cur_events: List[tuple] = []
    threads: Dict[str, str] = {}
    dropped = 0
    frames = 0
    truncated = False
    pos = 0
    n = len(data)
    while pos < n:
        sp = data.find(b" ", pos, pos + 20)
        if sp < 0:
            truncated = True
            break
        try:
            body_len = int(data[pos:sp])
        except ValueError:
            truncated = True
            break
        start, end = sp + 1, sp + 1 + body_len
        if end + 1 > n or data[end:end + 1] != b"\n":
            truncated = True
            break
        try:
            body = json.loads(data[start:end])
        except ValueError:
            truncated = True
            break
        frames += 1
        pos = end + 1
        if body.get("t") == "meta":
            if meta is None:
                meta = body
            if cur_events:
                segments.append((cur_base, cur_events))
                cur_events = []
            cur_base = body.get("base_unix")
        elif body.get("t") == "ev":
            cur_events.extend(tuple(e) for e in body.get("events", ()))
            threads.update(body.get("threads") or {})
            dropped += int(body.get("dropped", 0))
    if cur_events:
        segments.append((cur_base, cur_events))
    return {
        "meta": meta,
        "segments": segments,
        "threads": threads,
        "dropped": dropped,
        "truncated": truncated,
        "frames": frames,
    }


def spool_to_chrome(path: str) -> Dict:
    """One spool file -> Chrome-trace events in ITS OWN timebase, plus the
    realignment key. Returns ``{"events": [...], "base_unix": float|None,
    "pid": int, "ident": int|None, "truncated": bool, "dropped": int}``.

    Multi-segment spools (the tracer rebased mid-run) shift later segments
    into the FIRST segment's timebase using the per-segment unix stamps, so
    one spool always yields one coherent timeline."""
    parsed = read_spool(path)
    meta = parsed["meta"] or {}
    pid = int(meta.get("pid", 0))
    base0: Optional[float] = None
    out: List[dict] = []
    for tid, name in sorted(parsed["threads"].items()):
        out.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": int(tid),
                "args": {"name": name},
            }
        )
    for seg_base, events in parsed["segments"]:
        if base0 is None:
            base0 = seg_base
        shift_us = 0.0
        if seg_base is not None and base0 is not None and seg_base != base0:
            shift_us = (seg_base - base0) * 1e6
        for rec in events:
            try:
                name, cat, ph, ts, dur, tid, args = rec
            except ValueError:
                continue  # malformed row inside an intact frame: skip it
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": round(float(ts) + shift_us, 3),
                "pid": pid,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(float(dur), 3)
            if args:
                ev["args"] = args
            out.append(ev)
    return {
        "events": out,
        "base_unix": base0,
        "pid": pid,
        "ident": meta.get("ident"),
        "truncated": parsed["truncated"],
        "dropped": parsed["dropped"],
    }
