from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    initial_partition,
    integer_batch_split,
    rebalance,
    rebalance_py,
)
from dynamic_load_balance_distributeddnn_tpu.balance.timing import (
    HostOverheadMeter,
    TimeKeeper,
    exchange_times,
)

__all__ = [
    "initial_partition",
    "integer_batch_split",
    "rebalance",
    "rebalance_py",
    "HostOverheadMeter",
    "TimeKeeper",
    "exchange_times",
]
