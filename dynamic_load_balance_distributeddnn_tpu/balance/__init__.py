from dynamic_load_balance_distributeddnn_tpu.balance.controller import (
    OnlineRebalanceController,
    SwitchDecision,
)
from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    equilibrium_shares,
    initial_partition,
    integer_batch_split,
    rebalance,
    rebalance_py,
)
from dynamic_load_balance_distributeddnn_tpu.balance.timing import (
    HostOverheadMeter,
    TimeKeeper,
    exchange_times,
)

__all__ = [
    "OnlineRebalanceController",
    "SwitchDecision",
    "equilibrium_shares",
    "initial_partition",
    "integer_batch_split",
    "rebalance",
    "rebalance_py",
    "HostOverheadMeter",
    "TimeKeeper",
    "exchange_times",
]
