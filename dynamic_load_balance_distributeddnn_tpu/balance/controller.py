"""Online DBS: the window-cadence rebalance controller (ISSUE 11).

The reference (and this engine's epoch loop) re-solves the inverse-time
partition once per EPOCH, so a straggler that appears mid-epoch is paid for
until the next boundary — the time-varying scenario (``sin``/ramp injection
schedules, faults.py ScheduledStragglerInjector) the epoch cadence cannot
touch. With supersteps (one dispatch per window), compile-horizon-zero and
solver-trajectory speculation already shipped, a mid-epoch plan change is
nearly free — what was missing is the DECISION machinery: when is a switch
worth its cost?

This controller answers at window cadence, in the style of *Online Dynamic
Batching with Formal Guarantees for LLM Training* (PAPERS.md): a regret-style
account where the cost of acting (switching plans) is only ever paid when the
predicted remaining-horizon win covers it with margin, and cumulative switch
spend is budgeted against cumulative banked wins so the plan cannot thrash
even under an adversarial signal.

Signal path (engine -> controller):

* **EMA per-worker rates** — seconds/example per worker, seeded from the
  engine's probe anchors (``per_example_cost``) or last node-time vector and
  folded with ``observe_rates`` each evaluation;
* **instantaneous fault multipliers** — the injector's ``faults_at`` view of
  the schedule at the next window's midpoint (the engine composes them into
  the effective rates it hands ``propose``);
* **measured step-wall feedback** — the realized wall of the windows since
  the last evaluation vs the model's prediction, folded in as a bounded
  multiplicative scale (``observe_wall``), so genuine un-modeled speed
  changes move the ABSOLUTE win estimate (and therefore the hysteresis
  decision) without disturbing the relative allocation.

Decision rule (hysteresis + regret budget):

    switch  iff  candidate != current plan
            and  win >= hysteresis * predicted remaining time   (relative)
            and  win >= margin * switch_cost                    (absolute)
            and  spent + switch_cost <= budget_frac * (credit + win)

where ``win = (step_time(current) - step_time(candidate)) * remaining_steps``
under the per-device step-time model (max over devices of the summed worker
times on that device), ``switch_cost`` is the EMA of MEASURED switch costs
(seeded by ``cost_init``), and (spent, credit) are the cumulative cost/win
ledgers. Every quantity is host-side numpy; the controller never touches jax.

The engine additionally warm-gates: a switch whose candidate executables are
not yet AOT-compiled is DEFERRED (``note_deferred``), so a switch never pays
a foreground XLA compile — the zero-foreground-compile sentinel contract
(tests/test_online_dbs.py).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    quantize_batches,
    rebalance,
)
from dynamic_load_balance_distributeddnn_tpu.obs.trace import get_tracer

# decision-journal ring cap: one entry per controller evaluation; a week-long
# run at window cadence stays bounded, and the postmortem question ("why did
# epoch 7 rebalance?") only ever needs the recent tail
JOURNAL_CAP = 4096


@dataclasses.dataclass
class SwitchDecision:
    """One evaluation's outcome. ``switch`` is the controller's verdict; the
    engine may still defer (cold executables) via ``note_deferred``."""

    switch: bool
    reason: str
    candidate_batches: Optional[np.ndarray] = None
    candidate_shares: Optional[np.ndarray] = None
    predicted_win_s: float = 0.0
    cur_step_s: float = 0.0
    new_step_s: float = 0.0
    cost_est_s: float = 0.0
    remaining_steps: int = 0


def step_time(
    rates: np.ndarray,
    batches: np.ndarray,
    groups: Sequence[Sequence[int]],
    comm_s: float = 0.0,
) -> float:
    """Modeled per-step wall under a batch split: workers sharing a device
    serialize (sum), devices run in parallel (max) — the elastic dispatch
    topology's cost model. ``comm_s`` is the gradient-collective wall the
    step pays AFTER the slowest device finishes its compute (ISSUE 17):
    batch-split-independent (the wire moves the same bytes whatever the
    shares), so it is additive — it shifts both modeled walls equally and
    therefore damps the RELATIVE win (hysteresis sees win/cur_step), keeping
    the controller honest on comm-bound topologies where a compute
    rebalance buys less of the step than the compute-only model claims."""
    r = np.asarray(rates, dtype=np.float64)
    b = np.asarray(batches, dtype=np.float64)
    per_worker = r * b
    compute = float(
        max(sum(per_worker[w] for w in g) for g in groups if len(g))
    )
    return compute + max(float(comm_s), 0.0)


class OnlineRebalanceController:
    """Window-cadence hysteresis controller over the inverse-time solver."""

    def __init__(
        self,
        world_size: int,
        global_batch: int,
        groups: Sequence[Sequence[int]],
        *,
        bucket: int = 0,
        max_share: Optional[float] = None,
        hysteresis: float = 0.1,
        margin: float = 3.0,
        budget_frac: float = 0.5,
        rate_alpha: float = 0.5,
        cost_init: float = 0.01,
        logger=None,
    ):
        if not 0.0 < rate_alpha <= 1.0:
            raise ValueError("rate_alpha must be in (0, 1]")
        if hysteresis < 0.0 or margin < 0.0 or budget_frac <= 0.0:
            raise ValueError("hysteresis/margin must be >= 0, budget_frac > 0")
        self.world_size = int(world_size)
        self.global_batch = int(global_batch)
        self.groups = [list(g) for g in groups if len(g)]
        self.bucket = int(bucket)
        self.max_share = float(max_share) if max_share is not None else None
        self.hysteresis = float(hysteresis)
        self.margin = float(margin)
        self.budget_frac = float(budget_frac)
        self.rate_alpha = float(rate_alpha)
        self.cost_init = float(cost_init)
        self.logger = logger
        # modeled per-step gradient-collective wall (seconds): the engine
        # sets it from _comm_bytes_per_step over the probe's measured link
        # rates when --grad_comm hier resolves (ISSUE 17); 0.0 = compute-only
        # model (flat combine or no probe data)
        self.comm_step_s = 0.0
        # EMA state
        self.rates: Optional[np.ndarray] = None  # seconds/example per worker
        self.wall_scale = 1.0  # bounded measured/modeled wall feedback
        self.switch_cost_s: Optional[float] = None  # EMA of measured costs
        # ledgers (the regret-style account)
        self.spent_s = 0.0  # switch cost actually paid
        self.credit_s = 0.0  # predicted wins banked at executed switches
        self.switches = 0
        self.evals = 0
        self.deferred = 0  # engine vetoes (candidate executables cold)
        self.last_candidate_batches: Optional[np.ndarray] = None
        self.events: List[Dict] = []
        self.on_switch = None  # test/observability hook: fn(event_dict)
        # decision journal (ISSUE 15): EVERY evaluation's verdict — hold or
        # switch — with the inputs it was decided on, so "why did epoch 7
        # rebalance?" (and "why did it NOT?") is answerable offline. Ring-
        # bounded; mirrored as graftscope ``decision`` instants when tracing
        # is enabled and surfaced by `graftscope decisions`.
        self.journal: deque = deque(maxlen=JOURNAL_CAP)
        # ring evictions: a replayed corpus must be honest about truncation —
        # a journal that silently lost its head is not the full history
        self.journal_dropped = 0
        # engine-owned position tag ({"epoch": e, "window": w}) merged into
        # every journal entry at decision time, so HOLD verdicts carry their
        # epoch too (commit() only annotates executed switches) and the
        # `graftscope decisions --since` filter has something to cut on
        self.eval_context: Dict = {}
        self._config_traced = False

    # ---------------------------------------------------------- replay seam

    def journal_config(self) -> Dict:
        """The construction surface a replay needs to rebuild THIS controller
        (balance/replaylab.py): topology + knobs, JSON-safe. Carried in the
        registry snapshot and (once, lazily) as a ``dbs_config`` trace
        instant so spools and traces are self-describing corpora."""
        return {
            "world_size": self.world_size,
            "global_batch": self.global_batch,
            "groups": [list(g) for g in self.groups],
            "bucket": self.bucket,
            "max_share": self.max_share,
            "hysteresis": self.hysteresis,
            "margin": self.margin,
            "budget_frac": self.budget_frac,
            "rate_alpha": self.rate_alpha,
            "cost_init": self.cost_init,
        }

    @classmethod
    def from_journal_config(
        cls, config: Dict, **knob_overrides
    ) -> "OnlineRebalanceController":
        """Rebuild a fresh controller from a recorded ``journal_config()``,
        optionally overriding the decision knobs (hysteresis / margin /
        budget_frac / rate_alpha / cost_init) for counterfactual replay."""
        kw = {
            "bucket": int(config.get("bucket", 0)),
            "max_share": config.get("max_share"),
            "hysteresis": float(config.get("hysteresis", 0.1)),
            "margin": float(config.get("margin", 3.0)),
            "budget_frac": float(config.get("budget_frac", 0.5)),
            "rate_alpha": float(config.get("rate_alpha", 0.5)),
            "cost_init": float(config.get("cost_init", 0.01)),
        }
        for k, v in knob_overrides.items():
            if k not in kw:
                raise ValueError(f"unknown controller knob override: {k!r}")
            if v is not None:
                kw[k] = float(v)
        return cls(
            int(config["world_size"]),
            int(config["global_batch"]),
            [list(g) for g in config["groups"]],
            **kw,
        )

    # ------------------------------------------------------------- signal

    def observe_rates(self, rates: np.ndarray) -> None:
        """Fold a fresh per-worker per-example rate estimate into the EMA
        (``rate_alpha`` weights the newest sample). A world-size change
        restarts the track — stale per-worker identities mean nothing."""
        r = np.asarray(rates, dtype=np.float64)
        if not np.isfinite(r).all() or (r <= 0).any():
            return
        if self.rates is None or self.rates.shape != r.shape:
            self.rates = r.copy()
            return
        scale = float(np.median(r) / max(np.median(self.rates), 1e-300))
        if not 0.25 <= scale <= 4.0:
            # a whole-track scale jump is a re-anchoring (fresh probe
            # baseline, clock regime change), not a gradual drift — folding
            # it through the EMA would leave the absolute win estimates at
            # the wrong scale for a half-life of evaluations
            self.rates = r.copy()
            return
        self.rates = self.rate_alpha * r + (1.0 - self.rate_alpha) * self.rates

    def observe_wall(self, measured_s: float, modeled_s: float) -> None:
        """Step-wall feedback: the measured wall of the windows since the
        last evaluation vs the model's prediction for the same windows. The
        bounded ratio scales the ABSOLUTE win estimate (a uniformly slow or
        fast host moves every worker the same way — the relative allocation
        stays with the rates); the clip keeps one outlier wall from swinging
        the hysteresis decision."""
        if modeled_s <= 0 or measured_s <= 0 or not np.isfinite(measured_s):
            return
        scale = float(np.clip(measured_s / modeled_s, 0.25, 4.0))
        self.wall_scale = 0.5 * scale + 0.5 * self.wall_scale

    # ----------------------------------------------------------- decision

    def cost_estimate(self) -> float:
        return self.switch_cost_s if self.switch_cost_s is not None else self.cost_init

    def _record_decision(
        self,
        dec: SwitchDecision,
        eff_rates: Optional[np.ndarray] = None,
        cur_batches: Optional[np.ndarray] = None,
    ) -> SwitchDecision:
        """Journal one evaluation: verdict + the inputs it was decided on
        (EMA rates, modeled walls, regret ledgers, hysteresis state). Also
        emitted as a graftscope ``decision`` instant so the flight
        recorder's spool carries the journal through a crash."""
        ev: Dict = {
            "eval": int(self.evals),
            "switch": bool(dec.switch),
            "reason": dec.reason,
            "predicted_win_s": round(float(dec.predicted_win_s), 6),
            "cur_step_s": round(float(dec.cur_step_s), 6),
            "new_step_s": round(float(dec.new_step_s), 6),
            "cost_est_s": round(float(dec.cost_est_s), 6),
            "remaining_steps": int(dec.remaining_steps),
            # replay INPUTS (balance/replaylab.py restores these before
            # re-proposing): full precision, NOT rounded — JSON round-trips
            # float64 exactly, and the decision gates sit at exact
            # equalities often enough that a 1e-6 display round flips
            # borderline verdicts and breaks bit-for-bit parity
            "wall_scale": float(self.wall_scale),
            "comm_step_s": float(self.comm_step_s),
            "hysteresis": self.hysteresis,
            "margin": self.margin,
            "budget_frac": self.budget_frac,
            "spent_s": float(self.spent_s),
            "credit_s": float(self.credit_s),
            "switch_cost_ema_s": (
                float(self.switch_cost_s)
                if self.switch_cost_s is not None
                else None
            ),
        }
        for k, v in self.eval_context.items():
            ev.setdefault(k, v)
        if eff_rates is not None:
            ev["eff_rates"] = [float(r) for r in eff_rates]
        if cur_batches is not None:
            ev["cur_batches"] = [int(b) for b in cur_batches]
        if dec.candidate_batches is not None:
            ev["candidate_batches"] = [int(b) for b in dec.candidate_batches]
        if dec.candidate_shares is not None:
            ev["candidate_shares"] = [
                round(float(s), 6) for s in dec.candidate_shares
            ]
        if len(self.journal) == self.journal.maxlen:
            self.journal_dropped += 1
        self.journal.append(ev)
        tracer = get_tracer()
        if tracer.enabled:
            if not self._config_traced:
                # once per controller: the construction surface, so a spool
                # or trace file alone is a replayable corpus
                self._config_traced = True
                tracer.instant(
                    "dbs_config", cat="decision", args=self.journal_config()
                )
            # a COPY: commit/note_deferred annotate the journal entry later,
            # and the trace must keep the verdict as decided
            args = dict(ev)
            if self.journal_dropped:
                args["journal_dropped"] = self.journal_dropped
            tracer.instant("dbs_decision", cat="decision", args=args)
        return dec

    def decision_journal(self) -> List[Dict]:
        """The journal as a JSON-safe list (oldest first, ring-bounded)."""
        return [dict(ev) for ev in self.journal]

    def propose(
        self,
        eff_rates: np.ndarray,
        cur_batches: np.ndarray,
        remaining_steps: int,
    ) -> SwitchDecision:
        """One evaluation: solve the inverse-time partition on the effective
        rates and decide whether switching the remaining windows onto it
        beats the measured switch cost under hysteresis + budget."""
        self.evals += 1
        c = np.asarray(eff_rates, dtype=np.float64)
        b_cur = np.asarray(cur_batches, dtype=np.int64)
        if remaining_steps <= 0:
            return self._record_decision(SwitchDecision(False, "no-horizon"))
        if not np.isfinite(c).all() or (c <= 0).any():
            return self._record_decision(SwitchDecision(False, "no-signal"))
        cur_shares = b_cur.astype(np.float64) / max(b_cur.sum(), 1)
        times = c * np.maximum(b_cur, 1)
        new_shares, batches = rebalance(
            times, cur_shares, self.global_batch, max_share=self.max_share
        )
        if self.bucket > 0:
            batches = quantize_batches(batches, self.bucket, self.global_batch)
            new_shares = batches.astype(np.float64) / batches.sum()
        self.last_candidate_batches = batches.copy()
        if np.array_equal(batches, b_cur):
            return self._record_decision(
                SwitchDecision(
                    False, "same-plan", batches, new_shares,
                    remaining_steps=int(remaining_steps),
                ),
                c, b_cur,
            )
        cur_step = (
            step_time(c, b_cur, self.groups, comm_s=self.comm_step_s)
            * self.wall_scale
        )
        new_step = (
            step_time(c, batches, self.groups, comm_s=self.comm_step_s)
            * self.wall_scale
        )
        win = (cur_step - new_step) * remaining_steps
        cost = self.cost_estimate()
        dec = SwitchDecision(
            False,
            "",
            batches,
            new_shares,
            predicted_win_s=win,
            cur_step_s=cur_step,
            new_step_s=new_step,
            cost_est_s=cost,
            remaining_steps=int(remaining_steps),
        )
        if win < self.hysteresis * cur_step * remaining_steps:
            dec.reason = "below-hysteresis"
            return self._record_decision(dec, c, b_cur)
        if win < self.margin * cost:
            dec.reason = "below-margin"
            return self._record_decision(dec, c, b_cur)
        if self.spent_s + cost > self.budget_frac * (self.credit_s + win):
            dec.reason = "budget-exhausted"
            return self._record_decision(dec, c, b_cur)
        dec.switch = True
        dec.reason = "switch"
        return self._record_decision(dec, c, b_cur)

    # --------------------------------------------------------- bookkeeping

    def commit(
        self, dec: SwitchDecision, measured_cost_s: float, **extra
    ) -> Dict:
        """The engine EXECUTED the switch: pay the measured cost into the
        ledger, bank the predicted win, fold the cost EMA, and record the
        event (engine mirrors it into recorder meta / graftscope)."""
        self.switches += 1
        self.spent_s += float(measured_cost_s)
        self.credit_s += max(float(dec.predicted_win_s), 0.0)
        prev = self.switch_cost_s
        self.switch_cost_s = (
            float(measured_cost_s)
            if prev is None
            else 0.5 * float(measured_cost_s) + 0.5 * prev
        )
        ev = {
            "reason": dec.reason,
            "predicted_win_s": round(float(dec.predicted_win_s), 6),
            "switch_cost_s": round(float(measured_cost_s), 6),
            "cur_step_s": round(float(dec.cur_step_s), 6),
            "new_step_s": round(float(dec.new_step_s), 6),
            "remaining_steps": int(dec.remaining_steps),
            "batches": [int(b) for b in dec.candidate_batches],
            "spent_s": round(self.spent_s, 6),
            "credit_s": round(self.credit_s, 6),
        }
        ev.update(extra)
        self.events.append(ev)
        if self.journal:
            # annotate the evaluation that produced this switch with what
            # actually happened (the engine may defer/veto between the two)
            self.journal[-1]["outcome"] = "committed"
            self.journal[-1]["measured_cost_s"] = round(float(measured_cost_s), 6)
            for k in ("epoch", "window", "step"):
                if k in extra:
                    self.journal[-1][k] = extra[k]
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant("dbs_switch", cat="decision", args=dict(ev))
        if self.logger is not None:
            self.logger.info(
                f"online-dbs: switched plan -> {ev['batches']} "
                f"(win {ev['predicted_win_s']}s over {ev['remaining_steps']} "
                f"steps, cost {ev['switch_cost_s']}s)"
            )
        if self.on_switch is not None:
            self.on_switch(ev)
        return ev

    def note_deferred(self) -> None:
        """A verdict-positive switch the engine vetoed because the candidate
        executables were still compiling (warm gating): the hysteresis
        re-evaluates at the next cadence boundary, by which time the
        speculative submit issued alongside the verdict has usually landed."""
        self.deferred += 1
        if self.journal:
            self.journal[-1]["outcome"] = "deferred"
        tracer = get_tracer()
        if tracer.enabled:
            tracer.instant(
                "dbs_deferred", cat="decision", args={"deferred": self.deferred}
            )

    def snapshot(self, include_journal: bool = False) -> Dict:
        """JSON-safe controller observability (recorder meta / registry).
        ``include_journal=True`` additionally embeds the construction config
        and the full decision journal — the shape `balance/replaylab.py`
        loads as a replay corpus (the bench's ``online_dbs_ab`` arm and
        `scripts/harvest_replay_corpus.py` harvest through this)."""
        out = {
            "evals": self.evals,
            "switches": self.switches,
            "deferred": self.deferred,
            "spent_s": round(self.spent_s, 6),
            "credit_s": round(self.credit_s, 6),
            "switch_cost_ema_s": (
                round(self.switch_cost_s, 6)
                if self.switch_cost_s is not None
                else None
            ),
            "wall_scale": round(self.wall_scale, 4),
            "comm_step_s": round(self.comm_step_s, 6),
            "decisions": len(self.journal),
            "journal_dropped": self.journal_dropped,
            "last_decision": dict(self.journal[-1]) if self.journal else None,
        }
        if include_journal:
            out["config"] = self.journal_config()
            out["journal"] = self.decision_journal()
        return out
