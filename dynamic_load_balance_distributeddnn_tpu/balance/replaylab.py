"""Controller lab (ISSUE 19): counterfactual replay, scenario synthesis,
and knob sweeps over the REAL :class:`OnlineRebalanceController` — no
devices, no jax, pure host-side numpy.

PR 15's decision journal records every controller verdict WITH the inputs
it was decided on; the crash-durable spool carries it through any incident.
That is a complete dataset for counterfactual replay, and this module is
its consumer. Three modes (CLI: ``graftscope replay`` / ``graftscope
sweep``):

* **replay** (:func:`load_corpus` + :func:`replay`) — load a decision
  journal from a corpus JSON, a registry/controller snapshot, a trace
  file, or a spool directory; rebuild a FRESH controller through the
  recorded ``journal_config()`` (optionally overriding ``hysteresis`` /
  ``margin`` / ``budget_frac`` / ``rate_alpha`` / ``cost_init``); drive it
  with the reconstructed input stream; report counterfactual modeled wall,
  switch count, and ledger trajectory vs the recorded outcome. With no
  knob overrides the replay is a STRICT parity check: every recorded
  verdict must reproduce bit-for-bit from its recorded inputs (the tier-1
  corpus regression gate, tests/test_replaylab.py).

* **synthesize** (:class:`Scenario` + :func:`simulate`) — the scenario
  library feeds per-worker rate traces (every
  :class:`ScheduledStragglerInjector` schedule: sin/ramp/spike/diurnal/
  brownout/killstorm) through the controller under the existing
  :func:`step_time` cost model, closed-loop: noisy rate observations fold
  through the controller's own EMA, realized walls feed ``observe_wall``,
  switches pay the scenario's switch cost into the true wall.

* **sweep** (:func:`knob_grid` / :func:`random_knobs` + :func:`sweep`) —
  grid or seeded-random knob sweeps across a scenario library, ranked by
  geometric-mean speedup over the never-switch hold baseline, with the
  best-found knob set reported against the defaults.

Every replayed or simulated journal passes through
:func:`check_invariants`: cumulative switch spend admissible under the
regret budget at every switch verdict, hold-when-no-modeled-gain, ledger
monotonicity and recurrence consistency. A violation means either a
corrupted corpus or a controller change that broke the contract — both are
exactly what the gate exists to catch.

Wall-clock note: "modeled wall" here is the controller's OWN cost model
(:func:`step_time` × recorded ``wall_scale``) integrated over the recorded
horizon — the honest basis for comparing knob sets against each other, not
a promise about any specific fleet's real seconds.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
import os
import random
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dynamic_load_balance_distributeddnn_tpu.balance.controller import (
    OnlineRebalanceController,
    step_time,
)
from dynamic_load_balance_distributeddnn_tpu.balance.solver import (
    quantize_batches,
    rebalance,
)
from dynamic_load_balance_distributeddnn_tpu.faults import (
    ScheduledStragglerInjector,
)

# decision-gate comparison slack: journal quantities are recorded at 1e-6
# resolution and the hysteresis gate multiplies a rounded step wall by the
# remaining-step horizon, so honest recordings can miss exact equality by
# ~1e-3 in the worst case — violations the checker exists for are orders of
# magnitude larger
GATE_EPS = 1e-3
# ledger recurrence slack: two rounded 1e-6 quantities per hop
LEDGER_EPS = 5e-6

KNOBS = ("hysteresis", "margin", "budget_frac", "rate_alpha", "cost_init")


# --------------------------------------------------------------- corpus IO


def _entries_from_decision_instants(events: List[dict]) -> "Tuple[Optional[Dict], List[Dict]]":
    """Reconstruct (config, journal) from ``cat=="decision"`` trace
    instants. The live journal annotates outcomes in place; the trace
    stream instead interleaves ``dbs_switch``/``dbs_deferred`` instants
    after the ``dbs_decision`` they resolve, so outcomes are re-paired
    here. ``dbs_config`` (emitted once per controller) carries the
    construction surface."""
    config: Optional[Dict] = None
    journal: List[Dict] = []
    for ev in events:
        name, args = ev.get("name"), dict(ev.get("args") or {})
        if name == "dbs_config":
            config = args
        elif name == "dbs_decision":
            args.pop("journal_dropped", None)
            journal.append(args)
        elif name == "dbs_switch" and journal:
            journal[-1]["outcome"] = "committed"
            if "switch_cost_s" in args:
                journal[-1]["measured_cost_s"] = args["switch_cost_s"]
            for k in ("epoch", "window", "step"):
                if k in args:
                    journal[-1][k] = args[k]
        elif name == "dbs_deferred" and journal:
            journal[-1]["outcome"] = "deferred"
    return config, journal


def _corpus_from_snapshot(obj: Dict) -> Optional[Dict]:
    """A controller ``snapshot(include_journal=True)`` — possibly nested
    inside a registry snapshot's ``controller`` section or a corpus file's
    top level — normalised to {"config", "journal", ...}."""
    for candidate in (obj, obj.get("controller"), obj.get("rebalance_controller")):
        if (
            isinstance(candidate, dict)
            and isinstance(candidate.get("journal"), list)
            and isinstance(candidate.get("config"), dict)
        ):
            return {
                "config": candidate["config"],
                "journal": candidate["journal"],
                "journal_dropped": int(candidate.get("journal_dropped", 0)),
                "label": obj.get("label"),
            }
    return None


def load_corpus(path: str) -> Dict:
    """Load a replay corpus: ``{"config": journal_config, "journal":
    [entries...], "journal_dropped", "label", "source"}``.

    Accepts a corpus/snapshot JSON (`scripts/harvest_replay_corpus.py`,
    ``controller.snapshot(include_journal=True)``, or a registry snapshot
    containing one), a graftscope trace file, a ``.spool`` file, or a
    directory of spools/traces. Raises ``ValueError`` when no decision
    journal can be reconstructed — an empty corpus is an error, not a
    clean replay."""
    if os.path.isdir(path) or path.endswith(".spool"):
        config, journal = _entries_from_decision_instants(
            _decision_instants(path)
        )
    else:
        with open(path) as fh:
            try:
                obj = json.load(fh)
            except json.JSONDecodeError as exc:
                raise ValueError(f"{path}: not JSON ({exc})") from exc
        if isinstance(obj, dict) and (got := _corpus_from_snapshot(obj)):
            got["source"] = path
            got["label"] = got.get("label") or os.path.basename(path)
            if not got["journal"]:
                raise ValueError(f"{path}: corpus journal is empty")
            return got
        if isinstance(obj, dict) and "traceEvents" in obj:
            config, journal = _entries_from_decision_instants(
                [
                    e
                    for e in obj["traceEvents"]
                    if e.get("ph") == "i" and e.get("cat") == "decision"
                ]
            )
        else:
            raise ValueError(
                f"{path}: neither a replay corpus (config+journal), a "
                "controller/registry snapshot, nor a graftscope trace"
            )
    if not journal:
        raise ValueError(f"{path}: no decision journal entries found")
    if config is None:
        raise ValueError(
            f"{path}: decision entries found but no dbs_config instant / "
            "config section — cannot rebuild the controller (re-record "
            "with a current build, or wrap the journal in a corpus JSON)"
        )
    return {
        "config": config,
        "journal": journal,
        "journal_dropped": 0,
        "label": os.path.basename(path.rstrip("/")),
        "source": path,
    }


def _decision_instants(path: str) -> List[dict]:
    # scope_cli owns the spool/trace merge machinery; imported lazily so
    # replaylab stays importable without the CLI module loaded (and the
    # CLI's replay/sweep handlers import replaylab lazily in turn)
    from dynamic_load_balance_distributeddnn_tpu.obs.scope_cli import (
        _decision_events,
    )

    return _decision_events(path)


def harvest(ctl: OnlineRebalanceController, label: str = "") -> Dict:
    """One live controller -> one corpus record (the shape
    :func:`load_corpus` reads and tests/corpus_replay/ checks in)."""
    snap = ctl.snapshot(include_journal=True)
    return {
        "label": label,
        "config": snap["config"],
        "journal": snap["journal"],
        "journal_dropped": snap["journal_dropped"],
        "snapshot": {
            k: v for k, v in snap.items() if k not in ("config", "journal")
        },
    }


# -------------------------------------------------------------- invariants


def check_invariants(config: Dict, journal: Sequence[Dict]) -> List[Dict]:
    """Check a decision journal against the controller's contract. Returns
    violation records (empty == clean):

    * ``switch-gate-hysteresis`` — a switch verdict whose predicted win is
      below the relative hysteresis threshold;
    * ``switch-gate-margin`` — a switch verdict whose win does not cover
      ``margin ×`` the cost estimate;
    * ``switch-gate-budget`` — cumulative spend + this cost exceeds
      ``budget_frac × (banked credit + this win)`` at a switch verdict;
    * ``no-modeled-gain`` — a switch verdict with non-positive win;
    * ``hold-reason`` — a hold whose recorded reason contradicts its own
      recorded inputs;
    * ``ledger-monotone`` / ``ledger-recurrence`` — spend/credit ledgers
      must be non-decreasing and evolve exactly by the recorded committed
      costs and banked wins.

    Gates use each ENTRY's recorded knobs (not ``config``'s), so a journal
    spanning a knob change is still checked against what the controller
    believed at each decision."""
    out: List[Dict] = []

    def flag(i: int, inv: str, detail: str) -> None:
        out.append({"index": i, "eval": journal[i].get("eval"),
                    "invariant": inv, "detail": detail})

    prev = None
    for i, e in enumerate(journal):
        if "predicted_win_s" not in e:  # foreign journal shape: skip entry
            continue
        win = float(e.get("predicted_win_s", 0.0))
        cur = float(e.get("cur_step_s", 0.0))
        cost = float(e.get("cost_est_s", 0.0))
        rem = int(e.get("remaining_steps", 0))
        h = float(e.get("hysteresis", config.get("hysteresis", 0.0)))
        m = float(e.get("margin", config.get("margin", 0.0)))
        bf = float(e.get("budget_frac", config.get("budget_frac", 1.0)))
        spent = float(e.get("spent_s", 0.0))
        credit = float(e.get("credit_s", 0.0))
        reason = e.get("reason", "")
        if e.get("switch"):
            if win <= 0.0:
                flag(i, "no-modeled-gain", f"switch with win {win} <= 0")
            if win + GATE_EPS < h * cur * rem:
                flag(i, "switch-gate-hysteresis",
                     f"win {win} < {h} * {cur} * {rem}")
            if win + GATE_EPS < m * cost:
                flag(i, "switch-gate-margin", f"win {win} < {m} * {cost}")
            if spent + cost > bf * (credit + win) + GATE_EPS:
                flag(i, "switch-gate-budget",
                     f"spent {spent} + cost {cost} > "
                     f"{bf} * (credit {credit} + win {win})")
        elif reason == "below-hysteresis" and win - GATE_EPS > h * cur * rem:
            flag(i, "hold-reason", f"win {win} >= {h} * {cur} * {rem}")
        elif reason == "below-margin" and win - GATE_EPS > m * cost:
            flag(i, "hold-reason", f"win {win} >= {m} * {cost}")
        elif (
            reason == "budget-exhausted"
            and spent + cost + GATE_EPS < bf * (credit + win)
        ):
            flag(i, "hold-reason",
                 f"budget had room: spent {spent} + cost {cost} < "
                 f"{bf} * (credit {credit} + win {win})")
        if prev is not None:
            p = journal[prev]
            p_spent = float(p.get("spent_s", 0.0))
            p_credit = float(p.get("credit_s", 0.0))
            if spent + LEDGER_EPS < p_spent or credit + LEDGER_EPS < p_credit:
                flag(i, "ledger-monotone",
                     f"spent {p_spent}->{spent} credit {p_credit}->{credit}")
            committed = p.get("outcome") == "committed"
            exp_spent = p_spent + (
                float(p.get("measured_cost_s", 0.0)) if committed else 0.0
            )
            exp_credit = p_credit + (
                max(float(p.get("predicted_win_s", 0.0)), 0.0)
                if committed
                else 0.0
            )
            if abs(spent - exp_spent) > LEDGER_EPS:
                flag(i, "ledger-recurrence",
                     f"spent {spent} != expected {exp_spent}")
            if abs(credit - exp_credit) > LEDGER_EPS:
                flag(i, "ledger-recurrence",
                     f"credit {credit} != expected {exp_credit}")
        prev = i
    return out


# ------------------------------------------------------------------ replay


def _knobs_of(config: Dict, overrides: Optional[Dict]) -> Dict:
    eff = {k: config.get(k) for k in KNOBS}
    for k, v in (overrides or {}).items():
        if k not in KNOBS:
            raise ValueError(f"unknown controller knob: {k!r}")
        if v is not None:
            eff[k] = float(v)
    return eff


def _elapsed_steps(journal: Sequence[Dict], i: int) -> int:
    """Steps the fleet ran between decision ``i`` and the next decision:
    the drop in the remaining-horizon counter, or — when the horizon GREW
    (an epoch boundary re-armed it) or this is the final entry — the rest
    of entry ``i``'s own horizon."""
    rem = int(journal[i].get("remaining_steps", 0))
    if i + 1 < len(journal):
        nxt = int(journal[i + 1].get("remaining_steps", 0))
        if 0 < nxt <= rem:
            return rem - nxt
    return max(rem, 0)


def replay(corpus: Dict, knobs: Optional[Dict] = None) -> Dict:
    """Re-run a recorded decision journal through a fresh controller.

    With no ``knobs`` this is STRICT parity: each entry's recorded inputs
    (eff rates, current batches, horizon, ledger/EMA state) are restored
    before the corresponding ``propose``, and the fresh controller's
    verdict must match the recording bit-for-bit — the corpus regression
    gate. With knob overrides it is a COUNTERFACTUAL: the controller keeps
    its own ledgers, batch trajectory, and switch-cost EMA (measured wall
    feedback and the rate stream stay the recorded, exogenous inputs), and
    the report compares modeled wall / switches / spend against the
    recording and the never-switch hold baseline.

    The replayed journal is always re-checked with
    :func:`check_invariants` — a counterfactual that breaks the budget
    contract is a bug, not a tuning datapoint."""
    config, journal = corpus["config"], corpus["journal"]
    strict = not knobs
    eff_knobs = _knobs_of(config, knobs)
    ctl = OnlineRebalanceController.from_journal_config(
        config, **{k: eff_knobs[k] for k in KNOBS}
    )
    ws = int(config["world_size"])
    groups = [list(g) for g in config["groups"]]
    filler_b = np.ones(ws, dtype=np.int64)

    mismatches: List[Dict] = []
    wall_rec = wall_rep = wall_hold = 0.0
    spend_rec = spend_rep = 0.0
    ledger: List[Dict] = []
    cur_cf: Optional[np.ndarray] = None  # counterfactual batch trajectory
    hold_b: Optional[np.ndarray] = None  # never-switch baseline trajectory
    prev_rem = None
    measured = [
        float(e["measured_cost_s"])
        for e in journal
        if e.get("outcome") == "committed" and "measured_cost_s" in e
    ]
    cf_cost = (
        float(np.mean(measured)) if measured else float(eff_knobs["cost_init"])
    )

    for i, e in enumerate(journal):
        reason = e.get("reason", "")
        rem = int(e.get("remaining_steps", 0))
        eff = e.get("eff_rates")
        cur_b = e.get("cur_batches")
        # exogenous measured-feedback state is replayed in BOTH modes: the
        # wall ratio and comm model are properties of the fleet, not of
        # the knob set under test
        ctl.wall_scale = float(e.get("wall_scale", ctl.wall_scale))
        if "comm_step_s" in e:
            ctl.comm_step_s = float(e["comm_step_s"])
        if strict:
            # parity mode makes each verdict a pure function of its
            # recorded inputs: restore the decision-time ledger/EMA state
            ctl.spent_s = float(e.get("spent_s", 0.0))
            ctl.credit_s = float(e.get("credit_s", 0.0))
            ema = e.get("switch_cost_ema_s")
            ctl.switch_cost_s = None if ema is None else float(ema)
        if reason == "no-horizon":
            dec = ctl.propose(np.ones(ws), filler_b, 0)
        elif reason == "no-signal":
            dec = ctl.propose(np.full(ws, -1.0), filler_b, max(rem, 1))
        elif eff is None or cur_b is None:
            mismatches.append(
                {"index": i, "field": "inputs",
                 "detail": f"entry lacks eff_rates/cur_batches ({reason})"}
            )
            continue
        else:
            rec_b = np.asarray(cur_b, dtype=np.int64)
            if hold_b is None or prev_rem is None or rem > prev_rem:
                # epoch boundary (or first sight): the engine re-plans at
                # boundaries outside this controller — both the hold
                # baseline and the counterfactual trajectory re-anchor on
                # the recorded plan
                hold_b = rec_b.copy()
                cur_cf = rec_b.copy()
            prev_rem = rem
            drive_b = rec_b if strict else cur_cf
            dec = ctl.propose(np.asarray(eff, dtype=np.float64), drive_b, rem)

        # verdict parity (strict mode is the gate; counterfactuals expect
        # drift — that is the point)
        if strict:
            if bool(dec.switch) != bool(e.get("switch")) or dec.reason != reason:
                mismatches.append(
                    {"index": i, "field": "verdict",
                     "detail": f"recorded ({e.get('switch')}, {reason!r}) "
                     f"replayed ({dec.switch}, {dec.reason!r})"}
                )
            elif "candidate_batches" in e and dec.candidate_batches is not None:
                if [int(b) for b in dec.candidate_batches] != [
                    int(b) for b in e["candidate_batches"]
                ]:
                    mismatches.append(
                        {"index": i, "field": "candidate_batches",
                         "detail": f"recorded {e['candidate_batches']} "
                         f"replayed {[int(b) for b in dec.candidate_batches]}"}
                    )

        # outcome bookkeeping + modeled-wall integration
        rec_committed = e.get("outcome") == "committed"
        rec_cost = float(e.get("measured_cost_s", cf_cost))
        if strict:
            if rec_committed and dec.switch:
                ctl.commit(dec, rec_cost)
            elif e.get("outcome") == "deferred" and dec.switch:
                ctl.note_deferred()
        elif dec.switch:
            # counterfactual: no warm-gate model — a verdict executes, at
            # the recorded measured cost when the recording has one for
            # this evaluation, else the corpus-mean measured cost
            ctl.commit(dec, rec_cost if rec_committed else cf_cost)
            cur_cf = np.asarray(dec.candidate_batches, dtype=np.int64)
        if eff is not None and cur_b is not None:
            steps = _elapsed_steps(journal, i)
            rates = np.asarray(eff, dtype=np.float64)
            scale = float(e.get("wall_scale", 1.0))
            comm = float(e.get("comm_step_s", 0.0))
            rec_b = np.asarray(cur_b, dtype=np.int64)
            rec_plan = (
                np.asarray(e["candidate_batches"], dtype=np.int64)
                if rec_committed and "candidate_batches" in e
                else rec_b
            )
            rep_plan = (
                rec_plan
                if strict
                else (cur_cf if cur_cf is not None else rec_b)
            )
            wall_rec += step_time(rates, rec_plan, groups, comm) * scale * steps
            wall_rep += step_time(rates, rep_plan, groups, comm) * scale * steps
            wall_hold += (
                step_time(rates, hold_b, groups, comm) * scale * steps
            )
            if rec_committed:
                wall_rec += rec_cost
                spend_rec += rec_cost
        if not strict and dec.switch:
            paid = rec_cost if rec_committed else cf_cost
            wall_rep += paid
            spend_rep += paid
        ledger.append(
            {"eval": e.get("eval", i), "spent_s": round(ctl.spent_s, 6),
             "credit_s": round(ctl.credit_s, 6)}
        )

    if strict:
        wall_rep, spend_rep = wall_rec, spend_rec
    replayed_journal = ctl.decision_journal()
    violations = check_invariants(ctl.journal_config(), replayed_journal)
    rec_switches = sum(1 for e in journal if e.get("outcome") == "committed")
    rec_deferred = sum(1 for e in journal if e.get("outcome") == "deferred")
    return {
        "label": corpus.get("label"),
        "mode": "strict" if strict else "counterfactual",
        "entries": len(journal),
        "knobs": eff_knobs,
        "parity": not mismatches if strict else None,
        "mismatches": mismatches,
        "invariant_violations": violations,
        "recorded": {
            "switches": rec_switches,
            "deferred": rec_deferred,
            "modeled_wall_s": round(wall_rec, 6),
            "switch_spend_s": round(spend_rec, 6),
        },
        "replayed": {
            "switches": ctl.switches,
            "deferred": ctl.deferred,
            "modeled_wall_s": round(wall_rep, 6),
            "switch_spend_s": round(spend_rep, 6),
            "spent_s": round(ctl.spent_s, 6),
            "credit_s": round(ctl.credit_s, 6),
        },
        "hold_modeled_wall_s": round(wall_hold, 6),
        "ledger": ledger,
    }


# -------------------------------------------------------------- synthesize


def _even_batches(global_batch: int, ws: int) -> np.ndarray:
    base, rem = divmod(int(global_batch), ws)
    return np.array(
        [base + (1 if i < rem else 0) for i in range(ws)], dtype=np.int64
    )


@dataclasses.dataclass
class Scenario:
    """One synthesized fleet: per-worker base rates modulated by an
    injection schedule, stepped at window cadence through the controller.
    Times are in the same abstract seconds the controller reasons in."""

    name: str
    world_size: int = 4
    base_rates: Tuple[float, ...] = ()   # s/example; default mildly skewed
    factors: Tuple[float, ...] = ()      # straggler factors; default (6,1..)
    schedule: str = "sin"
    period: float = 2.0
    phase: float = 0.0
    duty: float = 0.25
    seed: int = 0
    epochs: int = 4
    windows_per_epoch: int = 8
    steps_per_window: int = 4
    global_batch: int = 256
    bucket: int = 8
    switch_cost_s: float = 0.05
    comm_step_s: float = 0.0
    noise: float = 0.05                  # relative rate-measurement noise

    def resolved_rates(self) -> np.ndarray:
        if self.base_rates:
            return np.asarray(self.base_rates, dtype=np.float64)
        # mild deterministic skew so "even" is never accidentally optimal
        return 0.002 * (1.0 + 0.05 * np.arange(self.world_size))

    def resolved_factors(self) -> np.ndarray:
        if self.factors:
            return np.asarray(self.factors, dtype=np.float64)
        f = np.ones(self.world_size)
        f[0] = 6.0
        return f


def builtin_scenarios(world_size: int = 4) -> List[Scenario]:
    """The stock scenario library the sweep (and the bench's
    ``controller_sweep`` field) runs against: one per schedule family."""
    return [
        Scenario("sin-surge", world_size, schedule="sin", period=2.0),
        Scenario("ramp-degrade", world_size, schedule="ramp", period=1.5),
        Scenario("spike-burst", world_size, schedule="spike",
                 period=1.0, duty=0.2),
        Scenario("diurnal-load", world_size, schedule="diurnal", period=2.0),
        Scenario("rack-brownout", world_size, schedule="brownout",
                 period=1.0, seed=5,
                 factors=tuple([4.0] * world_size)),
        Scenario("kill-storm", world_size, schedule="killstorm",
                 period=1.0, seed=9,
                 factors=tuple([8.0] * world_size)),
    ]


def simulate(
    scenario: Scenario,
    knobs: Optional[Dict] = None,
    include_journal: bool = False,
) -> Dict:
    """Run one scenario through a fresh controller, closed loop: noisy
    per-window rate measurements fold through the controller's own EMA
    (``rate_alpha`` matters), realized walls feed ``observe_wall``, and a
    committed switch pays ``switch_cost_s`` into the TRUE wall. Reports
    the controller's realized modeled wall against the never-switch hold
    baseline and the zero-cost per-window oracle, plus the invariant check
    over the produced journal."""
    ws = scenario.world_size
    base = scenario.resolved_rates()
    groups = [[i] for i in range(ws)]
    kw = {"bucket": scenario.bucket, "cost_init": scenario.switch_cost_s}
    for k, v in (knobs or {}).items():
        if k not in KNOBS:
            raise ValueError(f"unknown controller knob: {k!r}")
        if v is not None:
            kw[k] = float(v)
    ctl = OnlineRebalanceController(ws, scenario.global_batch, groups, **kw)
    ctl.comm_step_s = scenario.comm_step_s
    inj = ScheduledStragglerInjector(
        scenario.resolved_factors(),
        schedule=scenario.schedule,
        period=scenario.period,
        phase=scenario.phase,
        duty=scenario.duty,
        seed=scenario.seed,
    )
    rng = random.Random(scenario.seed * 7907 + 3)
    cur = _even_batches(scenario.global_batch, ws)
    hold = cur.copy()
    wall = hold_wall = oracle_wall = 0.0
    spw = scenario.steps_per_window
    for e in range(scenario.epochs):
        for w in range(scenario.windows_per_epoch):
            t_mid = e + (w + 0.5) / scenario.windows_per_epoch
            eff_true = base * inj.factors_at(t_mid)
            measured = eff_true * np.array(
                [1.0 + scenario.noise * (2.0 * rng.random() - 1.0)
                 for _ in range(ws)]
            )
            ctl.observe_rates(measured)
            signal = ctl.rates if ctl.rates is not None else measured
            remaining = (scenario.windows_per_epoch - w) * spw
            ctl.eval_context = {"epoch": e, "window": w}
            dec = ctl.propose(signal, cur, remaining)
            if dec.switch:
                ctl.commit(dec, scenario.switch_cost_s, epoch=e, window=w)
                cur = np.asarray(dec.candidate_batches, dtype=np.int64)
                wall += scenario.switch_cost_s
            true_step = step_time(
                eff_true, cur, groups, comm_s=scenario.comm_step_s
            )
            wall += true_step * spw
            modeled = (
                step_time(signal, cur, groups, comm_s=scenario.comm_step_s)
                * ctl.wall_scale
            )
            ctl.observe_wall(true_step * spw, modeled * spw)
            hold_wall += (
                step_time(eff_true, hold, groups, comm_s=scenario.comm_step_s)
                * spw
            )
            o_shares, o_b = rebalance(
                eff_true * np.maximum(hold, 1),
                hold.astype(np.float64) / max(hold.sum(), 1),
                scenario.global_batch,
            )
            if scenario.bucket > 0:
                o_b = quantize_batches(
                    o_b, scenario.bucket, scenario.global_batch
                )
            oracle_wall += (
                step_time(eff_true, o_b, groups, comm_s=scenario.comm_step_s)
                * spw
            )
    journal = ctl.decision_journal()
    violations = check_invariants(ctl.journal_config(), journal)
    out = {
        "scenario": scenario.name,
        "knobs": {k: getattr(ctl, k) for k in KNOBS},
        "evals": ctl.evals,
        "switches": ctl.switches,
        "spent_s": round(ctl.spent_s, 6),
        "credit_s": round(ctl.credit_s, 6),
        "wall_s": round(wall, 6),
        "hold_wall_s": round(hold_wall, 6),
        "oracle_wall_s": round(oracle_wall, 6),
        "speedup_vs_hold": round(hold_wall / wall, 6) if wall > 0 else None,
        "oracle_frac": (
            round((hold_wall - wall) / (hold_wall - oracle_wall), 6)
            if hold_wall > oracle_wall
            else None
        ),
        "invariant_violations": violations,
    }
    if include_journal:
        out["config"] = ctl.journal_config()
        out["journal"] = journal
    return out


# ------------------------------------------------------------------- sweep


def knob_grid(size: str = "small") -> List[Dict]:
    """Deterministic grid over the decision knobs. ``small`` (18 points)
    fits the tier-1/bench budget; ``full`` is the offline-tuning grid."""
    if size == "small":
        hs, ms, bfs = (0.05, 0.1, 0.2), (1.5, 3.0, 6.0), (0.5, 1.0)
    elif size == "full":
        hs = (0.02, 0.05, 0.1, 0.2, 0.4)
        ms = (1.0, 1.5, 3.0, 6.0, 12.0)
        bfs = (0.25, 0.5, 1.0, 2.0)
    else:
        raise ValueError("size must be 'small' or 'full'")
    return [
        {"hysteresis": h, "margin": m, "budget_frac": bf}
        for h, m, bf in itertools.product(hs, ms, bfs)
    ]


def random_knobs(n: int, seed: int = 0) -> List[Dict]:
    """``n`` seeded log-uniform knob draws (the fuzz arm of the sweep)."""
    rng = random.Random(seed * 104729 + 1)

    def logu(lo: float, hi: float) -> float:
        return float(
            math.exp(rng.uniform(math.log(lo), math.log(hi)))
        )

    return [
        {
            "hysteresis": round(logu(0.02, 0.4), 4),
            "margin": round(logu(1.0, 8.0), 4),
            "budget_frac": round(logu(0.25, 2.0), 4),
            "rate_alpha": round(logu(0.2, 0.9), 4),
        }
        for _ in range(n)
    ]


def _geomean(xs: Sequence[float]) -> float:
    return float(math.exp(sum(math.log(max(x, 1e-12)) for x in xs) / len(xs)))


def sweep(
    scenarios: Sequence[Scenario],
    knob_sets: Sequence[Dict],
    include_default: bool = True,
) -> Dict:
    """Run every knob set over every scenario; rank by geometric-mean
    speedup over the hold baseline. The report carries the full ranked
    table, the winner, the defaults' row, and winner-vs-default — the
    artifact the ``controller_sweep`` bench field records."""
    candidates: List[Optional[Dict]] = (
        [None] if include_default else []
    ) + [dict(k) for k in knob_sets]
    results = []
    total_violations = 0
    for knobs in candidates:
        runs = [simulate(sc, knobs=knobs) for sc in scenarios]
        total_violations += sum(
            len(r["invariant_violations"]) for r in runs
        )
        results.append(
            {
                "knobs": knobs if knobs is not None else "default",
                "score": round(
                    _geomean([r["speedup_vs_hold"] or 1.0 for r in runs]), 6
                ),
                "switches": sum(r["switches"] for r in runs),
                "spent_s": round(sum(r["spent_s"] for r in runs), 6),
                "per_scenario": {
                    r["scenario"]: r["speedup_vs_hold"] for r in runs
                },
                "invariant_violations": sum(
                    len(r["invariant_violations"]) for r in runs
                ),
            }
        )
    ranked = sorted(results, key=lambda r: -r["score"])
    default_row = next(
        (r for r in results if r["knobs"] == "default"), None
    )
    best = ranked[0] if ranked else None
    return {
        "scenarios": [sc.name for sc in scenarios],
        "candidates": len(candidates),
        "results": ranked,
        "best": best,
        "default": default_row,
        "best_vs_default": (
            round(best["score"] / default_row["score"], 6)
            if best and default_row and default_row["score"] > 0
            else None
        ),
        "invariant_violations": total_violations,
    }
