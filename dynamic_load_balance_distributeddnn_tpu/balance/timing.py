"""Per-worker time measurement and exchange.

The reference measures each worker's epoch compute time with wall-clock
deltas, *excluding* accumulated communication wait (dbs.py:226-250), then ring
all-gathers the scalar times so every worker can run the solver on an
identical vector (dbs.py:479-499). That compute/comm split is load-bearing:
the balancer must react to compute speed, not network jitter (SURVEY §2.4).

Here the controller process dispatches every logical worker's step and blocks
on each worker's outputs in completion order, so per-worker durations fall out
of completion timestamps; combine/update (the communication) is timed
separately. Across hosts, the ring all-gather becomes a host-level
``process_allgather`` (per-epoch metadata — no reason to burn an ICI
collective on 8 scalars).

Superstep epochs (ISSUE 2): the elastic hot loop dispatches whole windows, so
there is no per-step host boundary left to time — per-worker walls still come
from the standalone probe steps (raw-wall differencing against the per-device
dispatch overhead, exactly as before), and the host's own cost of driving the
epoch is accumulated separately by :class:`HostOverheadMeter` (dispatch/enqueue
walls vs transfer walls), the quantity the superstep exists to shrink.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

import numpy as np


class TimeKeeper:
    """Accumulates per-worker compute and injected-straggler seconds for one
    epoch; the engine combines them (with any fault time multipliers) into the
    solver's node-time vector. Comm time is deliberately absent: the balancer
    reacts to compute speed only (reference contract, dbs.py:250/425).
    Not thread-safe; the engine drives it from the controller thread."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.reset()

    def reset(self) -> None:
        self.compute_s = np.zeros(self.world_size, dtype=np.float64)
        self.injected_s = np.zeros(self.world_size, dtype=np.float64)

    def add_compute(self, worker: int, seconds: float) -> None:
        self.compute_s[worker] += seconds

    def add_injected(self, worker: int, seconds: float) -> None:
        """Virtual straggler seconds (fault_mode='virtual'): counted into the
        time vector the solver sees, mirroring the reference's sleeps being
        measured into train_time (dbs.py:103, 241)."""
        self.injected_s[worker] += seconds


class HostOverheadMeter:
    """Per-epoch accounting of the HOST's cost of driving the device: seconds
    spent enqueueing work (``dispatch()`` — Python dispatch loops; async, so
    this is pure host overhead, not device compute) and seconds spent in
    host→device transfers (``add_put_s`` — called from the transfer
    pipeline's worker threads, hence the lock). These walls deliberately do
    NOT sync the device: they measure the controller, which is exactly what
    wall-clock-around-async-dispatch measures (the G002 failure mode, here
    the intended quantity). The elastic superstep path exists to shrink
    them; bench.py reports them per step as the dispatch-overhead A/B."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.dispatch_s = 0.0
            self.put_s = 0.0
            self.dispatches = 0
            self._mark_dispatch_s = 0.0
            self._mark_put_s = 0.0
            self._mark_dispatches = 0

    @contextmanager
    def dispatch(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.dispatch_s += dt
                self.dispatches += 1

    def add_put_s(self, seconds: float) -> None:
        with self._lock:
            self.put_s += float(seconds)

    def mark_window(self) -> "tuple[float, float, int]":
        """Per-window snapshot: (dispatch_s, put_s, dispatches) accumulated
        since the previous mark — the host-side component of the window
        controller's step-wall signal (ISSUE 11). The cumulative epoch
        totals above are untouched; marks only move the window baseline."""
        with self._lock:
            d = self.dispatch_s - getattr(self, "_mark_dispatch_s", 0.0)
            p = self.put_s - getattr(self, "_mark_put_s", 0.0)
            n = self.dispatches - getattr(self, "_mark_dispatches", 0)
            self._mark_dispatch_s = self.dispatch_s
            self._mark_put_s = self.put_s
            self._mark_dispatches = self.dispatches
            return d, p, n

    def per_step(self, num_steps: int) -> float:
        """Host overhead (dispatch + put walls) amortized per plan step."""
        with self._lock:
            return (self.dispatch_s + self.put_s) / max(int(num_steps), 1)


def exchange_times(local_times: np.ndarray) -> np.ndarray:
    """All-gather per-worker times across hosts (reference's time_allreduce
    ring, dbs.py:479-499). Single-host: identity. Multi-host: each host
    contributes its local workers' slice; result is rank-ordered like the
    reference's rotate+reverse step (dbs.py:495-498)."""
    import jax

    if jax.process_count() == 1:
        return np.asarray(local_times, dtype=np.float64)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray(local_times, dtype=np.float64)
    )
    return np.asarray(gathered).reshape(-1)


def ring_exchange_times(local_times: np.ndarray, mesh=None) -> np.ndarray:
    """Device-side ring all-gather of per-worker scalar times over the mesh's
    ICI — the literal structure of the reference's isend/recv ring
    (dbs.py:487-493: size-1 hops, each device forwarding what it received),
    built from ``lax.ppermute``. The host ``exchange_times`` is the default
    (8 scalars per epoch do not merit a device collective, SURVEY §5.8); this
    exists for topology faithfulness and as the pattern to scale metadata
    exchange on large meshes where host gathers would serialize on one
    coordinator.

    ``local_times``: [n_dev] — entry d is the time measured for the worker on
    mesh device d. Returns the full rank-ordered [n_dev] vector, identical on
    every device (and to the input, since every device contributes its slot).
    """
    import jax
    import jax.numpy as jnp

    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import data_mesh

    mesh = mesh or data_mesh()
    n = len(mesh.devices.flat)
    times = jnp.asarray(local_times, dtype=jnp.float32)
    return np.asarray(_build_ring_exchange(mesh, n)(times), dtype=np.float64)


_RING_EXCHANGE_CACHE: dict = {}


def _build_ring_exchange(mesh, n: int):
    """Compile the ring all-gather ONCE per (mesh, n): the pre-fix form built
    a fresh jit wrapper (a fresh closure identity, so a fresh XLA compile)
    inside ring_exchange_times on every call — graftlint G001."""
    cached = _RING_EXCHANGE_CACHE.get((mesh, n))
    if cached is not None:
        return cached

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dynamic_load_balance_distributeddnn_tpu.parallel.mesh import (
        DATA_AXIS,
        shard_map,
    )

    def ring(t_local):
        # t_local: [1] — this device's scalar. Accumulate into slot idx of a
        # local [n] buffer, then forward the received value around the ring
        # n-1 times (dbs.py:487-493's loop, one ppermute per hop).
        idx = jax.lax.axis_index(DATA_AXIS)
        out = jnp.zeros((n,), jnp.float32).at[idx].set(t_local[0])
        perm = [(i, (i + 1) % n) for i in range(n)]

        def hop(carry, _):
            buf, recv, src = carry
            recv = jax.lax.ppermute(recv, DATA_AXIS, perm)
            src = jax.lax.ppermute(src, DATA_AXIS, perm)
            buf = buf.at[src].set(recv)
            return (buf, recv, src), None

        (out, _, _), _ = jax.lax.scan(
            hop, (out, t_local[0], idx), None, length=n - 1
        )
        return out

    sharded = jax.jit(
        shard_map(
            ring,
            mesh=mesh,
            in_specs=P(DATA_AXIS),
            out_specs=P(None),
            check_vma=False,
        )
    )
    _RING_EXCHANGE_CACHE[(mesh, n)] = sharded
    return sharded
